"""Multi-slice topology-aware placement (ISSUE 19): DCN-adjacency slice
scoring in the inventory (bind / keep-greedy release / prefer-domain
re-expansion), the mesh-to-slice planner (planner/meshmap.py), the
materializer's mesh env contract at full and degraded widths, the
elastic engine's mesh-integrity unit rounding (whole inter-slice dp
replicas, never mid-pipeline), pp-granular scheduler harvesting, the
mesh-env vet rule, and the CLI placement surfaces.  The end-to-end
gates (adjacency vs random, mid-run kill degrading by exactly one dp
replica) live in bench.py --multislice (`make multislice-smoke`)."""

import json
import os
import time

import pytest

from kubeflow_controller_tpu.api.core import (
    PHASE_FAILED,
    Container,
    PodTemplateSpec,
)
from kubeflow_controller_tpu.api.labels import (
    ANNOTATION_MESH_PP,
    ANNOTATION_PLACEMENT,
    ANNOTATION_SLICE_INDEX,
)
from kubeflow_controller_tpu.api.meta import ObjectMeta
from kubeflow_controller_tpu.api.tfjob import (
    ElasticSpec,
    ReplicaType,
    TFJob,
    TFJobPhase,
    TFReplicaSpec,
    TPUSpec,
    ValidationError,
    mesh_pp_span,
    validate_tfjob,
    validate_tpu_spec,
)
from kubeflow_controller_tpu.cluster import TPUInventory, TPUSlice
from kubeflow_controller_tpu.cluster.tpu import adjacency_score, dcn_domain
from kubeflow_controller_tpu.elastic import (
    KIND_DEGRADE,
    KIND_EXPAND,
    ElasticEngine,
    ElasticPolicy,
)
from kubeflow_controller_tpu.planner.materialize import (
    ENV_MESH,
    ENV_NUM_SLICES,
    ENV_SLICE_COORDINATOR,
    ENV_SLICE_ID,
    make_pod,
)
from kubeflow_controller_tpu.planner.meshmap import (
    MeshSlicePlan,
    mesh_slice_unit,
    plan_mesh_slices,
)

from test_elastic import mk_member, mk_tpu_elastic_job, set_width

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def sb_slices(n=8, per_block=2, accel="v5e-8"):
    """n slices across n/per_block superblocks: s0,s1 in sb0; s2,s3 in
    sb1; ..."""
    return [TPUSlice(f"s{i}", accel, num_hosts=2,
                     pod_id=f"sb{i // per_block}", pod_pos=i % per_block)
            for i in range(n)]


def env_of(pod) -> dict:
    return {e.name: e.value for e in pod.spec.containers[0].env}


# ---------------------------------------------------------------------------
# Inventory: adjacency-scored bind / keep-greedy release
# ---------------------------------------------------------------------------

class TestAdjacencyInventory:
    def test_score_and_domain_defaults(self):
        assert adjacency_score(1, 1) == 1.0
        assert adjacency_score(4, 1) == 1.0
        assert adjacency_score(4, 4) == 0.0
        assert adjacency_score(4, 2) == pytest.approx(2 / 3)
        # No topology coordinates: the slice is its own domain.
        assert dcn_domain(TPUSlice("lonely")) == "lonely"
        assert dcn_domain(TPUSlice("s", pod_id="sbX")) == "sbX"

    def test_bind_prefers_fewest_domains_over_first_fit(self):
        # sb0 is fragmented (s0 bound); first-fit would take s1 (sb0) +
        # s2 (sb1) and span 2 domains — adjacency takes the intact sb1.
        slices = sb_slices(6)
        slices[0].bound_gang = "other"
        inv = TPUInventory(slices)
        bound = inv.bind_gang("g", "v5e-8", n_slices=2)
        assert bound == ["s2", "s3"]
        assert inv.placement_of("g") == {
            "slices": ["s2", "s3"], "domains": ["sb1"], "score": 1.0}

    def test_bind_spans_minimum_domains_when_no_block_is_whole(self):
        slices = sb_slices(8)
        for i in (0, 3, 5, 7):  # one free slice per superblock
            slices[i].bound_gang = "other"
        inv = TPUInventory(slices)
        inv.bind_gang("g", "v5e-8", n_slices=3)
        pl = inv.placement_of("g")
        assert len(pl["domains"]) == 3  # one per block: can't do better
        assert pl["score"] == 0.0

    def test_random_placement_is_seeded_and_valid(self):
        a = TPUInventory(sb_slices(8), placement="random", seed=5)
        b = TPUInventory(sb_slices(8), placement="random", seed=5)
        assert a.bind_gang("g", "v5e-8", n_slices=4) == \
            b.bind_gang("g", "v5e-8", n_slices=4)
        with pytest.raises(ValueError):
            TPUInventory([], placement="topological")

    def test_flat_inventory_binds_in_table_order(self):
        # No pod_id: every slice its own domain — bit-identical to the
        # old first-fit scan (ties keep insertion order).
        inv = TPUInventory([TPUSlice(f"s{i}", "v5e-8") for i in range(4)])
        assert inv.bind_gang("g", "v5e-8", n_slices=2) == ["s0", "s1"]

    def test_release_keeps_coordinator_domain_whole(self):
        # Bind takes sb0 whole plus one sb2 slice ([s0, s1, s4]); grow
        # biases back into the gang's own domains ([s5], not the free
        # s2 in untouched sb1).  Releasing 2 must then drop the sb2
        # block whole — never the coordinator's block, never position 0.
        slices = sb_slices(6)
        slices[3].bound_gang = "other"  # fragment sb1
        inv = TPUInventory(slices)
        assert inv.bind_gang("g", "v5e-8", n_slices=3) == ["s0", "s1", "s4"]
        assert inv.placement_of("g")["score"] == 0.5
        assert inv.grow_gang("g", "v5e-8", 1) == ["s5"]
        assert inv.release_slices("g", 2) == ["s4", "s5"]
        assert inv.gang_slices("g") == ["s0", "s1"]
        assert inv.placement_of("g") == {
            "slices": ["s0", "s1"], "domains": ["sb0"], "score": 1.0}

    def test_release_non_tail_when_coordinator_domain_rebound_late(self):
        # Keep-greedy is position-aware, not tail-biased: a gang that
        # re-expanded back INTO its coordinator's domain releases the
        # foreign MIDDLE slice, not the newest one.
        mk = lambda name, dom, pos: TPUSlice(
            name, "v5e-8", num_hosts=2, pod_id=dom, pod_pos=pos)
        slices = [mk("a0", "A", 0), mk("a1", "A", 1), mk("a2", "A", 2),
                  mk("b0", "B", 0)]
        slices[2].bound_gang = "other"  # only a0, a1 free in A initially
        inv = TPUInventory(slices)
        assert inv.bind_gang("g", "v5e-8", n_slices=3) == ["a0", "a1", "b0"]
        inv.add_slice(mk("a2", "A", 2))  # A's third slice frees up
        assert inv.grow_gang("g", "v5e-8", 1) == ["a2"]  # prefers A
        # slice_names is now [a0, a1, b0, a2]: b0 sits mid-list.
        assert inv.release_slices("g", 1) == ["b0"]
        assert inv.gang_slices("g") == ["a0", "a1", "a2"]
        assert inv.placement_of("g")["score"] == 1.0

    def test_flat_release_is_the_historical_tail_release(self):
        inv = TPUInventory([TPUSlice(f"s{i}", "v5e-8") for i in range(4)])
        inv.bind_gang("g", "v5e-8", n_slices=4)
        assert inv.release_slices("g", 2) == ["s2", "s3"]
        assert inv.gang_slices("g") == ["s0", "s1"]

    def test_regrow_prefers_the_gangs_existing_domains(self):
        slices = sb_slices(8)
        inv = TPUInventory(slices)
        inv.bind_gang("g", "v5e-8", n_slices=2)       # sb0 whole
        inv.bind_gang("other", "v5e-8", n_slices=2)   # sb1 whole
        inv.release_slices("g", 1)                    # s1 freed
        inv.release_gang("other")                     # sb1 free again
        # Without the prefer-domains bias the largest free group (sb1,
        # also sb2/sb3: all size 2 vs sb0's 1) would win the tie.
        assert inv.grow_gang("g", "v5e-8", 1) == ["s1"]


# ---------------------------------------------------------------------------
# planner/meshmap.py: mesh-to-slice factoring
# ---------------------------------------------------------------------------

def mk_tpu(mesh, num_slices=4, num_hosts=2):
    return TPUSpec(accelerator_type="v5e-8", num_hosts=num_hosts,
                   num_slices=num_slices, mesh=mesh)


class TestMeshSlicePlan:
    def test_full_width_pp_dp_factoring(self):
        p = plan_mesh_slices(mk_tpu({"pp": 2, "dp": 2, "fsdp": 4}))
        assert isinstance(p, MeshSlicePlan)
        assert p.axes == {"dp": 2, "fsdp": 4, "pp": 2}
        assert (p.pp_span, p.dp_inter, p.dp_intra) == (2, 2, 1)
        scope = p.axis_scope()
        assert scope["pp"] == "dcn" and scope["fsdp"] == "ici"

    def test_degraded_width_sheds_whole_dp_replicas(self):
        tpu = mk_tpu({"pp": 2, "dp": 2, "fsdp": 4})
        p = plan_mesh_slices(tpu, num_slices_now=2)
        assert p.axes == {"dp": 1, "fsdp": 4, "pp": 2}

    def test_non_divisible_width_rounds_down_to_whole_pipelines(self):
        tpu = mk_tpu({"pp": 2, "dp": 2, "fsdp": 4})
        # 3 slices cannot host 1.5 pipelines: plan as 2 (one dp replica).
        p = plan_mesh_slices(tpu, num_slices_now=3)
        assert p.num_slices == 2
        assert p.axes["dp"] == 1

    def test_dp_only_mesh_spreads_over_dcn_and_ici(self):
        p = plan_mesh_slices(mk_tpu({"dp": 8, "fsdp": 1}, num_slices=4))
        assert p.axes["dp"] == 8
        assert (p.dp_inter, p.dp_intra) == (4, 2)
        assert p.axis_scope()["dp"] == "dcn x ici"

    def test_empty_mesh_plans_empty(self):
        p = plan_mesh_slices(mk_tpu({}))
        assert p.axes == {}
        assert p.pp_span == 1

    def test_unit_is_hosts_times_pp_span(self):
        assert mesh_slice_unit(mk_tpu({"pp": 2, "dp": 2})) == 4
        assert mesh_slice_unit(mk_tpu({"dp": 4})) == 2
        assert mesh_slice_unit(None) == 1

    def test_validation_rejects_non_slice_granular_pipelines(self):
        with pytest.raises(ValidationError, match="slice-granular"):
            validate_tpu_spec(mk_tpu({"pp": 3}, num_slices=4))
        with pytest.raises(ValidationError, match="unknown mesh axis"):
            validate_tpu_spec(mk_tpu({"warp": 2}))
        with pytest.raises(ValidationError, match="integer >= 1"):
            validate_tpu_spec(mk_tpu({"dp": 0}))
        validate_tpu_spec(mk_tpu({"pp": 2, "dp": 2, "fsdp": 4}))

    def test_elastic_floor_must_be_whole_dp_replicas(self):
        job = mk_tpu_elastic_job("mj", num_slices=4, min_width=2)
        job.spec.tf_replica_specs[0].tpu.mesh = {"pp": 2, "dp": 2}
        with pytest.raises(ValidationError, match="pipeline"):
            validate_tfjob(job)
        job.spec.elastic = ElasticSpec(min_width=4)
        validate_tfjob(job)
        assert mesh_pp_span(job.spec.tf_replica_specs[0].tpu) == 2


# ---------------------------------------------------------------------------
# Materializer: the mesh env contract at full and degraded widths
# ---------------------------------------------------------------------------

class TestMaterializeMeshEnv:
    def _job(self, mesh={"pp": 2, "dp": 2, "fsdp": 4}):
        job = mk_tpu_elastic_job("mmat", num_slices=4, min_width=4)
        job.spec.tf_replica_specs[0].tpu.mesh = dict(mesh)
        return job

    def test_full_width_stamps_mesh_env_and_pp_annotation(self):
        job = self._job()
        pod = make_pod(job, job.spec.tf_replica_specs[0], 3)
        env = env_of(pod)
        assert json.loads(env[ENV_MESH]) == {"dp": 2, "fsdp": 4, "pp": 2}
        assert env[ENV_NUM_SLICES] == "4"
        assert env[ENV_SLICE_ID] == "1"          # index 3 // 2 hosts
        assert env[ENV_SLICE_COORDINATOR].startswith("host-2.")
        assert pod.metadata.annotations[ANNOTATION_MESH_PP] == "2"
        assert pod.metadata.annotations[ANNOTATION_SLICE_INDEX] == "1"

    def test_degraded_width_replans_the_mesh(self):
        job = self._job()
        set_width(job, 4, 1)
        pod = make_pod(job, job.spec.tf_replica_specs[0], 3)
        env = env_of(pod)
        assert json.loads(env[ENV_MESH]) == {"dp": 1, "fsdp": 4, "pp": 2}
        assert env[ENV_NUM_SLICES] == "2"

    def test_non_divisible_width_edge(self):
        # Width 3 on 2-host slices: ceil(3/2)=2 slices — the slice/local
        # math stays consistent and the plan rounds to whole pipelines.
        job = self._job()
        set_width(job, 3, 1)
        pod = make_pod(job, job.spec.tf_replica_specs[0], 2)
        env = env_of(pod)
        assert (env[ENV_SLICE_ID], env[ENV_NUM_SLICES]) == ("1", "2")
        assert json.loads(env[ENV_MESH])["dp"] == 1

    def test_width_change_mid_generation_rematerializes_consistently(self):
        # The pod is a pure function of (job, index): the same index
        # materialized before and after a width patch carries each
        # width's mesh — no stale-env replica can join the new world.
        job = self._job()
        before = env_of(make_pod(job, job.spec.tf_replica_specs[0], 1))
        set_width(job, 4, 1)
        after = env_of(make_pod(job, job.spec.tf_replica_specs[0], 1))
        assert json.loads(before[ENV_MESH])["dp"] == 2
        assert json.loads(after[ENV_MESH])["dp"] == 1
        assert (before[ENV_NUM_SLICES], after[ENV_NUM_SLICES]) == ("4", "2")

    def test_meshless_tpu_pod_has_no_mesh_env(self):
        job = mk_tpu_elastic_job("plain", num_slices=2, min_width=2)
        pod = make_pod(job, job.spec.tf_replica_specs[0], 0)
        env = env_of(pod)
        assert ENV_MESH not in env
        assert ANNOTATION_MESH_PP not in pod.metadata.annotations
        assert env[ENV_SLICE_COORDINATOR].startswith("host-0.")


# ---------------------------------------------------------------------------
# Elastic engine: shrink/expand by whole inter-slice dp replicas
# ---------------------------------------------------------------------------

def tpu_members(n, gen=0, failed=(), fit_step=None, job="tjob"):
    return {ReplicaType.TPU: [
        mk_member(f"m{i}", i, gen=gen, typ="TPU", job=job,
                  phase=PHASE_FAILED if i in failed else "Running",
                  reason="Error: exit -9" if i in failed else "",
                  fit_step=fit_step)
        for i in range(n)]}


class TestEngineMeshUnits:
    def _job(self):
        job = mk_tpu_elastic_job("tjob", num_slices=4, min_width=4)
        job.spec.tf_replica_specs[0].tpu.mesh = {"pp": 2, "dp": 2,
                                                 "fsdp": 4}
        return job

    def test_one_death_degrades_by_a_whole_dp_replica(self):
        eng = ElasticEngine(ElasticPolicy(warmup_s=1.0))
        a = eng.assess("default/tjob", self._job(),
                       tpu_members(8, failed=(5,)), None, now=100.0)
        assert a.transition is not None
        assert a.transition.kind == KIND_DEGRADE
        # 7 survivors would split a pipeline (3.5 slices): round to 4,
        # never 6 (6 = 3 slices = 1.5 pipelines).
        assert (a.transition.from_width, a.transition.to_width) == (8, 4)

    def test_degrade_below_a_whole_replica_defers_to_recovery(self):
        eng = ElasticEngine(ElasticPolicy(warmup_s=1.0))
        job = self._job()
        set_width(job, 4, 1)
        a = eng.assess("default/tjob", job,
                       tpu_members(4, gen=1, failed=(1,)), None, now=100.0)
        assert a.transition is None  # next unit (0) is under the floor

    def test_expand_counts_the_gangs_still_bound_slices(self):
        class Inv:
            def __init__(self, free, bound):
                self.free, self.bound = free, bound

            def free_slice_count(self, accel=""):
                return self.free

            def gang_slices(self, name):
                assert name == "tjob-rid"
                return [f"s{i}" for i in range(self.bound)]

        eng = ElasticEngine(ElasticPolicy(warmup_s=0.0, min_degraded_s=0.0,
                                          progress_grace_s=0.0))
        job = self._job()
        set_width(job, 4, 1)
        members = tpu_members(4, gen=1, fit_step=9)
        # Crash-degraded gang: zero free slices but all 4 still bound —
        # re-expansion must not wait for capacity it already holds.
        a = eng.assess("k", job, members, None, now=100.0,
                       inventory=Inv(free=0, bound=4))
        assert a.transition is not None and a.transition.kind == KIND_EXPAND
        assert a.transition.to_width == 8
        # Harvested gang: binding shrunk to 2, nothing free -> hold.
        b = eng.assess("k2", job, members, None, now=100.0,
                       inventory=Inv(free=0, bound=2))
        assert b.transition is None

    def test_partial_capacity_expands_by_whole_dp_replicas_only(self):
        class Inv:
            def free_slice_count(self, accel=""):
                return 1  # half a pipeline replica

            def gang_slices(self, name):
                return ["s0", "s1"]

        eng = ElasticEngine(ElasticPolicy(warmup_s=0.0, min_degraded_s=0.0,
                                          progress_grace_s=0.0))
        job = self._job()
        set_width(job, 4, 1)
        a = eng.assess("k", job, tpu_members(4, gen=1, fit_step=9), None,
                       now=100.0, inventory=Inv())
        assert a.transition is None  # 4+2=6 rounds down to 4: no expand


# ---------------------------------------------------------------------------
# Scheduler: pp-granular width harvesting
# ---------------------------------------------------------------------------

class TestPpGranularHarvest:
    def _rig(self, n_slices=4):
        from kubeflow_controller_tpu.scheduler import (
            GangScheduler,
            SchedulerPolicy,
        )

        inv = TPUInventory([TPUSlice(f"s{i}", "v5e-8", num_hosts=2)
                            for i in range(n_slices)])
        sched = GangScheduler(inv, SchedulerPolicy())
        evictions = []
        sched.set_evictor(lambda keys, reason: evictions.append(
            (sorted(keys), reason)))
        return inv, sched, evictions

    def _admit(self, sched, job, n):
        pods = [make_pod(job, job.spec.tf_replica_specs[0], i)
                for i in range(n)]
        for i, p in enumerate(pods):
            p.metadata.name = f"{job.metadata.name}-{i}"
        [sched.offer(p) for p in pods]
        sched.pod_started(pods[0])
        results = [sched.offer(p) for p in pods]
        return pods, results

    def _mesh_job(self, name, num_slices, min_width, cls="low"):
        job = mk_tpu_elastic_job(name, num_slices=num_slices,
                                 min_width=min_width)
        job.spec.tf_replica_specs[0].tpu.mesh = {"pp": 2, "dp": 2}
        job.spec.priority_class_name = cls
        return job

    def test_harvest_rounds_up_to_whole_pipeline_replicas(self):
        inv, sched, evictions = self._rig()
        low = self._mesh_job("low", 4, min_width=4)
        self._admit(sched, low, 8)
        high = mk_tpu_elastic_job("high", num_slices=1, min_width=2)
        high.spec.elastic = None
        high.spec.priority_class_name = "high"
        _, results = self._admit(sched, high, 2)
        assert any(results)
        # High needed 1 slice; the victim lost 2 (one whole pp replica),
        # never 1 — a 3-slice binding would orphan half a pipeline.
        assert len(sched.gang_slices("low-rid")) == 2
        keys, reason = evictions[0]
        assert reason.startswith("WidthHarvested")
        assert len(keys) == 4  # 2 slices x 2 hosts
        assert {k.rsplit("-", 1)[1] for k in keys} == {"4", "5", "6", "7"}

    def test_harvest_skips_victims_that_cannot_shed_a_whole_replica(self):
        inv, sched, evictions = self._rig()
        # Floor 6 -> min 3 slices: surplus is 1 slice, but the pp unit
        # is 2 — a 1-slice harvest would orphan half a pipeline, so the
        # victim is skipped and admission falls back to WHOLE
        # preemption.  Mid-pipeline theft never happens.
        low = self._mesh_job("low", 4, min_width=6)
        self._admit(sched, low, 8)
        high = mk_tpu_elastic_job("high", num_slices=1, min_width=2)
        high.spec.elastic = None
        high.spec.priority_class_name = "high"
        _, results = self._admit(sched, high, 2)
        assert any(results)
        assert not [r for _, r in evictions
                    if r.startswith("WidthHarvested")]
        keys, reason = next((k, r) for k, r in evictions
                            if r.startswith("Preempted"))
        assert len(keys) == 8  # the whole gang, not a partial span
        assert sched.gang_slices("low-rid") == []

    def test_placement_of_delegates_through_the_scheduler(self):
        from kubeflow_controller_tpu.scheduler import (
            GangScheduler,
            SchedulerPolicy,
        )

        inv = TPUInventory(sb_slices(4))
        sched = GangScheduler(inv, SchedulerPolicy())
        low = self._mesh_job("pl", 4, min_width=4)
        pods = [make_pod(low, low.spec.tf_replica_specs[0], i)
                for i in range(8)]
        for i, p in enumerate(pods):
            p.metadata.name = f"pl-{i}"
            sched.offer(p)
        pl = sched.placement_of("pl-rid")
        assert pl is not None
        assert pl["domains"] == ["sb0", "sb1"]
        assert pl["score"] == pytest.approx(2 / 3, abs=1e-3)
        assert sched.placement_of("nope") is None


# ---------------------------------------------------------------------------
# vet: the mesh-env rule
# ---------------------------------------------------------------------------

class TestMeshEnvRule:
    FIXTURES = os.path.join(REPO_ROOT, "tests", "fixtures", "vet",
                            "workloads")

    def _vet(self, name):
        from kubeflow_controller_tpu.analysis import vet

        findings = vet.run([os.path.join(self.FIXTURES, name)],
                           root=REPO_ROOT, skip_catalogue=True)
        return findings, {f.rule for f in findings}

    def test_bad_fixture_flagged(self):
        findings, rules = self._vet("bad_meshenv.py")
        assert rules == {"mesh-env"}
        assert len(findings) == 3  # spec chain + bare num_slices + slice_id
        assert all("MEGASCALE" in f.message for f in findings)

    def test_good_fixture_clean(self):
        findings, _ = self._vet("good_meshenv.py")
        assert findings == []

    def test_rule_is_scoped_to_workloads(self):
        # The planner legitimately reads tpu.num_slices — it is what
        # turns spec topology into the per-generation env contract.
        from kubeflow_controller_tpu.analysis import vet

        path = os.path.join(REPO_ROOT, "kubeflow_controller_tpu",
                            "planner", "materialize.py")
        findings = vet.run([path], root=REPO_ROOT, skip_catalogue=True)
        assert not [f for f in findings if f.rule == "mesh-env"]


# ---------------------------------------------------------------------------
# CLI: the placement surfaces
# ---------------------------------------------------------------------------

PLACEMENT = {
    "slices": ["slice-0", "slice-1", "slice-2", "slice-3"],
    "domains": ["sb0", "sb1"],
    "score": 0.6667,
    "mesh": {"dp": "dcn", "fsdp": "ici", "pp": "dcn"},
}


class TestCLIPlacement:
    @pytest.fixture
    def served(self):
        from kubeflow_controller_tpu.cluster import Cluster
        from kubeflow_controller_tpu.cluster.apiserver import FakeAPIServer

        cluster = Cluster()
        srv = FakeAPIServer(cluster.store)
        url = srv.start()
        for name, placed in (("placed", True), ("plain", False)):
            job = TFJob(metadata=ObjectMeta(name=name, namespace="default"))
            t = PodTemplateSpec()
            t.spec.containers.append(Container(name="c", image="img"))
            job.spec.tf_replica_specs = [TFReplicaSpec(
                replicas=8, tf_replica_type=ReplicaType.TPU, template=t,
                tpu=TPUSpec(accelerator_type="v5e-8", num_hosts=2,
                            num_slices=4))]
            if placed:
                job.metadata.annotations[ANNOTATION_PLACEMENT] = (
                    json.dumps(PLACEMENT, sort_keys=True))
            cluster.tfjobs.create(job)
            j = cluster.tfjobs.get("default", name)
            j.status.phase = TFJobPhase.RUNNING
            cluster.tfjobs.update_status(j)
        yield url
        srv.stop()

    def row(self, out, name):
        hdr = next(ln for ln in out.splitlines()
                   if ln.startswith("NAMESPACE"))
        row = next(ln for ln in out.splitlines()
                   if ln.startswith("default") and f" {name} " in f"{ln} ")
        return hdr, row

    def test_get_appends_slices_marker_without_shifting_columns(
            self, served, capsys):
        from kubeflow_controller_tpu.cli.main import main

        assert main(["-master", served, "get"]) == 0
        out = capsys.readouterr().out
        hdr, row = self.row(out, "placed")
        # The marker rides the REPLICAS cell (the row's last, free-width
        # column) so every fixed-width column stays put.
        at = hdr.index("REPLICAS")
        assert row[at:] == "TPUx8[slices=4]"
        _, plain = self.row(out, "plain")
        assert plain[at:] == "TPUx8"  # unplaced -> no marker

    def test_describe_prints_the_placement_section(self, served, capsys):
        from kubeflow_controller_tpu.cli.main import main

        assert main(["-master", served, "describe", "placed"]) == 0
        out = capsys.readouterr().out
        assert ("Placement: 4 slice(s) across 2 DCN domain(s), "
                "adjacency=0.6667") in out
        assert "slices: slice-0, slice-1, slice-2, slice-3" in out
        assert "domains: sb0, sb1" in out
        assert "mesh: dp->dcn fsdp->ici pp->dcn" in out

    def test_describe_without_placement_has_no_section(self, served,
                                                       capsys):
        from kubeflow_controller_tpu.cli.main import main

        assert main(["-master", served, "describe", "plain"]) == 0
        assert "Placement:" not in capsys.readouterr().out


# ---------------------------------------------------------------------------
# Workload runtime: the $KCTPU_MESH consumer
# ---------------------------------------------------------------------------

class TestRuntimeMeshEnv:
    def test_from_env_parses_the_planner_mesh(self, monkeypatch):
        from kubeflow_controller_tpu.workloads.runtime import JobRuntime

        monkeypatch.setenv("KCTPU_MESH",
                           '{"dp": 1, "fsdp": 4, "pp": 2}')
        monkeypatch.setenv("MEGASCALE_NUM_SLICES", "2")
        monkeypatch.setenv("MEGASCALE_SLICE_ID", "1")
        monkeypatch.setenv("MEGASCALE_COORDINATOR_ADDRESS",
                           "host-2.svc:8476")
        rt = JobRuntime.from_env()
        assert rt.mesh == {"dp": 1, "fsdp": 4, "pp": 2}
        assert (rt.num_slices, rt.slice_id) == (2, 1)
        assert rt.slice_coordinator == "host-2.svc:8476"

    def test_garbage_mesh_env_degrades_to_empty(self, monkeypatch):
        from kubeflow_controller_tpu.workloads.runtime import JobRuntime

        monkeypatch.setenv("KCTPU_MESH", "{not json")
        assert JobRuntime.from_env().mesh == {}
        # A single bad axis discards the whole dict: half a mesh plan
        # is worse than falling back to the CLI flags.
        monkeypatch.setenv("KCTPU_MESH", '{"dp": 2, "pp": "x"}')
        assert JobRuntime.from_env().mesh == {}
        # Sizes clamp to >= 1.
        monkeypatch.setenv("KCTPU_MESH", '{"dp": 0, "pp": 2}')
        assert JobRuntime.from_env().mesh == {"dp": 1, "pp": 2}
