"""Planner unit tests: pure-function diff engine (the reference's richest
domain logic, pkg/tensorflow/distributed.go, rebuilt index-aware)."""

import pytest

from kubeflow_controller_tpu.api.core import (
    PHASE_FAILED,
    PHASE_PENDING,
    PHASE_RUNNING,
    PHASE_SUCCEEDED,
    Container,
    Pod,
    PodTemplateSpec,
)
from kubeflow_controller_tpu.api.labels import (
    ANNOTATION_GANG_NAME,
    ANNOTATION_GANG_SIZE,
    LABEL_INDEX,
)
from kubeflow_controller_tpu.api.meta import ObjectMeta
from kubeflow_controller_tpu.api.tfjob import (
    ReplicaType,
    TFJob,
    TFJobPhase,
    TFReplicaSpec,
    TPUSpec,
)
from kubeflow_controller_tpu.planner import (
    Action,
    make_pod,
    make_service,
    plan_job,
    service_name,
)
from kubeflow_controller_tpu.planner.materialize import (
    ENV_COORDINATOR,
    ENV_NUM_PROCESSES,
    ENV_PROCESS_ID,
    TF_PORT,
)


def mk_template(restart="OnFailure"):
    t = PodTemplateSpec()
    t.spec.containers.append(Container(name="tensorflow", image="img"))
    t.spec.restart_policy = restart
    return t


def mk_job(*types_and_replicas, restart="OnFailure", tpu=None):
    job = TFJob(metadata=ObjectMeta(name="dist-mnist", namespace="default", uid="u1"))
    job.spec.runtime_id = "abc12"
    for typ, n in types_and_replicas:
        spec = TFReplicaSpec(replicas=n, tf_replica_type=typ, template=mk_template(restart))
        if typ == ReplicaType.TPU:
            spec.tpu = tpu or TPUSpec(accelerator_type="v5e-8", chips_per_host=4)
        job.spec.tf_replica_specs.append(spec)
    return job


def mk_pod(job, typ, index, phase=PHASE_RUNNING, name=None, ts=1.0):
    p = make_pod(job, next(s for s in job.spec.tf_replica_specs if s.tf_replica_type == typ), index)
    p.metadata.name = name or f"{typ.value.lower()}-{index}-{phase.lower()}"
    p.metadata.creation_timestamp = ts
    p.status.phase = phase
    return p


def actions(plan):
    return [(e.action, e.replica_type, e.index) for e in plan.events]


# ---- fresh job: everything created, services before pods, workers before PS ----

def test_fresh_distributed_job_ordering():
    job = mk_job((ReplicaType.PS, 2), (ReplicaType.WORKER, 4))
    plan = plan_job(job, {}, {})
    acts = actions(plan)
    # 4 worker svcs, 2 ps svcs, 4 worker pods, 2 ps pods (ref ordering).
    assert acts[:4] == [(Action.ADD_SERVICE, ReplicaType.WORKER, i) for i in range(4)]
    assert acts[4:6] == [(Action.ADD_SERVICE, ReplicaType.PS, i) for i in range(2)]
    assert acts[6:10] == [(Action.ADD_POD, ReplicaType.WORKER, i) for i in range(4)]
    assert acts[10:] == [(Action.ADD_POD, ReplicaType.PS, i) for i in range(2)]
    assert plan.creations == 12 and plan.deletions == 0


def test_local_job_single_pod_no_services():
    job = mk_job((ReplicaType.LOCAL, 1))
    plan = plan_job(job, {}, {})
    assert actions(plan) == [(Action.ADD_POD, ReplicaType.LOCAL, 0)]


def test_steady_state_empty_plan():
    job = mk_job((ReplicaType.PS, 1), (ReplicaType.WORKER, 2))
    pods = {
        ReplicaType.WORKER: [mk_pod(job, ReplicaType.WORKER, i) for i in range(2)],
        ReplicaType.PS: [mk_pod(job, ReplicaType.PS, 0)],
    }
    svcs = {
        ReplicaType.WORKER: [make_service(job, job.spec.tf_replica_specs[1], i) for i in range(2)],
        ReplicaType.PS: [make_service(job, job.spec.tf_replica_specs[0], 0)],
    }
    for lst in svcs.values():
        for s in lst:
            s.metadata.labels[LABEL_INDEX]  # sanity: index label present
    assert plan_job(job, pods, svcs).empty


# ---- repair paths the reference cannot do ----

def test_failed_worker_replaced_at_same_index():
    job = mk_job((ReplicaType.WORKER, 2))
    pods = {ReplicaType.WORKER: [
        mk_pod(job, ReplicaType.WORKER, 0, PHASE_RUNNING),
        mk_pod(job, ReplicaType.WORKER, 1, PHASE_FAILED, name="w1-dead"),
    ]}
    svcs = {ReplicaType.WORKER: [make_service(job, job.spec.tf_replica_specs[0], i) for i in range(2)]}
    plan = plan_job(job, pods, svcs)
    assert actions(plan) == [
        (Action.DELETE_POD, ReplicaType.WORKER, 1),
        (Action.ADD_POD, ReplicaType.WORKER, 1),
    ]
    assert plan.events[0].name == "w1-dead"
    assert all(e.reason == "replace-failed" for e in plan.events)


def test_failed_worker_restart_never_not_replaced():
    job = mk_job((ReplicaType.WORKER, 1), restart="Never")
    pods = {ReplicaType.WORKER: [mk_pod(job, ReplicaType.WORKER, 0, PHASE_FAILED)]}
    assert [a for a in actions(plan_job(job, pods, {})) if a[0] == Action.ADD_POD] == []


def test_partial_service_repair():
    # The reference only creates services when count==0 (distributed.go:78-92).
    job = mk_job((ReplicaType.WORKER, 3))
    svcs = {ReplicaType.WORKER: [make_service(job, job.spec.tf_replica_specs[0], 1)]}
    pods = {ReplicaType.WORKER: [mk_pod(job, ReplicaType.WORKER, i) for i in range(3)]}
    plan = plan_job(job, pods, svcs)
    assert actions(plan) == [
        (Action.ADD_SERVICE, ReplicaType.WORKER, 0),
        (Action.ADD_SERVICE, ReplicaType.WORKER, 2),
    ]


def test_scale_down_deletes_extras():
    job = mk_job((ReplicaType.WORKER, 1))
    pods = {ReplicaType.WORKER: [
        mk_pod(job, ReplicaType.WORKER, 0),
        mk_pod(job, ReplicaType.WORKER, 1, name="extra"),
    ]}
    svcs = {ReplicaType.WORKER: [make_service(job, job.spec.tf_replica_specs[0], i) for i in range(2)]}
    plan = plan_job(job, pods, svcs)
    acts = actions(plan)
    assert (Action.DELETE_POD, ReplicaType.WORKER, 1) in acts
    assert (Action.DELETE_SERVICE, ReplicaType.WORKER, 1) in acts


def test_duplicate_index_keeps_oldest():
    job = mk_job((ReplicaType.WORKER, 1))
    old = mk_pod(job, ReplicaType.WORKER, 0, name="old", ts=1.0)
    new = mk_pod(job, ReplicaType.WORKER, 0, name="new", ts=2.0)
    svcs = {ReplicaType.WORKER: [make_service(job, job.spec.tf_replica_specs[0], 0)]}
    plan = plan_job(job, {ReplicaType.WORKER: [new, old]}, svcs)
    assert [(e.action, e.name) for e in plan.events] == [(Action.DELETE_POD, "new")]


def test_succeeded_worker_index_not_recreated():
    job = mk_job((ReplicaType.WORKER, 2))
    pods = {ReplicaType.WORKER: [
        mk_pod(job, ReplicaType.WORKER, 0, PHASE_SUCCEEDED),
    ]}
    svcs = {ReplicaType.WORKER: [make_service(job, job.spec.tf_replica_specs[0], i) for i in range(2)]}
    plan = plan_job(job, pods, svcs)
    assert actions(plan) == [(Action.ADD_POD, ReplicaType.WORKER, 1)]


# ---- terminal cleanup (the missing "Recycling") ----

def test_succeeded_job_recycles_ps_and_services():
    job = mk_job((ReplicaType.PS, 1), (ReplicaType.WORKER, 1))
    job.status.phase = TFJobPhase.SUCCEEDED
    pods = {
        ReplicaType.WORKER: [mk_pod(job, ReplicaType.WORKER, 0, PHASE_SUCCEEDED)],
        ReplicaType.PS: [mk_pod(job, ReplicaType.PS, 0, PHASE_RUNNING, name="ps-alive")],
    }
    svcs = {ReplicaType.PS: [make_service(job, job.spec.tf_replica_specs[0], 0)]}
    plan = plan_job(job, pods, svcs)
    kinds = {(e.action, e.name) for e in plan.events}
    assert (Action.DELETE_POD, "ps-alive") in kinds
    assert any(a == Action.DELETE_SERVICE for a, _ in kinds)
    # The succeeded worker pod is kept as a record.
    assert not any(n == pods[ReplicaType.WORKER][0].metadata.name for _, n in kinds)


# ---- TPU gang ----

def test_tpu_fresh_gang_coordinator_service_and_pods():
    job = mk_job((ReplicaType.TPU, 2))
    plan = plan_job(job, {}, {})
    acts = actions(plan)
    assert acts[0] == (Action.ADD_SERVICE, ReplicaType.TPU, 0)  # coordinator only
    assert acts[1:] == [(Action.ADD_POD, ReplicaType.TPU, i) for i in range(2)]


def test_tpu_gang_failure_replaces_whole_gang():
    job = mk_job((ReplicaType.TPU, 2))
    pods = {ReplicaType.TPU: [
        mk_pod(job, ReplicaType.TPU, 0, PHASE_RUNNING, name="h0"),
        mk_pod(job, ReplicaType.TPU, 1, PHASE_FAILED, name="h1"),
    ]}
    svcs = {ReplicaType.TPU: [make_service(job, job.spec.tf_replica_specs[0], 0)]}
    plan = plan_job(job, pods, svcs)
    acts = actions(plan)
    deletes = [e.name for e in plan.events if e.action == Action.DELETE_POD]
    assert sorted(deletes) == ["h0", "h1"]  # survivor torn down too
    assert [a for a in acts if a[0] == Action.ADD_POD] == [
        (Action.ADD_POD, ReplicaType.TPU, 0), (Action.ADD_POD, ReplicaType.TPU, 1)
    ]


# ---- materializers ----

def test_make_pod_tf_cluster_args_and_template_isolation():
    job = mk_job((ReplicaType.PS, 2), (ReplicaType.WORKER, 4))
    worker_spec = job.spec.tf_replica_specs[1]
    p1 = make_pod(job, worker_spec, 1)
    p3 = make_pod(job, worker_spec, 3)
    a1 = p1.spec.containers[0].args
    assert f"--task_index=1" in a1 and "--job_name=worker" in a1
    assert f"--task_index=3" in p3.spec.containers[0].args
    # Shared template untouched (vs distributed.go:120-128).
    assert worker_spec.template.spec.containers[0].args == []
    wh = next(a for a in a1 if a.startswith("--worker_hosts="))
    hosts = wh.split("=", 1)[1].split(",")
    assert len(hosts) == 4
    assert hosts[0] == f"{service_name(job, ReplicaType.WORKER, 0)}:{TF_PORT}"
    ph = next(a for a in a1 if a.startswith("--ps_hosts="))
    assert len(ph.split("=", 1)[1].split(",")) == 2
    assert p1.metadata.labels[LABEL_INDEX] == "1"
    assert p1.metadata.generate_name.startswith("dist-mnist-worker-1-")


def test_make_pod_tpu_env_and_resources():
    job = mk_job((ReplicaType.TPU, 2))
    spec = job.spec.tf_replica_specs[0]
    pod = make_pod(job, spec, 1)
    env = {e.name: e.value for e in pod.spec.containers[0].env}
    assert env[ENV_NUM_PROCESSES] == "2"
    assert env[ENV_PROCESS_ID] == "1"
    subdomain = service_name(job, ReplicaType.TPU, 0)
    assert env[ENV_COORDINATOR] == f"host-0.{subdomain}:8476"
    assert env["TPU_WORKER_HOSTNAMES"] == f"host-0.{subdomain},host-1.{subdomain}"
    assert pod.spec.hostname == "host-1" and pod.spec.subdomain == subdomain
    assert pod.spec.containers[0].resources.requests["google.com/tpu"] == "4"
    assert "nvidia.com/gpu" not in pod.spec.containers[0].resources.requests
    assert pod.metadata.annotations[ANNOTATION_GANG_SIZE] == "2"
    assert pod.metadata.annotations[ANNOTATION_GANG_NAME] == "dist-mnist-abc12"
    # Always is coerced to Never for slice processes.
    assert pod.spec.restart_policy in ("Never", "OnFailure")


def test_make_service_deterministic_and_selector():
    job = mk_job((ReplicaType.WORKER, 1))
    svc = make_service(job, job.spec.tf_replica_specs[0], 0)
    assert svc.metadata.name == "dist-mnist-abc12-worker0"
    assert svc.spec.selector[LABEL_INDEX] == "0"
    assert svc.spec.ports[0].port == TF_PORT


def test_service_name_truncation_preserves_identity():
    # A 63-char job name must still yield distinct per-index service names.
    job = mk_job((ReplicaType.WORKER, 2))
    job.metadata.name = "j" * 63
    names = {service_name(job, ReplicaType.WORKER, i) for i in range(12)}
    assert len(names) == 12
    assert all(len(n) <= 63 for n in names)
    assert all(n.endswith(f"-abc12-worker{i}") for i, n in enumerate(sorted(
        names, key=lambda x: int(x.rsplit("worker", 1)[1])
    )))


def test_tpu_headless_service():
    job = mk_job((ReplicaType.TPU, 2))
    svc = make_service(job, job.spec.tf_replica_specs[0], 0)
    assert svc.metadata.name == "dist-mnist-abc12-tpu"
    assert svc.spec.cluster_ip == "None"  # headless
    assert LABEL_INDEX not in svc.spec.selector  # selects the whole gang
    assert svc.spec.ports[0].port == 8476


def test_tpu_gang_replace_clears_succeeded_records():
    job = mk_job((ReplicaType.TPU, 2))
    pods = {ReplicaType.TPU: [
        mk_pod(job, ReplicaType.TPU, 0, PHASE_SUCCEEDED, name="h0-done"),
        mk_pod(job, ReplicaType.TPU, 1, PHASE_FAILED, name="h1-dead"),
    ]}
    svcs = {ReplicaType.TPU: [make_service(job, job.spec.tf_replica_specs[0], 0)]}
    plan = plan_job(job, pods, svcs)
    deletes = sorted(e.name for e in plan.events if e.action == Action.DELETE_POD)
    # The Succeeded record is torn down too: a fresh gang is a fresh world.
    assert deletes == ["h0-done", "h1-dead"]


def test_dir_fields_plumbed_to_env():
    job = mk_job((ReplicaType.LOCAL, 1))
    job.spec.model_dir = "/ckpt"
    job.spec.data_dir = "/data"
    pod = make_pod(job, job.spec.tf_replica_specs[0], 0)
    env = {e.name: e.value for e in pod.spec.containers[0].env}
    assert env["MODEL_DIR"] == "/ckpt" and env["DATA_DIR"] == "/data"


def test_multislice_pod_wiring():
    """2-slice gang: global jax.distributed ids, per-slice TPU runtime env,
    slice annotations (the DCN analog of generateTFClusterSpec)."""
    from kubeflow_controller_tpu.api.labels import (
        ANNOTATION_GANG_SIZE,
        ANNOTATION_NUM_SLICES,
        ANNOTATION_SLICE_INDEX,
    )
    from kubeflow_controller_tpu.planner.materialize import (
        ENV_NUM_PROCESSES,
        ENV_NUM_SLICES,
        ENV_PROCESS_ID,
        ENV_SLICE_ID,
        ENV_TPU_WORKER_HOSTNAMES,
        ENV_TPU_WORKER_ID,
        make_pod,
    )

    job = mk_job((ReplicaType.TPU, 4),
                 tpu=TPUSpec(accelerator_type="v5e-8", chips_per_host=4,
                             num_slices=2))
    spec = job.spec.tf_replica_specs[0]
    envs = []
    for index in range(4):
        pod = make_pod(job, spec, index)
        env = {e.name: e.value for e in pod.spec.containers[0].env}
        envs.append(env)
        ann = pod.metadata.annotations
        assert ann[ANNOTATION_GANG_SIZE] == "4"
        assert ann[ANNOTATION_NUM_SLICES] == "2"
        assert ann[ANNOTATION_SLICE_INDEX] == str(index // 2)
    # Global process ids span both slices; TPU worker ids are per-slice.
    assert [e[ENV_PROCESS_ID] for e in envs] == ["0", "1", "2", "3"]
    assert all(e[ENV_NUM_PROCESSES] == "4" for e in envs)
    assert [e[ENV_TPU_WORKER_ID] for e in envs] == ["0", "1", "0", "1"]
    assert all(e[ENV_NUM_SLICES] == "2" for e in envs)
    assert [e[ENV_SLICE_ID] for e in envs] == ["0", "0", "1", "1"]
    # Each pod's hostname list covers only its own slice's two hosts.
    assert envs[0][ENV_TPU_WORKER_HOSTNAMES] == envs[1][ENV_TPU_WORKER_HOSTNAMES]
    assert envs[2][ENV_TPU_WORKER_HOSTNAMES] == envs[3][ENV_TPU_WORKER_HOSTNAMES]
    assert envs[0][ENV_TPU_WORKER_HOSTNAMES] != envs[2][ENV_TPU_WORKER_HOSTNAMES]
    assert all("host-0" in envs[0][ENV_TPU_WORKER_HOSTNAMES] for _ in [0])
