"""Controller integration tests: the whole spine — API -> queue -> sync ->
planner -> create -> watch -> status — against the fake cluster + kubelet
(SURVEY.md §7 "minimum end-to-end slice" and beyond)."""

import time

import pytest

from kubeflow_controller_tpu.api.core import (
    PHASE_FAILED,
    PHASE_RUNNING,
    PHASE_SUCCEEDED,
    Container,
    PodTemplateSpec,
)
from kubeflow_controller_tpu.api.labels import LABEL_INDEX, LABEL_JOB_TYPE
from kubeflow_controller_tpu.api.meta import ObjectMeta
from kubeflow_controller_tpu.api.tfjob import (
    ReplicaType,
    TFJob,
    TFJobPhase,
    TFReplicaSpec,
    TPUSpec,
)
from kubeflow_controller_tpu.cluster import (
    Cluster,
    FakeKubelet,
    PhasePolicy,
    TPUInventory,
    TPUSlice,
)
from kubeflow_controller_tpu.controller import Controller


def mk_template(restart="OnFailure"):
    t = PodTemplateSpec()
    t.spec.containers.append(Container(name="tensorflow", image="img"))
    t.spec.restart_policy = restart
    return t


def mk_job(name, *types_and_replicas, restart="OnFailure"):
    job = TFJob(metadata=ObjectMeta(name=name, namespace="default"))
    for typ, n in types_and_replicas:
        spec = TFReplicaSpec(replicas=n, tf_replica_type=typ, template=mk_template(restart))
        if typ == ReplicaType.TPU:
            spec.tpu = TPUSpec(accelerator_type="v5e-8", chips_per_host=4)
        job.spec.tf_replica_specs.append(spec)
    return job


def wait_for(fn, timeout=10.0, interval=0.02):
    deadline = time.time() + timeout
    while time.time() < deadline:
        v = fn()
        if v:
            return v
        time.sleep(interval)
    raise AssertionError("condition not met within timeout")


@pytest.fixture
def rig():
    """cluster + controller + kubelet, fast clocks."""
    cluster = Cluster()
    inventory = TPUInventory([TPUSlice("slice-0", "v5e-8", num_hosts=2)])
    kubelet = FakeKubelet(cluster, policy=PhasePolicy(run_s=0.05), inventory=inventory)
    ctrl = Controller(cluster, inventory=inventory, resync_period_s=0.5)
    kubelet.start()
    ctrl.run(threadiness=2)
    yield cluster, ctrl, kubelet, inventory
    ctrl.stop()
    kubelet.stop()


def phase_of(cluster, name):
    return cluster.tfjobs.get("default", name).status.phase


def test_local_job_to_succeeded(rig):
    cluster, ctrl, _, _ = rig
    cluster.tfjobs.create(mk_job("local-mnist", (ReplicaType.LOCAL, 1)))
    wait_for(lambda: phase_of(cluster, "local-mnist") == TFJobPhase.SUCCEEDED)
    pods = cluster.pods.list("default")
    assert len(pods) == 1
    assert pods[0].metadata.labels[LABEL_JOB_TYPE] == "Local"
    assert cluster.services.list("default") == []
    # runtime_id persisted on the spec.
    assert cluster.tfjobs.get("default", "local-mnist").spec.runtime_id


def test_distributed_job_full_lifecycle(rig):
    cluster, ctrl, _, _ = rig
    cluster.tfjobs.create(mk_job("dist-mnist", (ReplicaType.PS, 2), (ReplicaType.WORKER, 4)))
    # All 6 pods + 6 services materialize.
    wait_for(lambda: len(cluster.pods.list("default")) == 6)
    wait_for(lambda: len(cluster.services.list("default")) == 6)
    # Workers succeed (kubelet), PS runs forever -> job Succeeded.
    wait_for(lambda: phase_of(cluster, "dist-mnist") == TFJobPhase.SUCCEEDED)
    # Recycle: PS pods and services get torn down after success.
    wait_for(lambda: cluster.services.list("default") == [])
    wait_for(lambda: all(
        p.status.phase == PHASE_SUCCEEDED for p in cluster.pods.list("default")
    ))
    # Worker pods kept as records.
    assert len(cluster.pods.list("default")) == 4
    # No duplicate creations: exactly 6 pods were ever created (4 kept + 2 PS
    # recycled) — check events.
    creates = [e for e in ctrl.recorder.all_events() if e.reason == "SuccessfulCreate"]
    assert sum(e.count for e in creates) == 12  # 6 pods + 6 services


def test_failed_worker_recovers_index(rig):
    cluster, ctrl, kubelet, _ = rig
    # Slow the simulated run so the manual failure injection below cannot
    # race the pod's own Succeeded transition (a 0.05s window flakes when
    # the host is loaded); the replacement pod also runs 2s — still well
    # inside the wait_for timeout.
    kubelet.policy.run_s = 2.0
    cluster.tfjobs.create(mk_job("recover", (ReplicaType.WORKER, 2)))
    wait_for(lambda: len(cluster.pods.list("default")) == 2)
    # Fail index 0's pod manually (kubelet would have succeeded it).
    target = next(p for p in cluster.pods.list("default")
                  if p.metadata.labels[LABEL_INDEX] == "0")
    kubelet.set_phase("default", target.metadata.name, PHASE_FAILED)
    # Controller deletes the failed pod and creates a replacement at index 0.
    def replaced():
        pods = [p for p in cluster.pods.list("default")
                if p.metadata.labels[LABEL_INDEX] == "0"]
        return pods and all(p.metadata.name != target.metadata.name for p in pods)
    wait_for(replaced)
    wait_for(lambda: phase_of(cluster, "recover") == TFJobPhase.SUCCEEDED)


def test_tpu_gang_job_to_succeeded(rig):
    cluster, ctrl, _, inventory = rig
    cluster.tfjobs.create(mk_job("tpu-train", (ReplicaType.TPU, 2)))
    wait_for(lambda: phase_of(cluster, "tpu-train") == TFJobPhase.SUCCEEDED)
    # Gang released: slice free again.
    assert all(not s.bound_gang for s in inventory.slices.values())
    # Exactly one (coordinator) service was created.
    svc_creates = [e for e in ctrl.recorder.all_events()
                   if e.reason == "SuccessfulCreate" and "service" in e.message]
    assert sum(e.count for e in svc_creates) == 1


def test_invalid_job_rejected_via_event(rig):
    cluster, ctrl, _, _ = rig
    bad = mk_job("bad", (ReplicaType.WORKER, 1))
    bad.spec.tf_replica_specs[0].template = None
    cluster.tfjobs.create(bad)
    wait_for(lambda: any(
        e.reason == "InvalidSpec" for e in ctrl.recorder.events_for("default", "bad")
    ))
    assert cluster.pods.list("default") == []


def test_job_delete_cascades_children(rig):
    cluster, ctrl, _, _ = rig
    cluster.tfjobs.create(mk_job("doomed", (ReplicaType.PS, 1), (ReplicaType.WORKER, 1)))
    wait_for(lambda: len(cluster.pods.list("default")) == 2)
    cluster.tfjobs.delete("default", "doomed")
    wait_for(lambda: cluster.pods.list("default") == [])
    wait_for(lambda: cluster.services.list("default") == [])


def test_reconcile_metrics_recorded(rig):
    cluster, ctrl, _, _ = rig
    cluster.tfjobs.create(mk_job("metrics", (ReplicaType.LOCAL, 1)))
    wait_for(lambda: phase_of(cluster, "metrics") == TFJobPhase.SUCCEEDED)
    snap = ctrl.metrics.snapshot()
    assert snap["syncs"] > 0
    assert snap["reconcile_p50_s"] >= 0.0
    assert snap["creates"] >= 1


def test_multislice_tpu_job_full_lifecycle(rig):
    """A 2-slice TPU gang (4 pods over 2 x v5e-8) schedules all-or-nothing
    across slices, runs, succeeds, and frees BOTH slices."""
    cluster, ctrl, _, inventory = rig
    inventory.add_slice(TPUSlice("slice-1", "v5e-8", num_hosts=2))
    job = mk_job("multislice", (ReplicaType.TPU, 4))
    job.spec.tf_replica_specs[0].tpu = TPUSpec(
        accelerator_type="v5e-8", chips_per_host=4, num_slices=2)
    job.spec.tf_replica_specs[0].replicas = 4
    cluster.tfjobs.create(job)
    wait_for(lambda: phase_of(cluster, "multislice") == TFJobPhase.SUCCEEDED)
    pods = cluster.pods.list("default")
    assert len(pods) == 4
    assert all(not s.bound_gang for s in inventory.slices.values())


def test_tpu_job_pending_until_capacity_returns(rig):
    """A TPU job created while EVERY slice is quarantined must stay
    Pending (a real cluster out of capacity — not a controller wedge),
    then bind and complete when a slice heals, with no new API event:
    the level-triggered resync is what must notice.  Deterministic form
    of the fuzz flake where chaos quarantined all slices (round 5)."""
    cluster, ctrl, _, inventory = rig
    for s in inventory.slices.values():
        s.healthy = False
    cluster.tfjobs.create(mk_job("starved", (ReplicaType.TPU, 2)))
    time.sleep(1.5)  # several resync periods
    assert phase_of(cluster, "starved") not in (TFJobPhase.SUCCEEDED,
                                                TFJobPhase.FAILED)
    for s in inventory.slices.values():
        s.healthy = True
    wait_for(lambda: phase_of(cluster, "starved") == TFJobPhase.SUCCEEDED,
             timeout=20.0)
    assert all(not s.bound_gang for s in inventory.slices.values())


def test_finalizer_guards_deletion_cleanup(rig):
    """Deletion is finalizer-gated: the job lingers with deletionTimestamp
    until the controller releases the gang and deletes children explicitly,
    then the API server finalizes it (ref: the stubbed delete handlers at
    controller.go:522-524, 601-603)."""
    from kubeflow_controller_tpu.controller.controller import FINALIZER

    cluster, ctrl, _, inventory = rig
    cluster.tfjobs.create(mk_job("fin", (ReplicaType.TPU, 2)))
    wait_for(lambda: phase_of(cluster, "fin") == TFJobPhase.SUCCEEDED)
    # The controller stamped its finalizer on the live job.
    job = cluster.tfjobs.get("default", "fin")
    assert FINALIZER in job.metadata.finalizers
    cluster.tfjobs.delete("default", "fin")
    # Fully gone only after the controller's cleanup removed the finalizer.
    def gone():
        try:
            cluster.tfjobs.get("default", "fin")
            return False
        except Exception:
            return True
    wait_for(gone)
    assert cluster.pods.list("default") == []
    assert cluster.services.list("default") == []
    assert all(not s.bound_gang for s in inventory.slices.values())


def test_events_are_api_objects(rig):
    """The recorder writes real Event objects (kubectl-describe parity) with
    count aggregation."""
    cluster, ctrl, _, _ = rig
    cluster.tfjobs.create(mk_job("evjob", (ReplicaType.WORKER, 2)))
    wait_for(lambda: phase_of(cluster, "evjob") == TFJobPhase.SUCCEEDED)
    # Sink writes flush on a background thread (broadcaster model).
    events = wait_for(lambda: [
        e for e in cluster.events.list("default")
        if e.reason == "SuccessfulCreate" and e.involved_object.name == "evjob"
    ] and cluster.events.list("default"))
    creates = [e for e in events
               if e.reason == "SuccessfulCreate"
               and e.involved_object.name == "evjob"]
    assert creates
    assert all(e.involved_object.kind == "TFJob" for e in creates)
    assert all(e.source_component == "tfjob-controller" for e in creates)
    # 2 worker pods + 2 services created; counts aggregate per message, so
    # total count across create events is 4.
    assert sum(e.count for e in creates) == 4


def test_invalid_job_still_deletable(rig):
    """A job whose spec goes invalid AFTER creation must still be
    finalizable on delete — cleanup must not sit behind validation."""
    cluster, ctrl, _, _ = rig
    cluster.tfjobs.create(mk_job("gone-bad", (ReplicaType.WORKER, 1)))
    wait_for(lambda: phase_of(cluster, "gone-bad") == TFJobPhase.SUCCEEDED)
    # Invalidate the stored spec (the fake API server has no admission).
    j = cluster.tfjobs.get("default", "gone-bad")
    j.spec.tf_replica_specs[0].template = None
    cluster.tfjobs.update(j)
    cluster.tfjobs.delete("default", "gone-bad")
    def gone():
        try:
            cluster.tfjobs.get("default", "gone-bad")
            return False
        except Exception:
            return True
    wait_for(gone)
    assert cluster.pods.list("default") == []


def test_many_concurrent_jobs_stress(rig):
    """20 mixed jobs at once: the per-key serialized queue + expectations
    machinery must drive every one to Succeeded with zero sync errors and
    no duplicate creations."""
    cluster, ctrl, _, inventory = rig
    for i in range(3):
        inventory.add_slice(TPUSlice(f"stress-slice-{i}", "v5e-8", num_hosts=2))
    names = []
    for i in range(20):
        kind = i % 3
        if kind == 0:
            job = mk_job(f"stress-local-{i}", (ReplicaType.LOCAL, 1))
        elif kind == 1:
            job = mk_job(f"stress-dist-{i}", (ReplicaType.PS, 1),
                         (ReplicaType.WORKER, 2))
        else:
            job = mk_job(f"stress-tpu-{i}", (ReplicaType.TPU, 2))
        names.append(job.metadata.name)
        cluster.tfjobs.create(job)
    for n in names:
        wait_for(lambda n=n: phase_of(cluster, n) == TFJobPhase.SUCCEEDED,
                 timeout=60.0)
    snap = ctrl.metrics.snapshot()
    assert snap["sync_errors"] == 0
    # Exactly the expected number of pods were ever created: 7 locals x1 +
    # 7 dists x3 + 6 TPUs x2 = 40 pods (no double-creates through the
    # expectations window).
    pod_creates = [e for e in ctrl.recorder.all_events()
                   if e.reason == "SuccessfulCreate" and "pod" in e.message]
    assert sum(e.count for e in pod_creates) == 7 * 1 + 7 * 3 + 6 * 2


def test_live_rescale_up_and_down(rig):
    """Editing replicas on a LIVE job reconciles both directions: scale-up
    creates the missing indices, scale-down deletes the excess — the
    reference declared ActionShouldDelete and never produced it (ref:
    types.go:39-40); its planner could not resize anything."""
    cluster, ctrl, _, _ = rig
    cluster.tfjobs.create(mk_job("resize", (ReplicaType.PS, 2)))  # PS: runs forever
    wait_for(lambda: len(cluster.pods.list("default")) == 2)

    j = cluster.tfjobs.get("default", "resize")
    j.spec.tf_replica_specs[0].replicas = 4
    cluster.tfjobs.update(j)
    wait_for(lambda: len([p for p in cluster.pods.list("default")
                          if p.status.phase == PHASE_RUNNING]) == 4)
    indices = sorted(p.metadata.labels[LABEL_INDEX]
                     for p in cluster.pods.list("default"))
    assert indices == ["0", "1", "2", "3"]

    j = cluster.tfjobs.get("default", "resize")
    j.spec.tf_replica_specs[0].replicas = 1
    cluster.tfjobs.update(j)
    wait_for(lambda: len(cluster.pods.list("default")) == 1)
    assert cluster.pods.list("default")[0].metadata.labels[LABEL_INDEX] == "0"
