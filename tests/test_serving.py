"""Serving plane: continuous-batching engine, slot-paged KV cache,
queue-depth autoscaler hysteresis, graceful drain, rolling updates, and
the bucketed-prefill compile-cache contract (docs/SERVING.md)."""

import random
import threading
import time

import pytest

from kubeflow_controller_tpu.api.core import (
    PHASE_FAILED,
    PHASE_PENDING,
    PHASE_RUNNING,
    PHASE_SUCCEEDED,
    Container,
    Pod,
    PodProgress,
    PodTemplateSpec,
)
from kubeflow_controller_tpu.api.labels import (
    ANNOTATION_DRAIN,
    ANNOTATION_GANG_GENERATION,
    ANNOTATION_SERVING_REPLICAS,
    LABEL_INDEX,
)
from kubeflow_controller_tpu.api.meta import ObjectMeta
from kubeflow_controller_tpu.api.tfjob import (
    AutoscaleSpec,
    ReplicaType,
    TFJob,
    TFJobPhase,
    TFReplicaSpec,
    ValidationError,
    is_serving_job,
    serving_spec,
    validate_tfjob,
)
from kubeflow_controller_tpu.checker import StallPolicy, StallTracker
from kubeflow_controller_tpu.planner import Action, make_pod, make_service, plan_job
from kubeflow_controller_tpu.serving.autoscale import (
    ServingAutoscaler,
    serving_width,
)
from kubeflow_controller_tpu.updater import compute_status
from kubeflow_controller_tpu.workloads.serve import (
    REFUSED_DRAINING,
    REFUSED_OVERLOADED,
    SUBMIT_OK,
    Request,
    ServeConfig,
    ServeEngine,
    SyntheticBackend,
)


def mk_template():
    t = PodTemplateSpec()
    t.spec.containers.append(Container(name="srv", image="img"))
    t.spec.restart_policy = "OnFailure"
    return t


def mk_serving_job(replicas=1, min_r=1, max_r=3, target=4.0,
                   autoscale=True, stabilization=3.0, tolerance=0.2):
    job = TFJob(metadata=ObjectMeta(name="svc", namespace="default",
                                    uid="u-svc"))
    job.spec.runtime_id = "rid42"
    if autoscale:
        job.spec.autoscale = AutoscaleSpec(
            min_replicas=min_r, max_replicas=max_r,
            target_queue_depth=target, tolerance=tolerance,
            scale_down_stabilization_s=stabilization)
    job.spec.tf_replica_specs.append(TFReplicaSpec(
        replicas=replicas, tf_replica_type=ReplicaType.SERVING,
        template=mk_template()))
    return job


def mk_serving_pod(job, index, phase=PHASE_RUNNING, ready=True,
                   queue_depth=0, generation=None, draining=False,
                   name=None, ts=1.0):
    spec = serving_spec(job)
    p = make_pod(job, spec, index)
    p.metadata.name = name or f"svc-serving-{index}-x{int(ts)}"
    p.metadata.creation_timestamp = ts
    p.status.phase = phase
    if generation is not None:
        p.metadata.annotations[ANNOTATION_GANG_GENERATION] = str(generation)
    if draining:
        p.metadata.annotations[ANNOTATION_DRAIN] = "scale-down"
    if ready and phase == PHASE_RUNNING:
        p.status.progress = PodProgress(
            step=10, phase="serving", qps=2.0, ttft_ms=5.0, itl_ms=1.0,
            queue_depth=queue_depth, slots_used=2, slots_total=4,
            timestamp=time.time())
    return p


def set_width(job, n):
    job.metadata.annotations[ANNOTATION_SERVING_REPLICAS] = str(n)


# ---------------------------------------------------------------------------
# API + validation
# ---------------------------------------------------------------------------

class TestServingAPI:
    def test_classifiers(self):
        job = mk_serving_job()
        assert is_serving_job(job)
        assert serving_spec(job) is job.spec.tf_replica_specs[0]

    def test_valid_spec(self):
        validate_tfjob(mk_serving_job())

    def test_autoscale_requires_serving_set(self):
        job = mk_serving_job()
        job.spec.tf_replica_specs[0].tf_replica_type = ReplicaType.WORKER
        with pytest.raises(ValidationError):
            validate_tfjob(job)

    def test_autoscale_bounds_validated(self):
        with pytest.raises(ValidationError):
            validate_tfjob(mk_serving_job(min_r=0))
        with pytest.raises(ValidationError):
            validate_tfjob(mk_serving_job(min_r=3, max_r=2, replicas=3))
        with pytest.raises(ValidationError):
            validate_tfjob(mk_serving_job(target=0.0))
        with pytest.raises(ValidationError):
            validate_tfjob(mk_serving_job(replicas=5, max_r=3))

    def test_serving_width_annotation_clamped(self):
        job = mk_serving_job(min_r=1, max_r=3)
        assert serving_width(job) == 1  # default = minReplicas
        set_width(job, 2)
        assert serving_width(job) == 2
        set_width(job, 9)
        assert serving_width(job) == 3  # clamped to maxReplicas
        job.metadata.annotations[ANNOTATION_SERVING_REPLICAS] = "junk"
        assert serving_width(job) == 1

    def test_serving_width_without_autoscale(self):
        job = mk_serving_job(replicas=2, autoscale=False)
        assert serving_width(job) == 2


# ---------------------------------------------------------------------------
# Engine: slot accounting, continuous vs static, drain
# ---------------------------------------------------------------------------

def mk_engine(slots=4, page_size=8, max_len=64, cont=True, backend=None):
    eng = ServeEngine(
        backend or SyntheticBackend(),
        ServeConfig(slots=slots, page_size=page_size, max_len=max_len,
                    prefill_buckets=(8, 16, 32), cont_batch=cont,
                    stats_window_s=2.0))
    eng.start()
    assert eng.wait_ready(30)
    return eng


class TestServeEngine:
    def test_all_requests_complete_exact_lengths(self):
        eng = mk_engine()
        rng = random.Random(3)
        reqs = [Request(id=str(i), tokens=[1 + i % 40] * rng.randrange(1, 30),
                        max_new_tokens=rng.randrange(1, 10))
                for i in range(25)]
        for r in reqs:
            assert eng.submit(r)
        for r in reqs:
            assert r.done.wait(30), r.id
            assert len(r.output) == r.max_new_tokens
        st = eng.stats()
        assert st.completed == 25 and st.dropped == 0
        assert st.slots_used == 0 and st.queue_depth == 0
        eng.stop()

    def test_slot_and_page_accounting_under_concurrent_admit_evict(self):
        """Hammer submits from several threads while the decode loop
        admits and evicts; every page must come home and the slot table
        must empty."""
        eng = mk_engine(slots=3, page_size=8, max_len=48)
        total_pages = 3 * (48 // 8)
        rng = random.Random(11)
        reqs = []
        errs = []

        def feeder(tid):
            local = random.Random(100 + tid)
            for i in range(30):
                r = Request(id=f"{tid}-{i}",
                            tokens=[1] * local.randrange(1, 40),
                            max_new_tokens=local.randrange(1, 12))
                reqs.append(r)
                if not eng.submit(r):
                    errs.append(r.id)
                time.sleep(local.random() * 0.002)

        threads = [threading.Thread(target=feeder, args=(t,),
                                    name=f"serve-feeder-{t}", daemon=True)
                   for t in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for r in reqs:
            assert r.done.wait(60), r.id
            assert len(r.output) == r.max_new_tokens
        assert not errs
        # Decode loop idle: pages all free, slots all empty.
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            st = eng.stats()
            if st.slots_used == 0:
                break
            time.sleep(0.01)
        assert eng.stats().slots_used == 0
        with eng._lock:
            assert sorted(eng._free_pages) == list(range(1, total_pages + 1))
            assert all(s is None for s in eng._slots)
        eng.stop()

    def test_continuous_beats_static_on_mixed_lengths(self):
        """Same request set, same backend cost model: continuous batching
        must finish the burst in fewer decode steps than the padding
        static baseline (steps are the device-time proxy)."""
        def burst(cont):
            eng = mk_engine(slots=4, cont=cont)
            rng = random.Random(5)
            reqs = [Request(id=str(i), tokens=[2] * 4,
                            max_new_tokens=rng.choice([2, 4, 8, 24]))
                    for i in range(24)]
            for r in reqs:
                eng.submit(r)
            for r in reqs:
                assert r.done.wait(30)
            steps = eng.stats().step
            eng.stop()
            return steps

        static_steps = burst(False)
        cont_steps = burst(True)
        assert cont_steps < static_steps / 1.5, (cont_steps, static_steps)

    def test_drain_stops_intake_finishes_inflight(self):
        backend = SyntheticBackend(step_s=0.005)
        eng = mk_engine(slots=2, backend=backend)
        inflight = [Request(id=f"in-{i}", tokens=[1, 2],
                            max_new_tokens=20) for i in range(2)]
        queued = [Request(id=f"q-{i}", tokens=[1], max_new_tokens=4)
                  for i in range(3)]
        for r in inflight + queued:
            eng.submit(r)
        # Let the two in-flight requests admit (slots=2).
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and eng.stats().slots_used < 2:
            time.sleep(0.005)
        handed_back = eng.drain()
        # Unadmitted queue handed back for re-routing; intake closed.
        assert {r.id for r in handed_back} <= {r.id for r in queued}
        late = Request(id="late", tokens=[1], max_new_tokens=1)
        assert not eng.submit(late)
        assert not late.done.is_set()  # untouched: caller re-routes
        # In-flight requests complete in full.
        for r in inflight:
            assert r.done.wait(30), r.id
            assert len(r.output) == r.max_new_tokens and not r.error
        assert eng._drained.wait(10)
        assert eng.stats().phase == "drain"
        eng.stop()


# ---------------------------------------------------------------------------
# Bucketed-prefill compile contract (the PR 8 cache fix)
# ---------------------------------------------------------------------------

class TestPrefillBuckets:
    def test_bucket_for(self):
        cfg = ServeConfig(prefill_buckets=(8, 16, 32))
        assert cfg.bucket_for(1) == 8
        assert cfg.bucket_for(8) == 8
        assert cfg.bucket_for(9) == 16
        assert cfg.bucket_for(33) == 32  # oversized: largest bucket

    def test_100_request_sweep_bounded_compiles(self):
        """The regression the fingerprint fix exists for: 100 requests of
        novel lengths must compile at most len(buckets) prefill
        programs — keying on raw lengths would compile ~one per length
        on the serving hot path."""
        eng = mk_engine(slots=4)
        rng = random.Random(17)
        reqs = [Request(id=str(i), tokens=[1] * rng.randrange(1, 33),
                        max_new_tokens=2) for i in range(100)]
        for r in reqs:
            eng.submit(r)
        for r in reqs:
            assert r.done.wait(60)
        assert eng.stats().prefill_compiles <= 3
        eng.stop()

    def test_fingerprint_keys_on_bucket_not_length(self):
        """LlamaBackend's AOT fingerprint is a pure function of the
        BUCKETED shape set (jax-free check: the fingerprint is computed
        before any compile)."""
        from kubeflow_controller_tpu.models.llama import LlamaConfig
        from kubeflow_controller_tpu.workloads.serve import LlamaBackend

        cfg = ServeConfig(slots=2, page_size=8, max_len=64,
                          prefill_buckets=(8, 16))
        b = LlamaBackend(LlamaConfig.tiny())
        b._serve_cfg = cfg
        b._num_pages = 1 + cfg.slots * cfg.pages_per_slot()
        # Lengths 3 and 7 share bucket 8 -> identical fingerprints.
        assert (b._fingerprint("prefill", cfg.bucket_for(3))
                == b._fingerprint("prefill", cfg.bucket_for(7)))
        # Different buckets -> different programs.
        assert (b._fingerprint("prefill", cfg.bucket_for(3))
                != b._fingerprint("prefill", cfg.bucket_for(9)))


# ---------------------------------------------------------------------------
# Autoscaler hysteresis
# ---------------------------------------------------------------------------

class TestAutoscalerHysteresis:
    def assess(self, a, job, depths, now, ready=True):
        pods = [mk_serving_pod(job, i, queue_depth=d, ready=ready)
                for i, d in enumerate(depths)]
        return a.assess("default/svc", job, pods, now=now)

    def test_scale_up_immediate(self):
        job = mk_serving_job(target=4.0)
        a = ServingAutoscaler()
        d = self.assess(a, job, [12], now=100.0)
        assert d.target == 3  # ceil(1 * 12/4) = 3, clamped to max 3

    def test_no_flapping_inside_tolerance(self):
        """Depths oscillating around the setpoint (within the band) must
        produce ZERO scale decisions over many assessments."""
        job = mk_serving_job(target=4.0, tolerance=0.25)
        set_width(job, 2)
        a = ServingAutoscaler()
        for i, d in enumerate([4, 5, 3, 4, 5, 3, 4] * 5):
            dec = self.assess(a, job, [d, d], now=100.0 + i)
            assert dec.target is None, (i, d, dec)

    def test_scale_down_waits_out_stabilization(self):
        job = mk_serving_job(target=4.0, stabilization=5.0)
        set_width(job, 3)
        a = ServingAutoscaler()
        d = self.assess(a, job, [0, 0, 0], now=100.0)
        assert d.target is None and d.requeue_after_s > 0
        d = self.assess(a, job, [0, 0, 0], now=103.0)
        assert d.target is None  # still inside the window
        d = self.assess(a, job, [0, 0, 0], now=105.5)
        assert d.target == 1

    def test_burst_resets_scale_down_window(self):
        job = mk_serving_job(target=4.0, stabilization=5.0)
        set_width(job, 3)
        a = ServingAutoscaler()
        self.assess(a, job, [0, 0, 0], now=100.0)
        # Load returns mid-window: the clock must reset.
        self.assess(a, job, [5, 5, 5], now=103.0)
        d = self.assess(a, job, [0, 0, 0], now=106.0)
        assert d.target is None  # a fresh window started at 106
        d = self.assess(a, job, [0, 0, 0], now=111.5)
        assert d.target == 1

    def test_scale_up_held_while_replicas_warm(self):
        """ready < current: the requested capacity hasn't materialized;
        asking again would double-provision the same backlog."""
        job = mk_serving_job(target=4.0)
        set_width(job, 2)
        a = ServingAutoscaler()
        pods = [mk_serving_pod(job, 0, queue_depth=40),
                mk_serving_pod(job, 1, ready=False)]
        d = a.assess("default/svc", job, pods, now=100.0)
        assert d.target is None

    def test_no_signal_no_action(self):
        job = mk_serving_job()
        a = ServingAutoscaler()
        d = a.assess("default/svc", job,
                     [mk_serving_pod(job, 0, ready=False)], now=100.0)
        assert d.target is None and d.requeue_after_s == 0


# ---------------------------------------------------------------------------
# Planner: serving plans (create / drain / rolling update)
# ---------------------------------------------------------------------------

POD_ACTIONS = (Action.ADD_POD, Action.DELETE_POD, Action.DRAIN_POD)


def actions(plan):
    return [(e.action, e.index) for e in plan.events
            if e.replica_type == ReplicaType.SERVING
            and e.action in POD_ACTIONS]


class TestServingPlanner:
    def plan(self, job, pods):
        return plan_job(job, {ReplicaType.SERVING: pods},
                        {ReplicaType.SERVING: []})

    def test_creates_to_target(self):
        job = mk_serving_job()
        set_width(job, 2)
        plan = self.plan(job, [])
        assert (Action.ADD_POD, 0) in actions(plan)
        assert (Action.ADD_POD, 1) in actions(plan)

    def test_scale_down_drains_not_deletes(self):
        job = mk_serving_job()
        set_width(job, 1)
        pods = [mk_serving_pod(job, 0), mk_serving_pod(job, 1),
                mk_serving_pod(job, 2)]
        acts = actions(self.plan(job, pods))
        assert (Action.DRAIN_POD, 1) in acts
        assert (Action.DRAIN_POD, 2) in acts
        assert not any(a == Action.DELETE_POD for a, _ in acts)

    def test_draining_pod_not_redrained(self):
        job = mk_serving_job()
        set_width(job, 1)
        pods = [mk_serving_pod(job, 0), mk_serving_pod(job, 1, draining=True)]
        assert actions(self.plan(job, pods)) == []

    def test_drained_record_cleared(self):
        job = mk_serving_job()
        set_width(job, 1)
        pods = [mk_serving_pod(job, 0),
                mk_serving_pod(job, 1, phase=PHASE_SUCCEEDED, ready=False)]
        acts = actions(self.plan(job, pods))
        assert (Action.DELETE_POD, 1) in acts
        assert (Action.ADD_POD, 1) not in acts

    def test_exited_server_at_in_target_index_recreated(self):
        """A serving index is never 'done': a Succeeded exit below the
        target is replaced (unlike batch workers)."""
        job = mk_serving_job()
        set_width(job, 1)
        pods = [mk_serving_pod(job, 0, phase=PHASE_SUCCEEDED, ready=False)]
        acts = actions(self.plan(job, pods))
        assert (Action.DELETE_POD, 0) in acts
        assert (Action.ADD_POD, 0) in acts

    def test_rolling_update_one_at_a_time(self):
        job = mk_serving_job()
        set_width(job, 3)
        job.metadata.annotations[ANNOTATION_GANG_GENERATION] = "1"
        pods = [mk_serving_pod(job, i, generation=0) for i in range(3)]
        acts = actions(self.plan(job, pods))
        drains = [i for a, i in acts if a == Action.DRAIN_POD]
        assert drains == [0]  # exactly one stale replica drains

    def test_rolling_waits_for_replacement_ready(self):
        job = mk_serving_job()
        set_width(job, 3)
        job.metadata.annotations[ANNOTATION_GANG_GENERATION] = "1"
        pods = [mk_serving_pod(job, 0, generation=1, ready=False),  # warming
                mk_serving_pod(job, 1, generation=0),
                mk_serving_pod(job, 2, generation=0)]
        acts = actions(self.plan(job, pods))
        assert not any(a == Action.DRAIN_POD for a, _ in acts)

    def test_rolling_waits_while_draining(self):
        job = mk_serving_job()
        set_width(job, 3)
        job.metadata.annotations[ANNOTATION_GANG_GENERATION] = "1"
        pods = [mk_serving_pod(job, 0, generation=0, draining=True),
                mk_serving_pod(job, 1, generation=0),
                mk_serving_pod(job, 2, generation=0)]
        acts = actions(self.plan(job, pods))
        assert not any(a == Action.DRAIN_POD for a, _ in acts)

    def test_fresh_generation_plan_is_stable(self):
        job = mk_serving_job()
        set_width(job, 2)
        pods = [mk_serving_pod(job, 0, generation=0),
                mk_serving_pod(job, 1, generation=0)]
        assert actions(self.plan(job, pods)) == []

    def test_serving_service_per_replica(self):
        job = mk_serving_job()
        set_width(job, 2)
        plan = self.plan(job, [])
        svc_adds = [e for e in plan.events
                    if e.action == Action.ADD_SERVICE
                    and e.replica_type == ReplicaType.SERVING]
        assert [e.index for e in svc_adds] == [0, 1]
        svc = make_service(job, serving_spec(job), 0)
        assert svc.spec.ports[0].port == 8500
        assert svc.spec.selector[LABEL_INDEX] == "0"


# ---------------------------------------------------------------------------
# Updater: serving rollup + long-running phase semantics
# ---------------------------------------------------------------------------

class TestServingStatus:
    def test_serving_job_never_succeeds(self):
        job = mk_serving_job()
        set_width(job, 1)
        pods = [mk_serving_pod(job, 0, phase=PHASE_SUCCEEDED, ready=False)]
        st = compute_status(job, {ReplicaType.SERVING: pods})
        assert st.phase != TFJobPhase.SUCCEEDED

    def test_running_and_rollup(self):
        job = mk_serving_job()
        set_width(job, 2)
        pods = [mk_serving_pod(job, 0, queue_depth=3),
                mk_serving_pod(job, 1, queue_depth=5)]
        st = compute_status(job, {ReplicaType.SERVING: pods})
        assert st.phase == TFJobPhase.RUNNING
        assert st.serving is not None
        assert st.serving.replicas == 2 and st.serving.ready == 2
        assert st.serving.queue_depth == 8
        assert st.serving.qps == 4.0
        assert st.serving.occupancy == 0.5
        assert st.serving.min_replicas == 1 and st.serving.max_replicas == 3

    def test_ready_requires_first_decode_step(self):
        job = mk_serving_job()
        set_width(job, 1)
        loading = mk_serving_pod(job, 0, ready=False)
        loading.status.progress = PodProgress(phase="load",
                                              timestamp=time.time())
        st = compute_status(job, {ReplicaType.SERVING: [loading]})
        ready = next(c for c in st.conditions if c.type.value == "Ready")
        assert ready.status == "False"
        assert st.serving.ready == 0
        st = compute_status(job,
                            {ReplicaType.SERVING: [mk_serving_pod(job, 0)]})
        ready = next(c for c in st.conditions if c.type.value == "Ready")
        assert ready.status == "True"

    def test_non_serving_job_has_no_serving_status(self):
        job = TFJob(metadata=ObjectMeta(name="j", namespace="default"))
        job.spec.runtime_id = "r1"
        job.spec.tf_replica_specs.append(TFReplicaSpec(
            replicas=1, tf_replica_type=ReplicaType.WORKER,
            template=mk_template()))
        st = compute_status(job, {ReplicaType.WORKER: []})
        assert st.serving is None


# ---------------------------------------------------------------------------
# Stall semantics: serving phases hold the frozen-step deadline
# ---------------------------------------------------------------------------

class TestServingStallHold:
    def mk_beat(self, step, phase, t):
        return PodProgress(step=step, phase=phase, timestamp=t)

    def test_idle_serving_replica_not_stalled(self):
        """Step counter frozen for far past the step deadline while
        phase="serving": held (idle servers are healthy); a fresh
        heartbeat keeps the liveness clock green."""
        tr = StallTracker(StallPolicy(heartbeat_deadline_s=30.0,
                                      step_deadline_s=10.0))
        t0 = 1000.0
        for dt in (0.0, 5.0, 11.0, 60.0, 300.0):
            assert not tr.observe("ns/p", self.mk_beat(7, "serving", t0 + dt),
                                  now=t0 + dt)

    def test_load_and_drain_held_too(self):
        for phase in ("load", "drain"):
            tr = StallTracker(StallPolicy(heartbeat_deadline_s=30.0,
                                          step_deadline_s=10.0))
            t0 = 2000.0
            for dt in (0.0, 15.0, 45.0):
                assert not tr.observe(f"ns/{phase}",
                                      self.mk_beat(0, phase, t0 + dt),
                                      now=t0 + dt)

    def test_dead_server_still_flagged_by_heartbeat(self):
        tr = StallTracker(StallPolicy(heartbeat_deadline_s=30.0,
                                      step_deadline_s=10.0))
        t0 = 3000.0
        assert not tr.observe("ns/dead", self.mk_beat(7, "serving", t0),
                              now=t0)
        # Beats STOP: the stale timestamp trips the heartbeat deadline.
        assert tr.observe("ns/dead", self.mk_beat(7, "serving", t0),
                          now=t0 + 31.0)


# ---------------------------------------------------------------------------
# E2E: controller + kubelet (scale up / drain down / roll / gauges)
# ---------------------------------------------------------------------------

@pytest.fixture()
def serving_cluster():
    from kubeflow_controller_tpu.cluster import (
        Cluster,
        FakeKubelet,
        PhasePolicy,
    )
    from kubeflow_controller_tpu.controller import Controller

    cluster = Cluster()
    kubelet = FakeKubelet(cluster, policy=PhasePolicy(run_s=0.05))
    ctrl = Controller(cluster, resync_period_s=2.0)
    kubelet.start()
    ctrl.run()
    yield cluster, kubelet, ctrl
    ctrl.stop()
    kubelet.stop()


def serving_pods(cluster, phase=None):
    out = [p for p in cluster.pods.list("default")
           if p.metadata.labels.get("job_type") == "Serving"]
    if phase:
        out = [p for p in out if p.status.phase == phase]
    return out


def beat_pod(cluster, p, depth):
    """What a live replica publishes: serving beats under load, a
    drain-ACK beat (phase="drain", empty) once it sees its annotation."""
    draining = bool(p.metadata.annotations.get(ANNOTATION_DRAIN))
    cluster.pods.update_progress("default", p.metadata.name, PodProgress(
        step=10, phase="drain" if draining else "serving",
        qps=2.0, ttft_ms=4.0, itl_ms=1.0,
        queue_depth=0 if draining else depth,
        slots_used=0 if draining else 2, slots_total=4))


def pump_until(cluster, depth, cond, timeout=15.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        for p in serving_pods(cluster, PHASE_RUNNING):
            beat_pod(cluster, p, depth)
        if cond():
            return True
        time.sleep(0.05)
    return False


@pytest.mark.slow
class TestServingE2E:
    def test_scale_up_drain_down_roll_and_gauge_cleanup(self, serving_cluster):
        import re

        from kubeflow_controller_tpu.obs.metrics import REGISTRY

        cluster, kubelet, ctrl = serving_cluster
        job = mk_serving_job(stabilization=1.0)
        cluster.tfjobs.create(job)

        assert pump_until(cluster, 0, lambda: len(
            serving_pods(cluster, PHASE_RUNNING)) == 1)

        # Load: queue depth far past target -> scale to max.
        assert pump_until(cluster, 12, lambda: len(
            serving_pods(cluster, PHASE_RUNNING)) == 3)
        j = cluster.tfjobs.get("default", "svc")
        assert j.metadata.annotations[ANNOTATION_SERVING_REPLICAS] == "3"

        # Quiet: graceful drain back to min (1); drained records cleared.
        assert pump_until(cluster, 0, lambda: len(
            serving_pods(cluster, PHASE_RUNNING)) == 1, timeout=30.0)

        # Per-replica gauge series freed on scale-down (Gauge.remove).
        def live_series():
            return re.findall(r'kctpu_serve_queue_depth\{[^}]*tfjob="svc"[^}]*\}',
                              REGISTRY.render())

        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and len(live_series()) > 1:
            for p in serving_pods(cluster, PHASE_RUNNING):
                beat_pod(cluster, p, 0)
            time.sleep(0.05)
        assert len(live_series()) <= 1

        # Rolling weight update: generation bump replaces the replica
        # through drain, zero hard deletes of a live server.
        def bump(m):
            m.annotations[ANNOTATION_GANG_GENERATION] = "1"

        cluster.tfjobs.patch_meta("default", "svc", bump)

        def rolled():
            r = serving_pods(cluster, PHASE_RUNNING)
            return bool(r) and all(
                p.metadata.annotations.get(ANNOTATION_GANG_GENERATION) == "1"
                for p in r)

        assert pump_until(cluster, 0, rolled, timeout=30.0)
        reasons = [e.reason for e in ctrl.recorder.events_for("default", "svc")]
        assert "ServingScaledUp" in reasons
        assert "ServingScaledDown" in reasons
        assert "ServingDraining" in reasons

        # Job deletion drops every serving series (deletion syncs are
        # async: wait for the final job-gone sync's drop to land).
        cluster.tfjobs.delete("default", "svc")

        def any_svc_series():
            page = REGISTRY.render()
            return (live_series()
                    or 'kctpu_serve_qps{namespace="default",tfjob="svc"}'
                    in page)

        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and any_svc_series():
            time.sleep(0.05)
        assert not any_svc_series()


# ---------------------------------------------------------------------------
# Executed entrypoint: SIGTERM = stop intake -> finish -> exit 0
# ---------------------------------------------------------------------------

@pytest.mark.slow
class TestServeMainDrain:
    def test_sigterm_graceful_exit(self, tmp_path):
        import json
        import os
        import signal
        import socket
        import subprocess
        import sys

        port = _free_port()
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        proc = subprocess.Popen(
            [sys.executable, "-m", "kubeflow_controller_tpu.workloads.serve",
             "--synthetic", "--port", str(port), "--slots", "2"],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env)
        try:
            deadline = time.monotonic() + 30
            sock = None
            while time.monotonic() < deadline:
                try:
                    sock = socket.create_connection(("127.0.0.1", port),
                                                    timeout=0.2)
                    break
                except OSError:
                    time.sleep(0.1)
            assert sock is not None, proc.stderr.peek()[:500]
            f = sock.makefile("rwb")
            f.write(json.dumps({"id": "r1", "prompt": [1, 2, 3],
                                "max_new": 4}).encode() + b"\n")
            f.flush()
            resp = json.loads(f.readline())
            assert resp["id"] == "r1" and len(resp["tokens"]) == 4
            # SIGTERM mid-request: the in-flight request must complete
            # and the process must exit 0.
            f.write(json.dumps({"id": "r2", "prompt": [5],
                                "max_new": 50}).encode() + b"\n")
            f.flush()
            time.sleep(0.05)
            proc.send_signal(signal.SIGTERM)
            resp2 = json.loads(f.readline())
            assert resp2["id"] == "r2"
            assert len(resp2["tokens"]) == 50 and not resp2["error"]
            sock.close()
            assert proc.wait(timeout=30) == 0
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait()


def _free_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# ---------------------------------------------------------------------------
# Paged KV cache vs the dense oracle (models/generate.py)
# ---------------------------------------------------------------------------

@pytest.mark.slow
class TestPagedCache:
    def test_paged_decode_matches_generate(self):
        """Two staggered slots decoded through the paged pool reproduce
        the contiguous-cache generate() exactly (greedy)."""
        import jax
        import jax.numpy as jnp
        import numpy as np

        from kubeflow_controller_tpu.models.generate import (
            generate,
            init_paged_cache,
            paged_decode_step,
            paged_prefill,
        )
        from kubeflow_controller_tpu.models.llama import (
            LlamaConfig,
            llama_init,
        )

        cfg = LlamaConfig.tiny()
        params = llama_init(jax.random.PRNGKey(0), cfg)
        page = 8
        cache = init_paged_cache(cfg, num_pages=17, page_size=page)
        prompts = [[7, 3, 9, 11, 2], [5, 1, 4, 1, 5, 9, 2, 6, 5]]
        new_tokens = 6

        # Host-side page tables: slot 0 -> pages 1..8, slot 1 -> 9..16.
        tables = np.zeros((2, 8), np.int32)
        tables[0] = np.arange(1, 9)
        tables[1] = np.arange(9, 17)
        outs = [[], []]
        positions = []
        for b, prompt in enumerate(prompts):
            plen = len(prompt)
            bucket = 16
            toks = np.zeros((1, bucket), np.int32)
            toks[0, :plen] = prompt
            rows = np.zeros(bucket, np.int32)
            for j in range(bucket):
                if j < plen:
                    rows[j] = tables[b, j // page] * page + j % page
            logits, cache = paged_prefill(params, jnp.asarray(toks), cache,
                                          jnp.asarray(rows), plen, cfg)
            outs[b].append(int(jnp.argmax(logits)))
            positions.append(plen)
        for _ in range(new_tokens - 1):
            toks = jnp.asarray([outs[0][-1], outs[1][-1]], jnp.int32)
            logits, cache = paged_decode_step(
                params, toks, cache, jnp.asarray(positions, jnp.int32),
                jnp.asarray(tables), cfg, page)
            nxt = jnp.argmax(logits, axis=-1)
            for b in range(2):
                outs[b].append(int(nxt[b]))
                positions[b] += 1

        for b, prompt in enumerate(prompts):
            oracle = np.asarray(generate(
                params, jnp.asarray([prompt]), cfg,
                max_new_tokens=new_tokens))[0, len(prompt):]
            assert outs[b] == [int(x) for x in oracle], b

    def test_engine_matches_generate_oracle(self):
        """The full engine (admission, paging, bucketing) is greedy-exact
        against generate() for a batch of concurrent requests."""
        import jax
        import jax.numpy as jnp
        import numpy as np

        from kubeflow_controller_tpu.models.generate import generate
        from kubeflow_controller_tpu.models.llama import (
            LlamaConfig,
            llama_init,
        )
        from kubeflow_controller_tpu.workloads.serve import LlamaBackend

        cfg = LlamaConfig.tiny()
        params = llama_init(jax.random.PRNGKey(0), cfg)
        eng = mk_engine(slots=3, page_size=8, max_len=64,
                        backend=LlamaBackend(cfg, seed=0))
        rng = random.Random(23)
        reqs = [Request(id=str(i),
                        tokens=[rng.randrange(1, 250)
                                for _ in range(rng.randrange(2, 20))],
                        max_new_tokens=5) for i in range(7)]
        for r in reqs:
            eng.submit(r)
        for r in reqs:
            assert r.done.wait(120), r.id
        eng.stop()
        for r in reqs:
            oracle = np.asarray(generate(
                params, jnp.asarray([r.tokens]), cfg,
                max_new_tokens=5))[0, len(r.tokens):]
            assert r.output == [int(x) for x in oracle], r.id


# ---------------------------------------------------------------------------
# Typed intake verdicts (the gateway's routing contract)
# ---------------------------------------------------------------------------

class TestSubmitResult:
    def test_truthiness_and_reasons(self):
        """Truthiness == accepted, so pre-gateway ``if eng.submit(r)``
        call sites keep working; the reason tells the gateway whether to
        retry NOW (draining) or back off (overloaded)."""
        assert SUBMIT_OK and SUBMIT_OK.accepted
        assert not REFUSED_DRAINING
        assert REFUSED_DRAINING.reason == "draining"
        assert not REFUSED_OVERLOADED
        assert REFUSED_OVERLOADED.reason == "overloaded"

    def test_draining_and_stopped_refuse_with_draining_reason(self):
        eng = mk_engine(slots=1)
        eng.drain()
        res = eng.submit(Request(id="late", tokens=[1], max_new_tokens=1))
        assert not res and res.reason == "draining"
        eng.stop()
        res = eng.submit(Request(id="later", tokens=[1], max_new_tokens=1))
        assert not res and res.reason == "draining"

    def test_overloaded_refusal_at_max_queue(self):
        # Unstarted engine: intake is the only actor, so the max_queue
        # bound is exact and the test is race-free.
        eng = ServeEngine(SyntheticBackend(), ServeConfig(
            slots=1, page_size=8, max_len=32, prefill_buckets=(8, 16),
            max_queue=2, stats_window_s=2.0))
        reqs = [Request(id=str(i), tokens=[1], max_new_tokens=1)
                for i in range(3)]
        assert eng.submit(reqs[0])
        assert eng.submit(reqs[1])
        res = eng.submit(reqs[2])
        assert not res and res.reason == "overloaded"
        # The refused request is untouched: re-routable elsewhere.
        assert not reqs[2].done.is_set() and not reqs[2].error
        eng.stop()


# ---------------------------------------------------------------------------
# Cross-request prefix page sharing (refcounts + copy-on-write)
# ---------------------------------------------------------------------------

class TestPrefixSharing:
    def mk_prefix_engine(self, slots=3, page_size=8, max_len=64,
                         prefix=True, backend=None):
        eng = ServeEngine(
            backend or SyntheticBackend(),
            ServeConfig(slots=slots, page_size=page_size, max_len=max_len,
                        prefill_buckets=(8, 16, 32), cont_batch=True,
                        prefix_cache=prefix, stats_window_s=2.0))
        eng.start()
        assert eng.wait_ready(30)
        return eng

    def run_multiturn(self, eng, sessions=3, turns=4, seed=5):
        """Synchronous multi-turn conversations; each turn's prompt is the
        prior history (a known prefix) plus a few fresh tokens.  Prompts
        stay under the largest prefill bucket (32): past it the cold path
        truncates to the bucket while the prefix path extends the full
        tail, so identity is only promised inside the compiled shape set."""
        rng = random.Random(seed)
        hist = {s: [rng.randrange(1, 99) for _ in range(12)]
                for s in range(sessions)}
        outputs = {}
        for t in range(turns):
            batch = []
            for s in range(sessions):
                r = Request(id=f"s{s}-t{t}", tokens=list(hist[s]),
                            max_new_tokens=3, session=f"s{s}")
                assert eng.submit(r)
                batch.append((s, r))
            for s, r in batch:
                assert r.done.wait(30), r.id
                assert not r.error, (r.id, r.error)
                outputs[r.id] = list(r.output)
                hist[s] += r.output + [rng.randrange(1, 99)
                                       for _ in range(2)]
        return outputs

    def pool_size(self, eng):
        return eng.config.slots * eng.config.pages_per_slot()

    def assert_conserved(self, eng):
        """Every physical page is either free or refcounted — never both,
        never neither, no page leaked or double-freed."""
        with eng._lock:
            free = list(eng._free_pages)
            refs = dict(eng._page_refs)
        assert len(free) + len(refs) == self.pool_size(eng)
        assert not set(free) & set(refs)
        assert sorted(set(free) | set(refs)) == list(
            range(1, self.pool_size(eng) + 1))
        assert all(r >= 1 for r in refs.values())

    def test_sharing_is_token_identical_with_cache_off(self):
        """CoW + tail-extend over shared pages must be invisible in the
        outputs: the same multi-turn traffic through a prefix-cache
        engine and a cache-off engine decodes identical tokens."""
        on = self.mk_prefix_engine(prefix=True)
        off = self.mk_prefix_engine(prefix=False)
        try:
            got_on = self.run_multiturn(on, seed=5)
            got_off = self.run_multiturn(off, seed=5)
            assert got_on == got_off
            st = on.stats()
            assert st.prefix_hits > 0
            assert st.prefix_reused_tokens > 0
            assert off.stats().prefix_hits == 0
        finally:
            on.stop()
            off.stop()

    def test_refcount_conservation_under_concurrent_sessions(self):
        """Concurrent admit/evict/share churn on a small pool: after the
        dust settles every page must come home to exactly one owner."""
        eng = self.mk_prefix_engine(slots=3, page_size=8, max_len=48)
        errs = []

        def feeder(tid):
            rng = random.Random(200 + tid)
            hist = [tid + 1] * 14  # shared per-thread prefix
            for i in range(12):
                r = Request(id=f"{tid}-{i}", tokens=list(hist),
                            max_new_tokens=rng.randrange(1, 6),
                            session=f"t{tid}")
                if not eng.submit(r):
                    errs.append(r.id)
                    continue
                if not r.done.wait(30) or r.error:
                    errs.append((r.id, r.error))
                    continue
                hist += r.output + [rng.randrange(1, 99)]
                if len(hist) > 40:
                    hist = hist[:14]
                time.sleep(rng.random() * 0.002)

        threads = [threading.Thread(target=feeder, args=(t,))
                   for t in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(60)
        try:
            assert not errs, errs
            self.assert_conserved(eng)
            st = eng.stats()
            assert st.prefix_hits > 0  # sharing actually happened
            assert st.slots_used == 0 and st.queue_depth == 0
        finally:
            eng.stop()

    def test_eviction_never_frees_page_a_slot_still_maps(self):
        """Force a full trie eviction sweep while a live slot shares
        retained pages: the shared pages are pinned by the slot's ref and
        must survive; only trie-only (refcount-1) pages may free."""
        eng = self.mk_prefix_engine(slots=2, page_size=8, max_len=32,
                                    backend=SyntheticBackend(step_s=0.01))
        try:
            warm = Request(id="warm", tokens=[7] * 15, max_new_tokens=1,
                           session="a")
            assert eng.submit(warm)
            assert warm.done.wait(30) and not warm.error
            # Follow-up shares the retained pages and HOLDS the slot
            # (slow backend) while we run the eviction sweep.
            follow = Request(id="follow", tokens=[7] * 15 + [9, 9],
                             max_new_tokens=8, session="a")
            assert eng.submit(follow)

            def slot_pages():
                with eng._lock:
                    for s in eng._slots:
                        if s is not None and s.req.id == "follow":
                            return list(s.pages)
                return None

            deadline = time.monotonic() + 10
            pages = None
            while pages is None and time.monotonic() < deadline:
                pages = slot_pages()
                time.sleep(0.002)
            assert pages, "follow-up never admitted"
            with eng._lock:
                eng._evict_prefix_locked(shortfall=10 ** 6)
                free = set(eng._free_pages)
                refs = dict(eng._page_refs)
            assert not set(pages) & free, "evicted a live slot's page"
            assert all(refs.get(p, 0) >= 1 for p in pages)
            assert follow.done.wait(30) and not follow.error
            assert len(follow.output) == 8
            self.assert_conserved(eng)
        finally:
            eng.stop()

    @pytest.mark.slow
    def test_cow_divergent_tail_bit_exact_llama(self):
        """Mid-page divergence on a real model: request 2 shares request
        1's first page, CoW-copies the partially-matched second page, and
        decodes bit-exactly what a cache-off engine produces."""
        from kubeflow_controller_tpu.models.llama import LlamaConfig
        from kubeflow_controller_tpu.workloads.serve import LlamaBackend

        cfg = LlamaConfig.tiny()
        base = [11, 23, 5, 42, 77, 102, 9, 61, 88, 14, 3, 250]
        prompts = [base + [33, 71, 6, 120],          # fills 2 pages
                   base[:10] + [200, 201, 202, 203]]  # diverges mid-page-2

        def run(prefix_on):
            eng = self.mk_prefix_engine(
                slots=2, page_size=8, max_len=64, prefix=prefix_on,
                backend=LlamaBackend(cfg, seed=0))
            outs = []
            try:
                for i, toks in enumerate(prompts):
                    r = Request(id=f"p{i}", tokens=list(toks),
                                max_new_tokens=5)
                    assert eng.submit(r)
                    assert r.done.wait(120) and not r.error, r.id
                    outs.append(list(r.output))
                st = eng.stats()
            finally:
                eng.stop()
            return outs, st

        got_on, st_on = run(True)
        got_off, st_off = run(False)
        assert got_on == got_off
        assert st_on.prefix_hits >= 1
        assert st_on.cow_copies >= 1
        assert st_off.cow_copies == 0
