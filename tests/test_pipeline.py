"""Pipeline parallelism: gpipe schedule vs sequential oracle."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_controller_tpu.models import LlamaConfig, llama_forward, llama_init
from kubeflow_controller_tpu.models.llama import llama_forward_pp
from kubeflow_controller_tpu.parallel import MeshSpec, build_mesh
from kubeflow_controller_tpu.parallel.pipeline import gpipe, split_stages
from kubeflow_controller_tpu.parallel.compat import set_mesh as compat_set_mesh


class TestGPipe:
    def test_matches_sequential_linear_stack(self):
        """8 stacked linear layers through a 2-stage pipeline == sequential."""
        L, D = 8, 16
        key = jax.random.PRNGKey(0)
        w = jax.random.normal(key, (L, D, D)) * (D ** -0.5)
        params = {"w": w}
        x = jax.random.normal(jax.random.PRNGKey(1), (4, 6, D))  # 4 microbatches

        def stage_fn(stage, xm):
            out, _ = jax.lax.scan(
                lambda c, lw: (jnp.tanh(c @ lw), None), xm, stage["w"])
            return out

        seq, _ = jax.lax.scan(lambda c, lw: (jnp.tanh(c @ lw), None), x.reshape(24, D), w)

        mesh = build_mesh(MeshSpec(pp=2, fsdp=-1))
        stages = split_stages(params, 2)
        with compat_set_mesh(mesh):
            out = jax.jit(lambda s, xm: gpipe(stage_fn, s, xm, mesh))(stages, x)
        np.testing.assert_allclose(
            np.asarray(out.reshape(24, D)), np.asarray(seq), atol=1e-5, rtol=1e-5)

    def test_pp1_falls_back_to_vmap(self):
        mesh = build_mesh(MeshSpec(pp=1, fsdp=-1))
        w = jnp.eye(4)[None].repeat(2, 0)
        stages = split_stages({"w": w}, 1)
        x = jnp.ones((2, 3, 4))
        out = gpipe(lambda s, xm: jax.lax.scan(
            lambda c, lw: (c @ lw, None), xm, s["w"])[0], stages, x, mesh)
        np.testing.assert_allclose(np.asarray(out), np.asarray(x))

    def test_indivisible_layers_raise(self):
        with pytest.raises(ValueError):
            split_stages({"w": jnp.zeros((3, 4, 4))}, 2)


class Test1F1B:
    """1F1B fused forward/backward schedule vs direct autodiff."""

    def _setup(self, L=8, D=16, M=4):
        w = jax.random.normal(jax.random.PRNGKey(0), (L, D, D)) * (D ** -0.5)
        head = jax.random.normal(jax.random.PRNGKey(1), (D,))
        x = jax.random.normal(jax.random.PRNGKey(2), (M, 6, D))
        targets = jax.random.normal(jax.random.PRNGKey(3), (M, 6))

        def stage_fn(stage, xm):
            out, _ = jax.lax.scan(
                lambda c, lw: (jnp.tanh(c @ lw), None), xm, stage["w"])
            return out

        def loss_fn(lp, y, aux):
            pred = y @ lp["head"]
            return jnp.mean((pred - aux) ** 2)

        return {"w": w}, {"head": head}, x, targets, stage_fn, loss_fn

    def _reference(self, params, lp, x, targets, stage_fn, loss_fn):
        """Mean-over-microbatches loss differentiated directly."""

        def total(params, lp, x):
            def one(xm, aux):
                y, _ = jax.lax.scan(
                    lambda c, lw: (jnp.tanh(c @ lw), None), xm, params["w"])
                return loss_fn(lp, y, aux)

            return jnp.mean(jax.vmap(one)(x, targets))

        l, (gp, glp, gx) = jax.value_and_grad(total, argnums=(0, 1, 2))(
            params, lp, x)
        return l, gp, glp, gx

    @pytest.mark.parametrize("pp,n_stages", [(2, 2), (1, 1), (4, 4)])
    def test_grads_match_autodiff(self, pp, n_stages):
        from kubeflow_controller_tpu.parallel.pipeline import pipeline_1f1b

        params, lp, x, targets, stage_fn, loss_fn = self._setup()
        ref_l, ref_gp, ref_glp, ref_gx = self._reference(
            params, lp, x, targets, stage_fn, loss_fn)

        mesh = build_mesh(MeshSpec(pp=pp, fsdp=-1))
        stages = split_stages(params, n_stages)
        with compat_set_mesh(mesh):
            loss, gstage, gloss, gmicro = jax.jit(
                lambda s, lp, x, t: pipeline_1f1b(
                    stage_fn, s, x, loss_fn, lp, t, mesh)
            )(stages, lp, x, targets)

        np.testing.assert_allclose(float(loss), float(ref_l), rtol=1e-5)
        got_w = np.asarray(gstage["w"]).reshape(ref_gp["w"].shape)
        np.testing.assert_allclose(got_w, np.asarray(ref_gp["w"]),
                                   atol=1e-5, rtol=1e-4)
        np.testing.assert_allclose(np.asarray(gloss["head"]),
                                   np.asarray(ref_glp["head"]),
                                   atol=1e-5, rtol=1e-4)
        np.testing.assert_allclose(np.asarray(gmicro), np.asarray(ref_gx),
                                   atol=1e-5, rtol=1e-4)

    def test_more_microbatches_than_stages(self):
        from kubeflow_controller_tpu.parallel.pipeline import pipeline_1f1b

        params, lp, x, targets, stage_fn, loss_fn = self._setup(M=8)
        ref_l, ref_gp, _, _ = self._reference(
            params, lp, x, targets, stage_fn, loss_fn)
        mesh = build_mesh(MeshSpec(pp=2, fsdp=-1))
        stages = split_stages(params, 2)
        with compat_set_mesh(mesh):
            loss, gstage, _, _ = jax.jit(
                lambda s, lp, x, t: pipeline_1f1b(
                    stage_fn, s, x, loss_fn, lp, t, mesh)
            )(stages, lp, x, targets)
        np.testing.assert_allclose(float(loss), float(ref_l), rtol=1e-5)
        got_w = np.asarray(gstage["w"]).reshape(ref_gp["w"].shape)
        np.testing.assert_allclose(got_w, np.asarray(ref_gp["w"]),
                                   atol=1e-5, rtol=1e-4)


@pytest.mark.slow
class TestLlamaPipeline:
    def test_pp2_matches_dense_forward(self):
        cfg = LlamaConfig.tiny(remat=False)  # 2 layers -> 1 per stage
        params = llama_init(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, cfg.vocab_size)
        ref = llama_forward(params, tokens, cfg)
        mesh = build_mesh(MeshSpec(pp=2, fsdp=-1))
        with compat_set_mesh(mesh):
            out = jax.jit(
                lambda p, t: llama_forward_pp(p, t, cfg, mesh, n_microbatches=2)
            )(params, tokens)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-4, rtol=2e-4)

    def test_1f1b_matches_dense_grads(self):
        """Full-model 1F1B loss+grads == jax.grad of the dense llama_loss."""
        from kubeflow_controller_tpu.models.llama import llama_loss_and_grads_pp
        from kubeflow_controller_tpu.models import llama_loss

        cfg = LlamaConfig.tiny(remat=False)  # 2 layers, dense FFN
        params = llama_init(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(5), (4, 8), 0, cfg.vocab_size)
        ref_l, ref_g = jax.value_and_grad(
            lambda p: llama_loss(p, tokens, cfg))(params)

        mesh = build_mesh(MeshSpec(pp=2, fsdp=-1))
        with compat_set_mesh(mesh):
            loss, grads = jax.jit(
                lambda p, t: llama_loss_and_grads_pp(p, t, cfg, mesh,
                                                     n_microbatches=2)
            )(params, tokens)

        np.testing.assert_allclose(float(loss), float(ref_l), rtol=1e-4)
        for path in (("layers", "wq"), ("layers", "w_gate"), ("embed",),
                     ("final_norm",), ("lm_head",)):
            a, b = grads, ref_g
            for k in path:
                a, b = a[k], b[k]
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=5e-4, rtol=5e-3,
                err_msg="/".join(path))

    def test_1f1b_moe_matches_dense_grads(self):
        """MoE-under-pp: with one microbatch the 1F1B loss+grads equal
        jax.grad of the dense llama_loss INCLUDING the router aux/z
        penalties (advisor round-2: previously silently dropped)."""
        from kubeflow_controller_tpu.models.llama import llama_loss_and_grads_pp
        from kubeflow_controller_tpu.models import llama_loss

        cfg = LlamaConfig.tiny(remat=False, n_experts=4, moe_top_k=2)
        params = llama_init(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(5), (4, 8), 0, cfg.vocab_size)
        ref_l, ref_g = jax.value_and_grad(
            lambda p: llama_loss(p, tokens, cfg))(params)

        mesh = build_mesh(MeshSpec(pp=2, fsdp=-1))
        with compat_set_mesh(mesh):
            loss, grads = jax.jit(
                lambda p, t: llama_loss_and_grads_pp(p, t, cfg, mesh,
                                                     n_microbatches=1)
            )(params, tokens)

        np.testing.assert_allclose(float(loss), float(ref_l), rtol=1e-4)
        for path in (("layers", "router"), ("layers", "w_gate"),
                     ("layers", "wq"), ("lm_head",)):
            a, b = grads, ref_g
            for k in path:
                a, b = a[k], b[k]
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=5e-4, rtol=5e-3,
                err_msg="/".join(path))

    def test_1f1b_grouped_moe_under_pp_no_fallback(self):
        """Round-5 (VERDICT item 6): dropless grouped MoE composes with
        pipeline parallelism — the 1F1B stage body is manual over pp and
        the grouped Pallas region nests inside it manual over (ep, fsdp,
        ...).  Any einsum fallback warning fails the test; grads must
        match the non-pp grouped oracle."""
        import warnings

        from kubeflow_controller_tpu.models import llama_loss
        from kubeflow_controller_tpu.models.llama import llama_loss_and_grads_pp

        # dim/intermediate at the 128 tiling grain so the grouped path is
        # eligible (tiny's dim=64 would legitimately fall back).
        cfg = LlamaConfig.tiny(remat=False, n_experts=4, moe_top_k=2,
                               dim=128, n_heads=4, n_kv_heads=2,
                               moe_dispatch="grouped")
        params = llama_init(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(5), (4, 8), 0,
                                    cfg.vocab_size)
        ref_l, ref_g = jax.value_and_grad(
            lambda p: llama_loss(p, tokens, cfg))(params)  # non-pp grouped

        mesh = build_mesh(MeshSpec(pp=2, ep=2, fsdp=2))
        with compat_set_mesh(mesh):
            with warnings.catch_warnings():
                warnings.filterwarnings(
                    "error", message=".*moe dispatch='grouped' cannot run.*")
                loss, grads = jax.jit(
                    lambda p, t: llama_loss_and_grads_pp(p, t, cfg, mesh,
                                                         n_microbatches=1)
                )(params, tokens)

        np.testing.assert_allclose(float(loss), float(ref_l), rtol=1e-4)
        for path in (("layers", "router"), ("layers", "w_gate"),
                     ("layers", "w_down"), ("layers", "wq"), ("lm_head",)):
            a, b = grads, ref_g
            for k in path:
                a, b = a[k], b[k]
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=5e-4, rtol=5e-3,
                err_msg="/".join(path))

    def test_1f1b_moe_router_gets_balancing_gradient(self):
        """With multiple microbatches the router still receives a nonzero
        load-balancing gradient through the pipeline schedule."""
        from kubeflow_controller_tpu.models.llama import llama_loss_and_grads_pp

        cfg = LlamaConfig.tiny(remat=False, n_experts=4, moe_top_k=2)
        params = llama_init(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(6), (4, 8), 0, cfg.vocab_size)
        mesh = build_mesh(MeshSpec(pp=2, fsdp=-1))
        with compat_set_mesh(mesh):
            loss, grads = jax.jit(
                lambda p, t: llama_loss_and_grads_pp(p, t, cfg, mesh,
                                                     n_microbatches=2)
            )(params, tokens)
        assert float(loss) > 0
        assert float(jnp.linalg.norm(grads["layers"]["router"])) > 0

    def test_gpipe_moe_forward_returns_aux(self):
        """GPipe forward threads router stats; with one microbatch they
        equal the non-pp forward's aux exactly."""
        cfg = LlamaConfig.tiny(remat=False, n_experts=4, moe_top_k=2)
        params = llama_init(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(7), (4, 8), 0, cfg.vocab_size)
        ref_logits, ref_aux = llama_forward(params, tokens, cfg, return_aux=True)
        mesh = build_mesh(MeshSpec(pp=2, fsdp=-1))
        with compat_set_mesh(mesh):
            out, aux = jax.jit(
                lambda p, t: llama_forward_pp(p, t, cfg, mesh,
                                              n_microbatches=1,
                                              return_aux=True)
            )(params, tokens)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref_logits),
                                   atol=2e-4, rtol=2e-4)
        for k in ("aux_loss", "z_loss", "overflow_frac"):
            np.testing.assert_allclose(float(aux[k]), float(ref_aux[k]),
                                       rtol=1e-5, atol=1e-6, err_msg=k)

    def test_pp2_grads_flow(self):
        cfg = LlamaConfig.tiny(remat=False)
        params = llama_init(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(2), (4, 8), 0, cfg.vocab_size)
        mesh = build_mesh(MeshSpec(pp=2, fsdp=-1))

        def loss(p):
            logits = llama_forward_pp(p, tokens, cfg, mesh, n_microbatches=2)
            logp = jax.nn.log_softmax(logits[:, :-1])
            return -jnp.mean(jnp.take_along_axis(logp, tokens[:, 1:, None], axis=-1))

        with compat_set_mesh(mesh):
            l, g = jax.jit(jax.value_and_grad(loss))(params)
        assert float(l) > 0
        gnorm = float(jnp.linalg.norm(g["layers"]["wq"]))
        assert gnorm > 0