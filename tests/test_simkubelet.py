"""Simulated-kubelet equivalence suite (ISSUE 14).

The event-driven ``SimKubelet`` must be observably indistinguishable from
the threaded ``FakeKubelet`` for simulated pods: same phase sequences, same
job conditions, same progress beats, same stall-injection behavior, same
gang-admission semantics — it only changes *how many threads* produce them.
Every scenario here runs once per kubelet class and compares the observable
stream, plus one direct structural gate: thread count stays O(1) in pod
count.
"""

import threading
import time

import pytest

from kubeflow_controller_tpu.api.core import (
    Container,
    PHASE_FAILED,
    PHASE_RUNNING,
    PHASE_SUCCEEDED,
    Pod,
    PodTemplateSpec,
    ResourceRequirements,
)
from kubeflow_controller_tpu.api.labels import (
    ANNOTATION_GANG_NAME,
    ANNOTATION_GANG_SIZE,
    LABEL_JOB_TYPE,
)
from kubeflow_controller_tpu.api.meta import ObjectMeta
from kubeflow_controller_tpu.api.tfjob import (
    ReplicaType,
    TFJob,
    TFJobPhase,
    TFReplicaSpec,
)
from kubeflow_controller_tpu.checker import StallPolicy
from kubeflow_controller_tpu.cluster import (
    Cluster,
    FakeKubelet,
    PhasePolicy,
    SimKubelet,
    TPUInventory,
    TPUSlice,
)
from kubeflow_controller_tpu.cluster.store import MODIFIED
from kubeflow_controller_tpu.controller import Controller

KUBELETS = [FakeKubelet, SimKubelet]


def mk_pod(name, ns="default", labels=None, annotations=None, tpu=False):
    pod = Pod(metadata=ObjectMeta(name=name, namespace=ns))
    pod.metadata.labels = labels or {}
    pod.metadata.annotations = annotations or {}
    c = Container(name="main")
    if tpu:
        c.resources = ResourceRequirements(requests={"google.com/tpu": "4"})
    pod.spec.containers.append(c)
    return pod


def wait_for(fn, timeout=10.0, interval=0.01, what="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        v = fn()
        if v:
            return v
        time.sleep(interval)
    raise AssertionError(f"{what} not met within {timeout}s")


def build(kubelet_cls, cluster, policy, inventory=None):
    if kubelet_cls is FakeKubelet:
        return FakeKubelet(cluster, policy=policy, inventory=inventory)
    return SimKubelet(cluster, policy=policy, inventory=inventory)


def phase_stream(cluster):
    """A pods watch started before the kubelet: collects each pod's phase
    transition sequence (dedup'd on change)."""
    w = cluster.store.watch("pods")
    seqs = {}

    def drain():
        for ev in w.next_batch(max_n=512, timeout=0):
            if ev.type != MODIFIED:
                continue
            name = ev.object.metadata.name
            seq = seqs.setdefault(name, [])
            if not seq or seq[-1] != ev.object.status.phase:
                seq.append(ev.object.status.phase)
    return w, seqs, drain


class TestPhaseEquivalence:
    """Direct-pod scenarios: identical phase sequences per pod."""

    def run_scenario(self, kubelet_cls, policy, pods):
        cluster = Cluster()
        w, seqs, drain = phase_stream(cluster)
        kubelet = build(kubelet_cls, cluster, policy)
        kubelet.start()
        try:
            for p in pods:
                cluster.pods.create(p)
            deadline = time.time() + 10.0
            while time.time() < deadline:
                drain()
                live = {p.metadata.name: cluster.pods.get(
                    "default", p.metadata.name) for p in pods}
                if all(lp.status.phase in (PHASE_SUCCEEDED, PHASE_FAILED)
                       or lp.metadata.labels.get(LABEL_JOB_TYPE) == "ps"
                       and lp.status.phase == PHASE_RUNNING
                       for lp in live.values()):
                    break
                time.sleep(0.01)
            time.sleep(0.1)
            drain()
        finally:
            kubelet.stop()
            w.stop()
        return seqs

    def test_success_failure_and_run_forever_sequences_match(self):
        def pods():
            return [
                mk_pod("w0", labels={LABEL_JOB_TYPE: "worker"}),
                mk_pod("w1", labels={LABEL_JOB_TYPE: "worker"}),
                mk_pod("ps0", labels={LABEL_JOB_TYPE: "ps"}),
                mk_pod("boom", labels={LABEL_JOB_TYPE: "worker"}),
            ]

        results = {}
        for cls in KUBELETS:
            policy = PhasePolicy(run_s=0.05, run_forever_types=("ps",),
                                 fail_once={"boom"})
            results[cls.__name__] = self.run_scenario(cls, policy, pods())
        fake, sim = results["FakeKubelet"], results["SimKubelet"]
        assert fake == sim
        assert sim["w0"] == [PHASE_RUNNING, PHASE_SUCCEEDED]
        assert sim["ps0"] == [PHASE_RUNNING]
        assert sim["boom"] == [PHASE_RUNNING, PHASE_FAILED]

    def test_per_job_run_override_applies(self):
        for cls in KUBELETS:
            policy = PhasePolicy(run_s=0.02,
                                 run_s_by_job={"slow": 0.3})
            cluster = Cluster()
            kubelet = build(cls, cluster, policy)
            kubelet.start()
            try:
                cluster.pods.create(mk_pod(
                    "fast", labels={LABEL_JOB_TYPE: "worker",
                                    "tf_job_name": "fast"}))
                cluster.pods.create(mk_pod(
                    "slow", labels={LABEL_JOB_TYPE: "worker",
                                    "tf_job_name": "slow"}))
                wait_for(lambda: cluster.pods.get(
                    "default", "fast").status.phase == PHASE_SUCCEEDED,
                    what=f"{cls.__name__} fast pod done")
                assert cluster.pods.get(
                    "default", "slow").status.phase == PHASE_RUNNING
                wait_for(lambda: cluster.pods.get(
                    "default", "slow").status.phase == PHASE_SUCCEEDED,
                    what=f"{cls.__name__} slow pod done")
            finally:
                kubelet.stop()

    def test_chaos_kill_flips_running_pod_to_failed(self):
        for cls in KUBELETS:
            cluster = Cluster()
            kubelet = build(cls, cluster, PhasePolicy(run_s=5.0))
            kubelet.start()
            try:
                cluster.pods.create(mk_pod(
                    "victim", labels={LABEL_JOB_TYPE: "worker"}))
                wait_for(lambda: cluster.pods.get(
                    "default", "victim").status.phase == PHASE_RUNNING,
                    what=f"{cls.__name__} victim running")
                assert kubelet.chaos_kill("default", "victim") == "simulated"
                pod = cluster.pods.get("default", "victim")
                assert pod.status.phase == PHASE_FAILED
                assert "ChaosKill" in pod.status.reason
                # The injected-failure path suppresses the in-place
                # outcome: the phase must STAY Failed past the run clock.
                time.sleep(0.3)
                assert cluster.pods.get(
                    "default", "victim").status.phase == PHASE_FAILED
            finally:
                kubelet.stop()


class TestProgressEquivalence:
    """Heartbeat beats + stall injection behave identically."""

    def test_beats_advance_and_suspend_stalls(self):
        steps = {}
        for cls in KUBELETS:
            cluster = Cluster()
            kubelet = build(cls, cluster,
                            PhasePolicy(run_s=30.0, heartbeat_s=0.02))
            kubelet.start()
            try:
                cluster.pods.create(mk_pod(
                    "t0", labels={LABEL_JOB_TYPE: "worker"}))

                def step():
                    p = cluster.pods.get("default", "t0")
                    return (p.status.progress.step
                            if p.status.progress else 0)
                wait_for(lambda: step() >= 3,
                         what=f"{cls.__name__} beats advancing")
                kubelet.suspend_heartbeats()
                time.sleep(0.1)
                frozen = step()
                time.sleep(0.2)
                assert step() == frozen, f"{cls.__name__} beat while suspended"
                kubelet.resume_heartbeats()
                wait_for(lambda: step() > frozen,
                         what=f"{cls.__name__} beats resumed")
                steps[cls.__name__] = True
            finally:
                kubelet.stop()
        assert steps == {"FakeKubelet": True, "SimKubelet": True}


class TestGangEquivalence:
    """TPU gang admission: all-or-nothing, capacity-ordered, reaped."""

    def gang_pods(self, gang, n):
        out = []
        for i in range(n):
            out.append(mk_pod(
                f"{gang}-{i}", tpu=True,
                labels={LABEL_JOB_TYPE: "tpu"},
                annotations={ANNOTATION_GANG_NAME: gang,
                             ANNOTATION_GANG_SIZE: str(n)}))
        return out

    def test_gang_all_or_nothing_then_second_gang_admits(self):
        for cls in KUBELETS:
            cluster = Cluster()
            inv = TPUInventory([TPUSlice("slice-0", "v5e-8")])
            kubelet = build(cls, cluster, PhasePolicy(run_s=0.15),
                            inventory=inv)
            kubelet.start()
            try:
                # Incomplete gang: one member offered, nothing admits.
                g1 = self.gang_pods("g1", 2)
                cluster.pods.create(g1[0])
                time.sleep(0.15)
                assert cluster.pods.get(
                    "default", "g1-0").status.phase != PHASE_RUNNING
                # Second member completes the gang: both run, then succeed.
                cluster.pods.create(g1[1])
                for p in ("g1-0", "g1-1"):
                    wait_for(lambda p=p: cluster.pods.get(
                        "default", p).status.phase == PHASE_SUCCEEDED,
                        what=f"{cls.__name__} {p} done")
                # A second gang needs the slice back (idle reap, ~1s):
                for p in self.gang_pods("g2", 2):
                    cluster.pods.create(p)
                for p in ("g2-0", "g2-1"):
                    wait_for(lambda p=p: cluster.pods.get(
                        "default", p).status.phase == PHASE_SUCCEEDED,
                        timeout=15.0, what=f"{cls.__name__} {p} done")
            finally:
                kubelet.stop()


class TestControllerEquivalence:
    """End-to-end through the controller: same terminal status shape."""

    def mk_job(self, name):
        job = TFJob(metadata=ObjectMeta(name=name, namespace="default"))
        for typ, n in ((ReplicaType.PS, 1), (ReplicaType.WORKER, 2)):
            t = PodTemplateSpec()
            t.spec.containers.append(Container(name="tensorflow",
                                               image="img"))
            t.spec.restart_policy = "OnFailure"
            job.spec.tf_replica_specs.append(
                TFReplicaSpec(replicas=n, tf_replica_type=typ, template=t))
        return job

    def terminal_shape(self, kubelet_cls):
        cluster = Cluster()
        kubelet = build(kubelet_cls, cluster, PhasePolicy(run_s=0.05))
        ctrl = Controller(cluster, resync_period_s=1.0)
        kubelet.start()
        ctrl.run(threadiness=2)
        try:
            cluster.tfjobs.create(self.mk_job("eq"))
            wait_for(lambda: cluster.tfjobs.get(
                "default", "eq").status.phase == TFJobPhase.SUCCEEDED,
                timeout=15.0, what=f"{kubelet_cls.__name__} job Succeeded")
            job = cluster.tfjobs.get("default", "eq")
            conds = sorted((c.type.value, c.status, c.reason)
                           for c in job.status.conditions)
            replicas = sorted(
                (r.type.value, r.state.value,
                 tuple(sorted(f"{k.value}={v}"
                              for k, v in r.tf_replicas_states.items())))
                for r in job.status.tf_replica_statuses)
            return job.status.phase.value, conds, replicas
        finally:
            ctrl.stop()
            kubelet.stop()

    def test_job_terminal_status_matches(self):
        fake = self.terminal_shape(FakeKubelet)
        sim = self.terminal_shape(SimKubelet)
        assert fake == sim

    def test_stall_detection_fires_under_simkubelet(self):
        """The stall-smoke scenario on the event-driven kubelet: suspend
        beats -> TrainingStalled; resume -> TrainingResumed."""
        cluster = Cluster()
        kubelet = SimKubelet(cluster, policy=PhasePolicy(run_s=60.0,
                                                         heartbeat_s=0.05))
        ctrl = Controller(cluster, resync_period_s=5.0,
                          stall_policy=StallPolicy(heartbeat_deadline_s=0.4,
                                                   step_deadline_s=0.0,
                                                   check_interval_s=0.1))
        kubelet.start()
        ctrl.run(threadiness=2)
        try:
            cluster.tfjobs.create(self.mk_job("stall"))
            wait_for(lambda: (cluster.tfjobs.get("default", "stall")
                              .status.progress or None) is not None
                     and cluster.tfjobs.get("default",
                                            "stall").status.progress.step > 0,
                     timeout=15.0, what="progress flowing")
            kubelet.suspend_heartbeats()
            wait_for(lambda: any(
                e.reason == "TrainingStalled"
                for e in ctrl.recorder.events_for("default", "stall")),
                timeout=15.0, what="TrainingStalled event")
            kubelet.resume_heartbeats()
            wait_for(lambda: any(
                e.reason == "TrainingResumed"
                for e in ctrl.recorder.events_for("default", "stall")),
                timeout=15.0, what="TrainingResumed event")
        finally:
            ctrl.stop()
            kubelet.stop()


class TestThreadEnvelope:
    """The structural point of the tentpole: O(1) threads in pod count."""

    @pytest.mark.slow
    def test_simkubelet_thread_count_flat_at_hundreds_of_pods(self):
        cluster = Cluster()
        kubelet = SimKubelet(cluster, policy=PhasePolicy(run_s=0.5))
        before = threading.active_count()
        kubelet.start()
        try:
            for i in range(300):
                cluster.pods.create(mk_pod(
                    f"p{i:03d}", labels={LABEL_JOB_TYPE: "worker"}))
            wait_for(lambda: sum(
                1 for p in cluster.pods.list()
                if p.status.phase == PHASE_RUNNING) >= 200,
                timeout=20.0, what="pods running")
            # One loop thread, regardless of pod count.
            assert threading.active_count() <= before + 2
            wait_for(lambda: all(
                p.status.phase == PHASE_SUCCEEDED
                for p in cluster.pods.list()),
                timeout=30.0, what="all pods done")
        finally:
            kubelet.stop()

    def test_simkubelet_single_loop_thread(self):
        cluster = Cluster()
        kubelet = SimKubelet(cluster, policy=PhasePolicy(run_s=0.2))
        before = threading.active_count()
        kubelet.start()
        try:
            for i in range(40):
                cluster.pods.create(mk_pod(
                    f"p{i:02d}", labels={LABEL_JOB_TYPE: "worker"}))
            time.sleep(0.1)
            assert threading.active_count() <= before + 2
        finally:
            kubelet.stop()
