"""Zero-dependency line coverage via sys.monitoring (PEP 669).

The build image has no pytest-cov/coverage.py and installs are not possible
(CI has the real tools; `make cov` uses them there).  This measures the same
quantity locally so the CI floor can be SET from a measurement instead of a
guess: LINE events over files under the package root, each line disabled
after first hit (near-zero steady-state overhead), denominator = the line
table of the compiled module (what coverage.py calls executable lines).

Usage:  python -m tests._linecov tests/ [pytest args...]
Prints per-file and total percentages, worst files first.
"""

from __future__ import annotations

import os
import sys
from types import CodeType

PKG = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                   "kubeflow_controller_tpu")

_hits: dict = {}


def _executable_lines(path: str) -> set:
    with open(path, encoding="utf-8") as fh:
        src = fh.read()
    try:
        code = compile(src, path, "exec")
    except SyntaxError:
        return set()
    lines: set = set()
    stack = [code]
    while stack:
        c = stack.pop()
        for _s, _e, ln in c.co_lines():
            if ln:
                lines.add(ln)
        stack.extend(k for k in c.co_consts if isinstance(k, CodeType))
    return lines


def _on_line(code: CodeType, line: int):
    f = code.co_filename
    if f.startswith(PKG):
        _hits.setdefault(f, set()).add(line)
    return sys.monitoring.DISABLE


def start() -> None:
    if not hasattr(sys, "monitoring"):
        raise SystemExit(
            "tests/_linecov.py needs Python 3.12+ (sys.monitoring); on older "
            "interpreters install pytest-cov and use `make cov` instead")
    mon = sys.monitoring
    mon.use_tool_id(mon.COVERAGE_ID, "linecov")
    mon.register_callback(mon.COVERAGE_ID, mon.events.LINE, _on_line)
    mon.set_events(mon.COVERAGE_ID, mon.events.LINE)


def report() -> float:
    rows = []
    tot_hit = tot_all = 0
    for root, _dirs, files in os.walk(PKG):
        if "__pycache__" in root:
            continue
        for fn in files:
            if not fn.endswith(".py"):
                continue
            path = os.path.join(root, fn)
            exe = _executable_lines(path)
            if not exe:
                continue
            hit = _hits.get(path, set()) & exe
            rows.append((len(hit) / len(exe), path, len(hit), len(exe)))
            tot_hit += len(hit)
            tot_all += len(exe)
    rows.sort()
    for frac, path, h, n in rows:
        print(f"{frac * 100:6.1f}%  {h:5d}/{n:<5d}  "
              f"{os.path.relpath(path, os.path.dirname(PKG))}")
    pct = 100.0 * tot_hit / max(tot_all, 1)
    print(f"TOTAL {pct:.2f}%  ({tot_hit}/{tot_all} lines)")
    return pct


def main() -> int:
    import pytest

    start()
    rc = pytest.main(sys.argv[1:] or ["tests/", "-q"])
    report()
    return rc


if __name__ == "__main__":
    sys.exit(main())
