"""Time-to-first-step pipeline tests: cache-key fingerprint stability,
serialized-executable reuse across sequential fits, overlap-vs-serial
bit-equivalence, the compile-phase heartbeat's journey to TFJobStatus,
stall-detector interaction, rendezvous readiness, and per-process dataset
memoization."""

from __future__ import annotations

import os
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import jax
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from kubeflow_controller_tpu.workloads import compile_cache as cc
from kubeflow_controller_tpu.workloads import data as d
from kubeflow_controller_tpu.workloads.progress import ProgressReporter, drop_filename
from kubeflow_controller_tpu.workloads.runtime import (
    ENV_RENDEZVOUS_DIR,
    HostSetup,
    JobRuntime,
)


# ---------------------------------------------------------------------------
# Fingerprint
# ---------------------------------------------------------------------------

class TestFingerprint:
    def test_stable_and_order_independent(self):
        a = cc.fingerprint(model="mlp", bs=96, dp=2)
        assert a == cc.fingerprint(model="mlp", bs=96, dp=2)
        assert a == cc.fingerprint(dp=2, bs=96, model="mlp")
        assert len(a) == 20
        assert all(ch in "0123456789abcdef" for ch in a)

    def test_shape_change_is_a_different_key(self):
        base = cc.fingerprint(model="mlp", bs=96, dp=2, dtype="float32")
        assert base != cc.fingerprint(model="mlp", bs=128, dp=2, dtype="float32")
        assert base != cc.fingerprint(model="mlp", bs=96, dp=4, dtype="float32")
        assert base != cc.fingerprint(model="mlp", bs=96, dp=2, dtype="bfloat16")

    def test_stable_across_processes(self):
        # hash() is salted per process; the fingerprint must not be.  A
        # subprocess with a pinned, different PYTHONHASHSEED must agree
        # with this process.
        code = ("from kubeflow_controller_tpu.workloads.compile_cache "
                "import fingerprint; "
                "print(fingerprint(model='mlp', bs=96, lr=5e-3))")
        out = subprocess.run(
            [sys.executable, "-c", code],
            env={**os.environ, "PYTHONHASHSEED": "12345",
                 "JAX_PLATFORMS": "cpu"},
            capture_output=True, text=True, cwd=REPO, timeout=120)
        assert out.returncode == 0, out.stderr
        assert out.stdout.strip() == cc.fingerprint(model="mlp", bs=96, lr=5e-3)


# ---------------------------------------------------------------------------
# AOT compile + serialized-executable reuse
# ---------------------------------------------------------------------------

def _hit_miss():
    from kubeflow_controller_tpu.obs.metrics import REGISTRY

    return (REGISTRY.counter("kctpu_compile_cache_hits_total", "").value,
            REGISTRY.counter("kctpu_compile_cache_misses_total", "").value)


class TestAOTCompile:
    def test_miss_then_hit_with_metrics_and_span(self, tmp_path):
        from kubeflow_controller_tpu.obs.trace import TRACER

        jitted = jax.jit(lambda x: x * 2.0 + 1.0)
        abstract = (jax.ShapeDtypeStruct((8,), np.float32),)
        key = cc.fingerprint(test="aot-roundtrip", n=8)
        h0, m0 = _hit_miss()
        r1 = cc.aot_compile(jitted, abstract, key=key,
                            cache_dir=str(tmp_path), what="t")
        assert r1.source == "compiled"
        assert os.path.exists(r1.path)
        r2 = cc.aot_compile(jitted, abstract, key=key,
                            cache_dir=str(tmp_path), what="t")
        assert r2.source == "cache-hit"
        h1, m1 = _hit_miss()
        assert (h1 - h0, m1 - m0) == (1, 1)
        # Both executables compute the same thing.
        x = np.arange(8, dtype=np.float32)
        assert np.array_equal(np.asarray(r1.compiled(x)),
                              np.asarray(r2.compiled(x)))
        spans = [s for s in TRACER.spans("workload/compile")
                 if s.args.get("key") == key]
        assert {s.args.get("source") for s in spans} == {"cache-hit", "compiled"}

    def test_shape_change_misses(self, tmp_path):
        jitted = jax.jit(lambda x: x * 3.0)
        k8 = cc.fingerprint(test="shape", n=8)
        k16 = cc.fingerprint(test="shape", n=16)
        cc.aot_compile(jitted, (jax.ShapeDtypeStruct((8,), np.float32),),
                       key=k8, cache_dir=str(tmp_path), what="t")
        r = cc.aot_compile(jitted, (jax.ShapeDtypeStruct((16,), np.float32),),
                           key=k16, cache_dir=str(tmp_path), what="t")
        assert r.source == "compiled"  # a new shape never reuses the old key
        assert cc.cache_entries(str(tmp_path))["aot"] == 2

    def test_corrupt_entry_falls_back_to_compile(self, tmp_path):
        jitted = jax.jit(lambda x: x - 1.0)
        key = cc.fingerprint(test="corrupt")
        r1 = cc.aot_compile(jitted, (jax.ShapeDtypeStruct((4,), np.float32),),
                            key=key, cache_dir=str(tmp_path), what="t")
        with open(r1.path, "wb") as fh:
            fh.write(b"not a pickle")
        r2 = cc.aot_compile(jitted, (jax.ShapeDtypeStruct((4,), np.float32),),
                            key=key, cache_dir=str(tmp_path), what="t")
        assert r2.source == "compiled"
        assert np.allclose(np.asarray(r2.compiled(np.ones(4, np.float32))),
                           np.zeros(4))


class TestSequentialFits:
    """The satellite's cross-process reuse story: two sequential
    single-host fits against one cache dir — the second loads the first's
    serialized executable instead of compiling (the same file-level
    mechanism a NEW process uses, exercised here without paying a second
    interpreter+jax boot)."""

    def _run(self, cache, model_dir=None, extra=()):
        from kubeflow_controller_tpu.workloads import mnist_dist

        env = {"KCTPU_COMPILE_CACHE": cache}
        if model_dir:
            env["MODEL_DIR"] = model_dir
        old = {k: os.environ.get(k) for k in env}
        os.environ.update(env)
        try:
            rc = mnist_dist.main([
                "--platform", "cpu", "--step-loop", "--steps", "6",
                "--batch-size", "32", "--train-size", "512",
                "--eval-size", "256", *extra])
        finally:
            for k, v in old.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
        assert rc == 0

    def test_second_fit_is_a_cache_hit(self, tmp_path):
        cache = str(tmp_path / "cache")
        h0, m0 = _hit_miss()
        self._run(cache)
        h1, m1 = _hit_miss()
        assert m1 - m0 >= 1 and h1 - h0 == 0  # cold: compiled, no hit
        self._run(cache)
        h2, m2 = _hit_miss()
        assert h2 - h1 >= 1 and m2 - m1 == 0  # warm: hit, zero new misses

    def test_overlap_and_serial_paths_are_bit_identical(self, tmp_path):
        from kubeflow_controller_tpu.models import mnist as m
        from kubeflow_controller_tpu.workloads.checkpoint import CheckpointManager
        from kubeflow_controller_tpu.workloads.trainer import (
            default_optimizer,
            numpy_opt_state,
        )

        target_p = m.mlp_init(0)
        target_s = numpy_opt_state(default_optimizer(5e-3), target_p)
        outs = {}
        for mode, extra in (("overlap", ()), ("serial", ("--no-overlap",))):
            mdir = str(tmp_path / f"model-{mode}")
            self._run(str(tmp_path / f"cache-{mode}"), model_dir=mdir,
                      extra=extra)
            params, _, step = CheckpointManager(mdir).restore(target_p, target_s)
            outs[mode] = (step, params)
        assert outs["overlap"][0] == outs["serial"][0]
        a, b = outs["overlap"][1], outs["serial"][1]
        assert sorted(a) == sorted(b)
        for k in a:
            assert np.asarray(a[k]).tobytes() == np.asarray(b[k]).tobytes(), k


# ---------------------------------------------------------------------------
# Compile phase vs the stall detector + the progress plane
# ---------------------------------------------------------------------------

class TestCompilePhaseStall:
    def _policy(self):
        from kubeflow_controller_tpu.checker import StallPolicy, StallTracker

        return StallTracker(StallPolicy(heartbeat_deadline_s=10.0,
                                        step_deadline_s=10.0))

    def test_compile_phase_holds_the_frozen_step_deadline(self):
        from kubeflow_controller_tpu.api.core import PodProgress

        tr = self._policy()
        t0 = 1000.0
        assert not tr.observe("k", PodProgress(step=0, phase="compile",
                                               timestamp=t0), now=t0)
        # Way past the step deadline, step frozen at 0 — but the replica
        # says it is compiling and its keepalive keeps beats fresh.
        for dt in (8.0, 16.0, 24.0):
            assert not tr.observe(
                "k", PodProgress(step=0, phase="compile", timestamp=t0 + dt),
                now=t0 + dt)
        # Compile ends; the advancement clock starts from the LAST compile
        # beat, not from step-0's first sighting.
        assert not tr.observe("k", PodProgress(step=0, phase="fit",
                                               timestamp=t0 + 30), now=t0 + 30)
        # A genuine post-compile freeze still trips the deadline.
        assert tr.observe("k", PodProgress(step=0, phase="fit",
                                           timestamp=t0 + 41), now=t0 + 41)

    def test_heartbeat_deadline_still_applies_while_compiling(self):
        from kubeflow_controller_tpu.api.core import PodProgress

        tr = self._policy()
        t0 = 1000.0
        # Beats STOPPED mid-compile (process died): stalled regardless of
        # the claimed phase.
        assert tr.observe("k", PodProgress(step=0, phase="compile",
                                           timestamp=t0), now=t0 + 11)

    def test_compile_beat_reaches_job_progress(self):
        from kubeflow_controller_tpu.api.core import (
            PHASE_RUNNING,
            Pod,
            PodProgress,
        )
        from kubeflow_controller_tpu.api.meta import ObjectMeta
        from kubeflow_controller_tpu.api.tfjob import (
            ReplicaType,
            TFJob,
            TFReplicaSpec,
        )
        from kubeflow_controller_tpu.api.labels import LABEL_INDEX
        from kubeflow_controller_tpu.planner.materialize import labels_for
        from kubeflow_controller_tpu.updater.status import compute_progress

        job = TFJob(metadata=ObjectMeta(name="j", namespace="default"))
        job.spec.tf_replica_specs = [
            TFReplicaSpec(replicas=1, tf_replica_type=ReplicaType.WORKER)]
        pod = Pod(metadata=ObjectMeta(name="j-worker-0", namespace="default"))
        pod.metadata.labels = {**labels_for(job, ReplicaType.WORKER),
                               LABEL_INDEX: "0"}
        pod.status.phase = PHASE_RUNNING
        pod.status.progress = PodProgress(step=0, phase="compile",
                                          timestamp=time.time())
        p = compute_progress(job, {ReplicaType.WORKER: [pod]})
        assert p is not None and p.replicas[0].phase == "compile"
        # ... and the executable provenance rides the same plane.
        pod.status.progress = PodProgress(step=1, phase="fit",
                                          compile_source="cache-hit",
                                          timestamp=time.time())
        p = compute_progress(job, {ReplicaType.WORKER: [pod]})
        assert p.replicas[0].compile_source == "cache-hit"


class TestReporterCompiling:
    def test_compiling_beats_phase_and_keepalive(self, tmp_path):
        import json

        rep = ProgressReporter(namespace="ns", name="pod-0",
                               drop_dir=str(tmp_path))
        path = tmp_path / drop_filename("ns", "pod-0")
        with rep.compiling(interval_s=0.05):
            body = json.loads(path.read_text())
            assert body["phase"] == "compile"
            assert rep._keepalive is not None
            m0 = path.stat().st_mtime_ns
            deadline = time.time() + 5
            while path.stat().st_mtime_ns == m0 and time.time() < deadline:
                time.sleep(0.02)
            assert path.stat().st_mtime_ns > m0  # keepalive re-drops
        assert rep._keepalive is None
        rep.beat(phase="fit", compile_source="cache-hit")
        body = json.loads(path.read_text())
        assert body["phase"] == "fit"
        assert body["compileSource"] == "cache-hit"


# ---------------------------------------------------------------------------
# Overlap helper + rendezvous readiness
# ---------------------------------------------------------------------------

class TestHostSetup:
    def test_overlap_runs_in_background(self):
        started = threading.Event()

        def fn():
            started.set()
            return 41 + 1

        hs = HostSetup(fn, overlap=True)
        assert started.wait(timeout=5.0)
        assert hs.result() == 42

    def test_serial_defers_until_result(self):
        calls = []
        hs = HostSetup(lambda: calls.append(1) or "v", overlap=False)
        assert calls == []  # nothing ran yet: the serial baseline ordering
        assert hs.result() == "v"
        assert calls == [1]
        assert hs.result() == "v"  # memoized, not re-run
        assert calls == [1]

    def test_exception_propagates(self):
        hs = HostSetup(lambda: 1 / 0, overlap=True)
        with pytest.raises(ZeroDivisionError):
            hs.result()


class TestRendezvousReadiness:
    def test_coordinator_drops_ready_file(self, tmp_path, monkeypatch):
        monkeypatch.setenv(ENV_RENDEZVOUS_DIR, str(tmp_path))
        rt = JobRuntime(coordinator="svc.example:2222", num_processes=2,
                        process_id=0)
        rt._drop_ready_file()
        assert os.path.exists(tmp_path / "svc.example_2222.ready")

    def test_worker_waits_for_drop_then_port(self, tmp_path, monkeypatch):
        monkeypatch.setenv(ENV_RENDEZVOUS_DIR, str(tmp_path))
        srv = socket.socket()
        srv.bind(("127.0.0.1", 0))
        port = srv.getsockname()[1]
        coord = f"127.0.0.1:{port}"
        rt = JobRuntime(coordinator=coord, num_processes=2, process_id=1)

        def coordinator_side():
            time.sleep(0.15)
            JobRuntime(coordinator=coord, num_processes=2,
                       process_id=0)._drop_ready_file()
            srv.listen(1)

        t = threading.Thread(target=coordinator_side, daemon=True)
        t0 = time.monotonic()
        t.start()
        rt._wait_coordinator(timeout_s=10.0)
        took = time.monotonic() - t0
        srv.close()
        assert 0.1 < took < 5.0  # waited for the drop, then connected

    def test_no_dir_falls_back_to_tcp_poll(self, monkeypatch):
        monkeypatch.delenv(ENV_RENDEZVOUS_DIR, raising=False)
        srv = socket.socket()
        srv.bind(("127.0.0.1", 0))
        srv.listen(1)
        rt = JobRuntime(coordinator=f"127.0.0.1:{srv.getsockname()[1]}",
                        num_processes=2, process_id=1)
        t0 = time.monotonic()
        rt._wait_coordinator(timeout_s=5.0)
        assert time.monotonic() - t0 < 2.0
        srv.close()


# ---------------------------------------------------------------------------
# Memoization
# ---------------------------------------------------------------------------

class TestDatasetMemoization:
    def test_teacher_means_is_one_shared_readonly_array(self):
        a = d.mnist_teacher_means()
        b = d.mnist_teacher_means()
        assert a is b
        assert not a.flags.writeable

    def test_synthetic_mnist_memoized_per_seed_and_size(self):
        a = d.synthetic_mnist(7, 64)
        assert d.synthetic_mnist(7, 64)[0] is a[0]
        assert d.synthetic_mnist(8, 64)[0] is not a[0]
        assert d.synthetic_mnist(7, 128)[0] is not a[0]

    def test_numpy_and_jax_variants_sample_the_same_mixture(self):
        xn, yn = d.synthetic_mnist_np(3, 32)
        xj, yj = d.synthetic_mnist(3, 32)
        assert np.array_equal(xn, np.asarray(xj))
        assert np.array_equal(yn.astype(np.int32), np.asarray(yj))

    def test_tokens_memoized(self):
        a = d.synthetic_tokens(1, 4, 16, 32)
        assert d.synthetic_tokens(1, 4, 16, 32) is a
        assert d.synthetic_tokens(2, 4, 16, 32) is not a


# ---------------------------------------------------------------------------
# Env plumbing: planner + kubelet
# ---------------------------------------------------------------------------

class TestCompileCacheEnvPlumbing:
    def test_planner_injects_spec_dir_next_to_model_dir(self):
        from kubeflow_controller_tpu.api.meta import ObjectMeta
        from kubeflow_controller_tpu.api.tfjob import TFJob
        from kubeflow_controller_tpu.planner.materialize import (
            ENV_COMPILE_CACHE,
            _dir_env,
        )

        job = TFJob(metadata=ObjectMeta(name="j"))
        job.spec.model_dir = "/ckpt"
        job.spec.compile_cache_dir = "/jit-cache"
        env = _dir_env(job)
        assert env["MODEL_DIR"] == "/ckpt"
        assert env[ENV_COMPILE_CACHE] == "/jit-cache"

    def test_kubelet_node_default_yields_to_spec_env(self):
        from kubeflow_controller_tpu.cluster import Cluster, FakeKubelet
        from kubeflow_controller_tpu.planner.materialize import ENV_COMPILE_CACHE

        kubelet = FakeKubelet(Cluster())
        try:
            env: dict = {}
            kubelet._wire_startup_env(env)
            assert env[ENV_COMPILE_CACHE] == kubelet._compile_cache_dir
            assert env[ENV_RENDEZVOUS_DIR] == kubelet._rendezvous_dir
            pinned = {ENV_COMPILE_CACHE: "/job-pinned"}
            kubelet._wire_startup_env(pinned)
            assert pinned[ENV_COMPILE_CACHE] == "/job-pinned"
        finally:
            kubelet.stop()
