"""Multi-tenant fair-share plane: DRF ledger edge cases, two-level
scheduling, borrow-then-reclaim conservation, the (tenant, gang) fairness
clock, per-tenant workqueue round-robin, apiserver write-path isolation
(429 + Retry-After), and the tenant CLI surfaces."""

import json
import time
import urllib.error
import urllib.request

import pytest

from kubeflow_controller_tpu.api.core import Container, PodTemplateSpec, TenantQuota, TenantQuotaSpec
from kubeflow_controller_tpu.api.meta import ObjectMeta
from kubeflow_controller_tpu.api.tenant import tenant_of, tenant_of_pod
from kubeflow_controller_tpu.api.tfjob import (
    ElasticSpec,
    JobGoodput,
    ReplicaType,
    TFJob,
    TFJobPhase,
    TFReplicaSpec,
    TPUSpec,
)
from kubeflow_controller_tpu.cluster import Cluster, TPUInventory, TPUSlice
from kubeflow_controller_tpu.cluster.apiserver import FakeAPIServer
from kubeflow_controller_tpu.cluster.rest import Kubeconfig, RestCluster
from kubeflow_controller_tpu.controller.workqueue import RateLimitingQueue
from kubeflow_controller_tpu.obs.metrics import REGISTRY
from kubeflow_controller_tpu.planner.materialize import make_pod
from kubeflow_controller_tpu.scheduler import GangScheduler, SchedulerPolicy
from kubeflow_controller_tpu.scheduler.tenants import TenantLedger


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------

def mk_tpu_job(name, ns="default", num_slices=1, priority="",
               elastic_min=0, runtime_id="rid"):
    job = TFJob(metadata=ObjectMeta(name=name, namespace=ns))
    job.metadata.uid = f"uid-{ns}-{name}"
    job.spec.runtime_id = runtime_id
    if priority:
        job.spec.priority_class_name = priority
    t = PodTemplateSpec()
    t.spec.containers.append(Container(name="c", image="img"))
    t.spec.restart_policy = "OnFailure"
    if elastic_min:
        job.spec.elastic = ElasticSpec(min_width=elastic_min)
    job.spec.tf_replica_specs = [TFReplicaSpec(
        replicas=2 * num_slices, tf_replica_type=ReplicaType.TPU, template=t,
        tpu=TPUSpec(accelerator_type="v5e-8", num_hosts=2,
                    num_slices=num_slices))]
    return job


def slices(n):
    return [TPUSlice(f"s{i}", "v5e-8", num_hosts=2) for i in range(n)]


def mk_pods(job):
    """Materialized member pods, named the way the controller would."""
    n = job.spec.tf_replica_specs[0].replicas
    pods = [make_pod(job, job.spec.tf_replica_specs[0], i) for i in range(n)]
    for i, p in enumerate(pods):
        p.metadata.name = f"{job.metadata.name}-{i}"
    return pods


def admit(sched, job):
    """Offer every pod of the job's gang, start the coordinator, offer
    again; returns (pods, offer results of the second pass)."""
    pods = mk_pods(job)
    for p in pods:
        sched.offer(p)
    sched.pod_started(pods[0])
    return pods, [sched.offer(p) for p in pods]


def counter_total(name, labels=("priority_class",)):
    c = REGISTRY.counter(name, "", labels)
    with c._lock:
        return sum(c._values.values())


def rig(n_slices):
    inv = TPUInventory(slices(n_slices))
    sched = GangScheduler(inv, SchedulerPolicy())
    evictions = []
    sched.set_evictor(lambda keys, reason: evictions.append(
        (sorted(keys), reason)))
    return inv, sched, evictions


# ---------------------------------------------------------------------------
# DRF ledger edge cases
# ---------------------------------------------------------------------------

class TestTenantLedger:
    def test_zero_usage_tenants_order_first(self):
        led = TenantLedger(lambda: 4)
        led.charge("busy", slices=3)
        led.touch("idle")
        assert next(iter(led.ordered())) == "idle"
        # Early break re-pushes what it consumed: a second iteration
        # still sees every tenant, same order.
        assert list(led.ordered()) == ["idle", "busy"]

    def test_dominant_resource_is_the_max_axis(self):
        led = TenantLedger(lambda: 4)
        led.charge("serve-only", serving=3)      # share 0.75
        led.charge("train-only", slices=2)       # share 0.50
        led.charge("mixed", slices=1, serving=1)  # share 0.25 (both axes)
        assert list(led.ordered()) == ["mixed", "train-only", "serve-only"]
        assert led.share_of("serve-only") == pytest.approx(0.75)
        assert led.share_of("mixed") == pytest.approx(0.25)

    def test_live_weight_change_reorders_immediately(self):
        led = TenantLedger(lambda: 4)
        led.charge("a", slices=2)   # 0.5
        led.charge("b", slices=1)   # 0.25
        assert list(led.ordered()) == ["b", "a"]
        led.set_quota("a", weight=4.0)   # 0.5 / 4 = 0.125
        assert list(led.ordered()) == ["a", "b"]

    def test_borrowed_inert_without_any_quota(self):
        led = TenantLedger(lambda: 4)
        led.charge("a", slices=3)
        assert led.borrowed("a") == 0 and led.total_borrowed() == 0
        # The first TenantQuota anywhere defines entitlements for all.
        led.set_quota("b", slices=1)
        assert led.borrowed("a") == 3
        led.remove_quota("b")
        assert led.borrowed("a") == 0

    def test_entitled_requires_quota_headroom(self):
        led = TenantLedger(lambda: 8)
        led.set_quota("q", slices=2)
        led.charge("q", slices=1)
        assert led.entitled("q", slices=1)
        assert not led.entitled("q", slices=2)
        assert not led.entitled("noquota", slices=1)

    def test_may_take_hard_caps_only_non_borrowable(self):
        led = TenantLedger(lambda: 8)
        led.set_quota("soft", slices=1)                    # borrowable
        led.set_quota("hard", slices=1, borrowable=False)  # opted out
        led.charge("soft", slices=1)
        led.charge("hard", slices=1)
        assert led.may_take("soft", slices=5)
        assert not led.may_take("hard", slices=1)
        assert led.may_take("neverseen", slices=5)

    def test_credit_clamps_at_zero(self):
        led = TenantLedger(lambda: 4)
        led.charge("a", slices=1)
        led.credit("a", slices=5)
        assert led.snapshot()["a"]["used_slices"] == 0


# ---------------------------------------------------------------------------
# Two-level DRF scheduling
# ---------------------------------------------------------------------------

class TestDRFScheduling:
    def test_idle_tenant_beats_older_waiter_of_busy_tenant(self):
        _, sched, _ = rig(2)
        admit(sched, mk_tpu_job("a1", ns="alpha"))
        admit(sched, mk_tpu_job("a2", ns="alpha"))
        # alpha queues ANOTHER gang first (older fairness clock), beta
        # queues one after: single-level FIFO would admit a3.
        a3 = mk_tpu_job("a3", ns="alpha")
        a3_pods = mk_pods(a3)
        b1 = mk_tpu_job("b1", ns="beta")
        b1_pods = mk_pods(b1)
        assert not any(sched.offer(p) for p in a3_pods)
        assert not any(sched.offer(p) for p in b1_pods)
        sched.release_gang("a1-rid")
        # beta's dominant share (0) < alpha's (1/2): beta wins the slice.
        assert any(sched.offer(p) for p in b1_pods)
        assert not any(sched.offer(p) for p in a3_pods)

    def test_weights_scale_the_share(self):
        _, sched, _ = rig(4)
        sched.set_tenant_quota("heavy", weight=4.0)
        sched.set_tenant_quota("light", weight=1.0)
        for name in ("h1", "h2", "h3"):
            admit(sched, mk_tpu_job(name, ns="heavy"))
        admit(sched, mk_tpu_job("l1", ns="light"))
        # light queues first; after release: heavy 2/4/4=0.125 < light
        # 1/4/1=0.25, so heavy's YOUNGER waiter wins.
        l2 = mk_tpu_job("l2", ns="light")
        l2_pods = mk_pods(l2)
        h4 = mk_tpu_job("h4", ns="heavy")
        h4_pods = mk_pods(h4)
        assert not any(sched.offer(p) for p in l2_pods)
        assert not any(sched.offer(p) for p in h4_pods)
        sched.release_gang("h1-rid")
        assert any(sched.offer(p) for p in h4_pods)
        assert not any(sched.offer(p) for p in l2_pods)

    def test_serving_gangs_charge_the_serving_axis(self):
        from kubeflow_controller_tpu.api.labels import LABEL_JOB_TYPE

        _, sched, _ = rig(2)
        job = mk_tpu_job("svc", ns="infer")
        pod = make_pod(job, job.spec.tf_replica_specs[0], 0)
        pod.metadata.labels[LABEL_JOB_TYPE] = "Serving"
        # Width-1 serving gang: rewrite the gang annotations.
        from kubeflow_controller_tpu.api.labels import (
            ANNOTATION_GANG_NAME,
            ANNOTATION_GANG_SIZE,
            ANNOTATION_NUM_SLICES,
        )
        pod.metadata.annotations[ANNOTATION_GANG_NAME] = "svc-rid-serve-0"
        pod.metadata.annotations[ANNOTATION_GANG_SIZE] = "1"
        pod.metadata.annotations[ANNOTATION_NUM_SLICES] = "1"
        assert sched.offer(pod)
        snap = sched.tenant_shares()["infer"]
        assert snap["used_serving"] == 1
        assert snap["used_slices"] == 0

    def test_borrow_then_reclaim_conserves_every_slice(self):
        """The tentpole gate in miniature: an over-quota elastic tenant
        is width-harvested (never whole-gang preempted) down to what an
        entitled claimant needs, and the ledger never leaks or
        double-counts a slice across the reclaim."""
        inv, sched, evictions = rig(4)
        sched.set_tenant_quota("lo", slices=2)
        sched.set_tenant_quota("hi", slices=2)
        admit(sched, mk_tpu_job("big", ns="lo", num_slices=4, elastic_min=2))
        assert len(sched.gang_slices("big-rid")) == 4
        assert sched.tenant_shares()["lo"]["borrowed"] == 2
        before = counter_total("kctpu_sched_preemptions_total")

        _, results = admit(sched, mk_tpu_job("claim", ns="hi", num_slices=2))
        assert any(results)
        assert len(sched.gang_slices("claim-rid")) == 2
        assert len(sched.gang_slices("big-rid")) == 2  # floor, not gone
        assert len(evictions) == 1
        assert evictions[0][1].startswith("WidthHarvested")
        assert counter_total("kctpu_sched_preemptions_total") == before

        snap = sched.tenant_shares()
        assert snap["lo"]["used_slices"] == 2 and snap["lo"]["borrowed"] == 0
        assert snap["hi"]["used_slices"] == 2
        bound = sum(len(sched.gang_slices(g)) for g in ("big-rid", "claim-rid"))
        assert bound == 4 == (snap["lo"]["used_slices"]
                              + snap["hi"]["used_slices"])
        # Releases give back exactly the remembered charge: no negative
        # clamp hiding a double-count, no residue.
        sched.release_gang("claim-rid")
        sched.release_gang("big-rid")
        snap = sched.tenant_shares()
        assert snap["lo"]["used_slices"] == 0
        assert snap["hi"]["used_slices"] == 0
        assert inv.free_slice_count("v5e-8") == 4

    def test_non_borrowable_tenant_pins_at_quota_without_deadlock(self):
        _, sched, _ = rig(2)
        sched.set_tenant_quota("capped", slices=1, borrowable=False)
        admit(sched, mk_tpu_job("c1", ns="capped"))
        c2 = mk_tpu_job("c2", ns="capped")
        c2_pods = mk_pods(c2)
        # A slice is free, but the hard cap holds c2 back...
        assert not any(sched.offer(p) for p in c2_pods)
        # ...and the pinned head must NOT drain admissions for others.
        _, results = admit(sched, mk_tpu_job("f1", ns="free"))
        assert any(results)
        # Once c1 releases, c2 fits inside quota again.
        sched.release_gang("c1-rid")
        assert any(sched.offer(p) for p in c2_pods)


# ---------------------------------------------------------------------------
# Fairness clock keyed by (tenant, gang) — the PR 7 fix
# ---------------------------------------------------------------------------

class TestFairnessClockTenantKey:
    def test_same_gang_name_across_tenants_gets_fresh_clock(self):
        """runtime_id is user-settable, so gang names collide across
        tenants.  A preempted tenant keeps its fairness seniority for its
        OWN comeback; another tenant reusing the name must not inherit
        it and queue-jump its own older waiters."""
        _, sched, _ = rig(1)
        admit(sched, mk_tpu_job("x", ns="a", priority="low"))
        t_a = sched._fairness[("a", "x-rid")]
        time.sleep(0.01)
        # b's first waiter (the senior one).
        old = mk_tpu_job("old", ns="b")
        old_pods = mk_pods(old)
        for p in old_pods:
            sched.offer(p)
        # b preempts a's started low gang with a high one...
        _, results = admit(sched, mk_tpu_job("hi", ns="b", priority="high"))
        assert any(results)
        assert ("a", "x-rid") in sched._fairness  # seniority survives
        time.sleep(0.01)
        # ...then b submits its OWN job named x with the same runtime id.
        bx = mk_tpu_job("x", ns="b")
        bx_pods = mk_pods(bx)
        for p in bx_pods:
            sched.offer(p)
        assert sched._fairness[("b", "x-rid")] > t_a
        # Behavioral check: on release, b's senior waiter wins — with the
        # old name-only key, b's "x" would have inherited a's clock and
        # jumped the line.
        sched.release_gang("hi-rid")
        assert any(sched.offer(p) for p in old_pods)
        assert not any(sched.offer(p) for p in bx_pods)


# ---------------------------------------------------------------------------
# Workqueue per-tenant fresh tier
# ---------------------------------------------------------------------------

class TestWorkqueueTenantRoundRobin:
    def test_fresh_tier_interleaves_tenants(self):
        q = RateLimitingQueue(name="rrq")
        for k in ("a/1", "a/2", "b/1", "a/3"):
            q.add(k)
        got = [q.get(timeout=1.0) for _ in range(4)]
        assert got == ["a/1", "b/1", "a/2", "a/3"]
        q.shut_down()

    def test_custom_tenant_resolver(self):
        q = RateLimitingQueue(name="rrq1", tenant_of=lambda k: "one")
        for k in ("a/1", "a/2", "b/1"):
            q.add(k)
        assert [q.get(timeout=1.0) for _ in range(3)] == ["a/1", "a/2", "b/1"]
        q.shut_down()

    def test_drain_pending_preserves_interleave(self):
        q = RateLimitingQueue(name="rrq2")
        for k in ("a/1", "a/2", "b/1"):
            q.add(k)
        drained = [k for k, _ in q.drain_pending()]
        assert drained == ["a/1", "b/1", "a/2"]
        assert len(q) == 0
        q.shut_down()


# ---------------------------------------------------------------------------
# Apiserver write-path isolation
# ---------------------------------------------------------------------------

def _post_job(url, ns, name, tenant):
    body = {"apiVersion": "kubeflow.caicloud.io/v1alpha1", "kind": "TFJob",
            "metadata": {"name": name, "namespace": ns},
            "spec": {"runtimeId": "r"}}
    req = urllib.request.Request(
        f"{url}/apis/kubeflow.caicloud.io/v1alpha1/namespaces/{ns}/tfjobs",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json",
                 "X-Kctpu-Tenant": tenant},
        method="POST")
    try:
        with urllib.request.urlopen(req) as r:
            return r.status, dict(r.headers)
    except urllib.error.HTTPError as e:
        e.read()
        return e.code, dict(e.headers)


class TestApiserverWriteThrottle:
    def test_429_isolated_per_tenant_with_retry_after(self):
        cluster = Cluster()
        srv = FakeAPIServer(cluster.store, write_qps=0.5, write_burst=1)
        url = srv.start()
        try:
            c = REGISTRY.counter("kctpu_apiserver_throttled_total", "",
                                 ("tenant",))
            with c._lock:
                before = dict(c._values)
            code1, _ = _post_job(url, "ns1", "j1", "noisy")
            assert code1 < 400
            code2, hdrs = _post_job(url, "ns1", "j2", "noisy")
            assert code2 == 429
            assert int(hdrs.get("Retry-After", "0")) >= 1
            # The noisy tenant's storm is its own problem: a different
            # tenant's bucket is untouched.
            code3, _ = _post_job(url, "ns2", "j3", "quiet")
            assert code3 < 400
            with c._lock:
                after = dict(c._values)
            assert after.get(("noisy",), 0) == before.get(("noisy",), 0) + 1
            assert after.get(("quiet",), 0) == before.get(("quiet",), 0)
        finally:
            srv.stop()

    def test_typed_client_honors_retry_after(self):
        cluster = Cluster()
        srv = FakeAPIServer(cluster.store, write_qps=5.0, write_burst=1)
        url = srv.start()
        rest = RestCluster(Kubeconfig(server=url))
        rest.set_tenant_provider(lambda: "bursty")
        try:
            waits_before = counter_total("kctpu_rest_throttle_waits_total",
                                         labels=())
            for i in range(3):
                job = mk_tpu_job(f"burst{i}", ns="bursty")
                rest.tfjobs.create(job)
            # Every write landed despite throttling (in-flight Retry-After
            # sleeps), and the client counted at least one honored wait.
            assert len(rest.tfjobs.list("bursty")) == 3
            assert counter_total("kctpu_rest_throttle_waits_total",
                                 labels=()) > waits_before
        finally:
            rest.close()
            srv.stop()


# ---------------------------------------------------------------------------
# CLI tenant surfaces
# ---------------------------------------------------------------------------

def mk_status_job(cluster, name, ns, tenant_label="", goodput=None):
    t = PodTemplateSpec()
    t.spec.containers.append(Container(name="w", image="img"))
    job = TFJob(metadata=ObjectMeta(name=name, namespace=ns))
    if tenant_label:
        job.metadata.labels["tenant"] = tenant_label  # kctpu: vet-ok(tenant-label) - test fixture seeds the raw label
    job.spec.tf_replica_specs.append(TFReplicaSpec(
        replicas=2, tf_replica_type=ReplicaType.WORKER, template=t))
    cluster.tfjobs.create(job)
    j = cluster.tfjobs.get(ns, name)
    j.status.phase = TFJobPhase.RUNNING
    j.status.goodput = goodput
    cluster.tfjobs.update_status(j)


class TestCLITenantSurfaces:
    @pytest.fixture
    def served(self):
        cluster = Cluster()
        srv = FakeAPIServer(cluster.store)
        url = srv.start()
        mk_status_job(cluster, "t1", "teama", goodput=JobGoodput(
            goodput_s=90, occupied_s=100, wall_s=120, ratio=0.9,
            buckets={"train": 90, "queued": 20, "rendezvous": 10}))
        mk_status_job(cluster, "t2", "teamb", goodput=JobGoodput(
            goodput_s=50, occupied_s=100, wall_s=120, ratio=0.5,
            buckets={"train": 50, "rendezvous": 50}))
        # Label override: lives in teamb's namespace, billed to teama.
        mk_status_job(cluster, "t3", "teamb", tenant_label="teama")
        cluster.tenantquotas.create(TenantQuota(
            metadata=ObjectMeta(name="teama", namespace="default"),
            spec=TenantQuotaSpec(weight=4.0, slices=2)))
        yield url
        srv.stop()

    def row(self, out, name):
        hdr = next(ln for ln in out.splitlines() if ln.startswith("NAMESPACE")
                   or ln.startswith("TENANT"))
        row = next(ln for ln in out.splitlines()
                   if f" {name} " in f"{ln} " and not ln.startswith("TENANT"))
        return hdr, row

    def test_get_has_aligned_tenant_column_and_filter(self, served, capsys):
        from kubeflow_controller_tpu.cli.main import main

        assert main(["-master", served, "get"]) == 0
        out = capsys.readouterr().out
        hdr, row = self.row(out, "t1")
        at = hdr.index("TENANT")
        assert row[at:at + 12].strip() == "teama"
        # The label override resolves, not the namespace.
        _, r3 = self.row(out, "t3")
        assert r3[at:at + 12].strip() == "teama"
        # Columns right of TENANT stay put.
        assert row[hdr.index("PHASE"):].startswith("Running")
        # --tenant filters on the resolved identity (t3 rides along).
        assert main(["-master", served, "get", "--tenant", "teama"]) == 0
        out = capsys.readouterr().out
        assert " t1 " in out and " t3 " in out and " t2 " not in out

    def test_describe_quota_share_section(self, served, capsys):
        from kubeflow_controller_tpu.cli.main import main

        assert main(["-master", served, "describe", "t1",
                     "-n", "teama"]) == 0
        out = capsys.readouterr().out
        assert "Tenant:    teama" in out
        assert "Quota:     weight=4 slices=2" in out
        # No quota object -> tenant line only.
        assert main(["-master", served, "describe", "t2",
                     "-n", "teamb"]) == 0
        out = capsys.readouterr().out
        assert "Tenant:    teamb" in out
        assert "Quota:" not in out

    def test_goodput_tenant_rollup_table(self, served, capsys):
        from kubeflow_controller_tpu.cli.main import main

        assert main(["-master", served, "goodput", "--tenant"]) == 0
        out = capsys.readouterr().out
        hdr = next(ln for ln in out.splitlines() if ln.startswith("TENANT"))
        assert "GOODPUT" in hdr and "OCC_S" in hdr
        rows = {ln.split()[0]: ln.split() for ln in out.splitlines()
                if ln.startswith("team")}
        # t3 has no ledger -> doesn't pollute teama's rollup.
        assert rows["teama"][1:4] == ["1", "90%", "90"]
        assert rows["teamb"][1:4] == ["1", "50%", "50"]
        # Worst ratio sorts first.
        assert out.index("teamb") < out.index("teama")

    def test_top_prints_tenant_rollup_line(self, served, capsys):
        from kubeflow_controller_tpu.cli.main import main

        assert main(["-master", served, "top"]) == 0
        out = capsys.readouterr().out
        line = next(ln for ln in out.splitlines()
                    if ln.startswith("tenants: "))
        assert "teama:2j" in line and "teamb:1j" in line
        assert "good=90%" in line  # teama's occupied-weighted ratio


# ---------------------------------------------------------------------------
# Tenant identity resolution
# ---------------------------------------------------------------------------

class TestTenantResolution:
    def test_label_overrides_namespace(self):
        job = mk_tpu_job("j", ns="nsx")
        assert tenant_of(job) == "nsx"
        job.metadata.labels["tenant"] = "acme"  # kctpu: vet-ok(tenant-label) - test fixture seeds the raw label
        assert tenant_of(job) == "acme"

    def test_pod_annotation_wins(self):
        job = mk_tpu_job("j", ns="nsx")
        pod = make_pod(job, job.spec.tf_replica_specs[0], 0)
        assert tenant_of_pod(pod) == "nsx"  # materialize stamped it
