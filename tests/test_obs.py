"""Observability layer tests: span tracer (nesting, thread-safety, ring
buffer, Chrome dumps), Prometheus instruments + text exposition (escaping,
counter monotonicity, histogram cumulativity), workqueue instrumentation
under concurrent workers, and the e2e /metrics surface of a completed
distributed job."""

import json
import threading
import time
import urllib.request

import pytest

from kubeflow_controller_tpu.obs import (
    REGISTRY,
    Registry,
    TRACER,
    Tracer,
    dump_to_env_dir,
    load_trace_events,
    merge_trace_dir,
    validate_exposition,
)
from kubeflow_controller_tpu.obs.lifecycle import JobLifecycle


# ---------------------------------------------------------------------------
# Tracer
# ---------------------------------------------------------------------------

class TestTracer:
    def test_span_records_duration_and_args(self):
        t = Tracer()
        with t.span("work/unit", key="a/b") as sp:
            time.sleep(0.01)
        assert sp.dur >= 0.01
        assert sp.args == {"key": "a/b"}
        spans = t.spans()
        assert len(spans) == 1 and spans[0].name == "work/unit"

    def test_nesting_records_parent(self):
        t = Tracer()
        with t.span("outer"):
            with t.span("inner"):
                pass
            with t.span("inner2"):
                pass
        with t.span("top"):
            pass
        by_name = {s.name: s for s in t.spans()}
        assert by_name["inner"].parent == "outer"
        assert by_name["inner2"].parent == "outer"
        assert by_name["outer"].parent == ""
        assert by_name["top"].parent == ""

    def test_prefix_query(self):
        t = Tracer()
        with t.span("sync/gather"):
            pass
        with t.span("workload/fit"):
            pass
        assert [s.name for s in t.spans("sync")] == ["sync/gather"]

    def test_ring_buffer_drops_oldest(self):
        t = Tracer(capacity=10)
        for i in range(25):
            with t.span(f"s{i}"):
                pass
        names = [s.name for s in t.spans()]
        assert names == [f"s{i}" for i in range(15, 25)]

    def test_thread_safety(self):
        t = Tracer(capacity=10_000)
        errors = []

        def worker(wid):
            try:
                for i in range(100):
                    with t.span(f"w{wid}/outer", i=i):
                        with t.span(f"w{wid}/inner"):
                            pass
            except Exception as e:  # pragma: no cover
                errors.append(e)

        threads = [threading.Thread(target=worker, args=(w,)) for w in range(8)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        assert not errors
        assert len(t) == 8 * 100 * 2
        # Nesting is per-thread: every inner span's parent is ITS thread's
        # outer span, never another thread's.
        for s in t.spans():
            if s.name.endswith("/inner"):
                assert s.parent == s.name.replace("/inner", "/outer")

    def test_chrome_trace_shape(self, tmp_path):
        t = Tracer()
        with t.span("phase/x", worker=1):
            pass
        doc = t.chrome_trace()
        assert doc["displayTimeUnit"] == "ms"
        (ev,) = doc["traceEvents"]
        assert ev["ph"] == "X" and ev["name"] == "phase/x"
        assert ev["dur"] >= 0 and ev["ts"] > 0
        assert ev["cat"] == "phase" and ev["args"]["worker"] == 1
        path = str(tmp_path / "trace.json")
        t.dump(path)
        assert len(load_trace_events(path)) == 1
        json.load(open(path))  # chrome-loadable JSON

    def test_env_dir_dump_and_merge(self, tmp_path, monkeypatch):
        d = str(tmp_path / "dumps")
        monkeypatch.setenv("KCTPU_TRACE_DIR", d)
        t = Tracer()
        assert dump_to_env_dir(t) is None  # nothing traced: no file
        with t.span("a"):
            pass
        p = dump_to_env_dir(t)
        assert p is not None and p.startswith(d)
        t2 = Tracer()
        with t2.span("b"):
            pass
        doc = merge_trace_dir(d, tracer=t2)
        assert sorted(e["name"] for e in doc["traceEvents"]) == ["a", "b"]

    def test_env_dir_unset_is_noop(self, monkeypatch):
        monkeypatch.delenv("KCTPU_TRACE_DIR", raising=False)
        t = Tracer()
        with t.span("a"):
            pass
        assert dump_to_env_dir(t) is None


# ---------------------------------------------------------------------------
# Instruments + exposition
# ---------------------------------------------------------------------------

class TestMetrics:
    def test_counter_monotonicity(self):
        reg = Registry()
        c = reg.counter("t_total", "help")
        c.inc()
        c.inc(2.5)
        assert c.value == 3.5
        with pytest.raises(ValueError):
            c.inc(-1)
        lc = reg.counter("tl_total", "help", labelnames=("k",))
        lc.labels(k="a").inc()
        with pytest.raises(ValueError):
            lc.labels(k="a").inc(-0.5)

    def test_get_or_create_and_mismatch(self):
        reg = Registry()
        a = reg.counter("same_total", "h", labelnames=("x",))
        b = reg.counter("same_total", "h", labelnames=("x",))
        assert a is b
        with pytest.raises(ValueError):
            reg.gauge("same_total", "h")  # type mismatch
        with pytest.raises(ValueError):
            reg.counter("same_total", "h", labelnames=("y",))  # label mismatch
        with pytest.raises(ValueError):
            reg.counter("bad name", "h")
        with pytest.raises(ValueError):
            reg.counter("ok_total", "h", labelnames=("0bad",))

    def test_gauge_set_and_callback(self):
        reg = Registry()
        g = reg.gauge("g", "h")
        g.set(4)
        g.dec()
        assert g.value == 3
        depth = reg.gauge("d", "h", labelnames=("name",))
        depth.labels(name="q").set_function(lambda: 7)
        text = reg.render()
        assert 'd{name="q"} 7.0' in text

    def test_histogram_cumulative_buckets(self):
        reg = Registry()
        h = reg.histogram("lat", "h", buckets=(0.1, 1.0, 10.0))
        for v in (0.05, 0.5, 0.5, 5.0, 50.0):
            h.observe(v)
        text = reg.render()
        assert 'lat_bucket{le="0.1"} 1' in text
        assert 'lat_bucket{le="1.0"} 3' in text
        assert 'lat_bucket{le="10.0"} 4' in text
        assert 'lat_bucket{le="+Inf"} 5' in text
        assert "lat_count 5" in text
        assert h.sum == pytest.approx(56.05)

    def test_label_escaping_round_trips_validation(self):
        reg = Registry()
        c = reg.counter("esc_total", "back\\slash and\nnewline",
                        labelnames=("v",))
        c.labels(v='quote " back \\ newline \n end').inc()
        text = reg.render()
        assert '\\"' in text and "\\\\" in text and "\\n" in text
        assert validate_exposition(text) == []

    def test_render_is_valid_exposition(self):
        reg = Registry()
        reg.counter("a_total", "h").inc()
        reg.gauge("b", "h").set(1.5)
        reg.histogram("c", "h", labelnames=("q",)).labels(q="x").observe(0.2)
        problems = validate_exposition(reg.render())
        assert problems == []

    def test_validator_catches_garbage(self):
        bad = "# TYPE x counter\nx{oops 1\nno_type_metric 2\nx NaNaN\n"
        problems = validate_exposition(bad)
        assert any("unparseable" in p or "malformed" in p for p in problems)
        assert any("no TYPE" in p for p in problems)

    def test_validator_catches_duplicate_series(self):
        bad = "# TYPE x counter\nx 1\nx 2\n"
        assert any("duplicate series" in p for p in validate_exposition(bad))

    def test_collector_keyed_replacement(self):
        from kubeflow_controller_tpu.obs.metrics import Family, Sample

        reg = Registry()
        reg.register_collector("k", lambda: [
            Family("one", "gauge", "h", [Sample("", {}, 1.0)])])
        reg.register_collector("k", lambda: [
            Family("two", "gauge", "h", [Sample("", {}, 2.0)])])
        text = reg.render()
        assert "two 2.0" in text and "one" not in text


# ---------------------------------------------------------------------------
# Reconcile metrics + lifecycle on a registry
# ---------------------------------------------------------------------------

class TestCollectors:
    def test_reconcile_metrics_summary(self):
        from kubeflow_controller_tpu.controller.metrics import ReconcileMetrics

        reg = Registry()
        m = ReconcileMetrics()
        m.register(reg)
        for v in (0.001, 0.002, 0.003):
            m.record_sync(v)
        m.record_sync(0.5, error=True)
        text = reg.render()
        assert validate_exposition(text) == []
        assert 'kctpu_reconcile_duration_seconds{quantile="0.5"}' in text
        assert "kctpu_reconcile_duration_seconds_count 4" in text
        assert "kctpu_controller_sync_errors_total 1.0" in text

    def test_lifecycle_dedups_and_measures(self):
        reg = Registry()
        lc = JobLifecycle(registry=reg)
        t0 = 1000.0
        lc.observe("uid1", "None", "Pending", now=t0 + 1, created=t0)
        lc.observe("uid1", "Pending", "Running", now=t0 + 3)
        # Stale recompute of the same transition: must not double-count.
        lc.observe("uid1", "Pending", "Running", now=t0 + 4)
        lc.observe("uid1", "Running", "Succeeded", now=t0 + 10)
        h = reg.histogram("kctpu_job_phase_transition_seconds", "",
                          labelnames=("from_phase", "to_phase"))
        pend = h.labels(from_phase="None", to_phase="Pending")
        run = h.labels(from_phase="Pending", to_phase="Running")
        done = h.labels(from_phase="Running", to_phase="Succeeded")
        assert pend.count == 1 and pend.sum == pytest.approx(1.0)
        assert run.count == 1 and run.sum == pytest.approx(2.0)
        assert done.count == 1 and done.sum == pytest.approx(7.0)
        assert lc.tracked() == 0  # terminal jobs drop their entry

    def test_lifecycle_bounded(self):
        reg = Registry()
        lc = JobLifecycle(registry=reg, max_jobs=5)
        for i in range(20):
            lc.observe(f"u{i}", "None", "Running", now=float(i))
        assert lc.tracked() <= 5

    def test_trainer_telemetry(self):
        from kubeflow_controller_tpu.workloads.trainer import record_step_telemetry

        reg = Registry()
        record_step_telemetry(200, 2.0, examples_per_step=96, registry=reg)
        assert reg.counter("kctpu_trainer_steps_total", "").value == 200
        assert reg.counter("kctpu_trainer_examples_total", "").value == 200 * 96
        assert reg.gauge("kctpu_trainer_examples_per_second", "").value == \
            pytest.approx(200 * 96 / 2.0)
        assert reg.histogram("kctpu_trainer_step_duration_seconds", "").count == 1
        record_step_telemetry(0, 1.0, registry=reg)  # no-op, no division
        assert validate_exposition(reg.render()) == []


# ---------------------------------------------------------------------------
# Workqueue instrumentation
# ---------------------------------------------------------------------------

class TestWorkqueueMetrics:
    def _handles(self, reg, name):
        depth = reg.gauge("kctpu_workqueue_depth", "", ("name",)).labels(name=name)
        adds = reg.counter("kctpu_workqueue_adds_total", "", ("name",)).labels(name=name)
        wait = reg.histogram("kctpu_workqueue_queue_duration_seconds", "",
                             ("name",)).labels(name=name)
        retries = reg.counter("kctpu_workqueue_retries_total", "", ("name",)).labels(name=name)
        requeues = reg.counter("kctpu_workqueue_requeues_total", "",
                               ("name",)).labels(name=name)
        return depth, adds, wait, retries, requeues

    def test_depth_and_queue_wait(self):
        from kubeflow_controller_tpu.controller.workqueue import RateLimitingQueue

        reg = Registry()
        q = RateLimitingQueue(name="t1", registry=reg)
        depth, adds, wait, _, _ = self._handles(reg, "t1")
        q.add("a")
        q.add("b")
        q.add("a")  # dedup-collapsed: not a new add
        assert depth.value == 2 and adds.value == 2
        got = q.get(timeout=1)
        assert got is not None
        assert depth.value == 1
        assert wait.count == 1 and wait.sum >= 0
        q.done(got)
        q.get(timeout=1)
        assert depth.value == 0
        q.shut_down()

    def test_requeue_and_retry_counters(self):
        from kubeflow_controller_tpu.controller.workqueue import RateLimitingQueue

        reg = Registry()
        q = RateLimitingQueue(name="t2", registry=reg)
        _, adds, _, retries, requeues = self._handles(reg, "t2")
        q.add("a")
        item = q.get(timeout=1)
        q.add("a")       # dirty while processing
        q.done(item)     # -> requeued
        assert requeues.value == 1
        q.get(timeout=1)
        q.done("a")
        q.add_rate_limited("a")
        assert retries.value == 1
        # The delayed add eventually lands and counts as an add.
        deadline = time.time() + 5
        while time.time() < deadline and adds.value < 3:
            time.sleep(0.01)
        assert adds.value == 3
        q.shut_down()

    def test_concurrent_workers_drain_cleanly(self):
        from kubeflow_controller_tpu.controller.workqueue import (
            RateLimitingQueue,
            ShutDown,
        )

        reg = Registry()
        q = RateLimitingQueue(name="t3", registry=reg)
        depth, adds, wait, _, _ = self._handles(reg, "t3")
        N = 200
        processed = []
        lock = threading.Lock()

        def worker():
            while True:
                try:
                    item = q.get(timeout=5)
                except ShutDown:
                    return
                if item is None:
                    return
                with lock:
                    processed.append(item)
                q.done(item)

        threads = [threading.Thread(target=worker) for _ in range(4)]
        for t in threads:
            t.start()
        for i in range(N):
            q.add(f"ns/job-{i}")
        deadline = time.time() + 10
        while time.time() < deadline and len(processed) < N:
            time.sleep(0.01)
        q.shut_down()
        for t in threads:
            t.join(timeout=5)
        assert sorted(set(processed)) == sorted(f"ns/job-{i}" for i in range(N))
        assert adds.value == N
        assert wait.count == len(processed)
        assert depth.value == 0


# ---------------------------------------------------------------------------
# e2e: completed distributed job -> /metrics over HTTP
# ---------------------------------------------------------------------------

def _mk_job(name, *types_and_replicas):
    from kubeflow_controller_tpu.api.core import Container, PodTemplateSpec
    from kubeflow_controller_tpu.api.meta import ObjectMeta
    from kubeflow_controller_tpu.api.tfjob import TFJob, TFReplicaSpec

    job = TFJob(metadata=ObjectMeta(name=name, namespace="default"))
    for typ, n in types_and_replicas:
        t = PodTemplateSpec()
        t.spec.containers.append(Container(name="tensorflow", image="img"))
        t.spec.restart_policy = "OnFailure"
        job.spec.tf_replica_specs.append(
            TFReplicaSpec(replicas=n, tf_replica_type=typ, template=t))
    return job


class TestMetricsEndpointE2E:
    def test_completed_dist_job_exposes_lifecycle_and_reconcile(self):
        from kubeflow_controller_tpu.api.tfjob import ReplicaType, TFJobPhase
        from kubeflow_controller_tpu.cluster import Cluster, FakeKubelet, PhasePolicy
        from kubeflow_controller_tpu.cluster.apiserver import FakeAPIServer
        from kubeflow_controller_tpu.controller import Controller

        cluster = Cluster()
        server = FakeAPIServer(cluster.store)
        url = server.start()
        kubelet = FakeKubelet(cluster, policy=PhasePolicy(run_s=0.05))
        ctrl = Controller(cluster, resync_period_s=1.0)
        kubelet.start()
        ctrl.run(threadiness=2)
        try:
            cluster.tfjobs.create(_mk_job(
                "obs-dist", (ReplicaType.PS, 1), (ReplicaType.WORKER, 2)))
            deadline = time.time() + 30
            while time.time() < deadline:
                if (cluster.tfjobs.get("default", "obs-dist").status.phase
                        == TFJobPhase.SUCCEEDED):
                    break
                time.sleep(0.05)
            else:
                raise AssertionError("job never reached Succeeded")
            with urllib.request.urlopen(f"{url}/metrics", timeout=10) as resp:
                assert "text/plain" in resp.headers.get("Content-Type", "")
                text = resp.read().decode()
        finally:
            ctrl.stop()
            kubelet.stop()
            server.stop()

        assert validate_exposition(text) == []

        def sample_value(prefix):
            for line in text.splitlines():
                if line.startswith(prefix):
                    return float(line.rsplit(" ", 1)[1])
            raise AssertionError(f"no sample {prefix!r} in /metrics")

        # Non-zero phase-transition histograms for the completed job.
        assert sample_value(
            'kctpu_job_phase_transition_seconds_count'
            '{from_phase="Pending",to_phase="Running"}') >= 1
        assert sample_value(
            'kctpu_job_phase_transition_seconds_count'
            '{from_phase="Running",to_phase="Succeeded"}') >= 1
        # Reconcile latency percentiles + counters.
        assert sample_value('kctpu_reconcile_duration_seconds{quantile="0.5"}') >= 0
        assert sample_value("kctpu_controller_syncs_total") >= 1
        # Workqueue instrumentation.
        assert sample_value('kctpu_workqueue_adds_total{name="tfJobs"}') >= 1
        assert sample_value(
            'kctpu_workqueue_queue_duration_seconds_count{name="tfJobs"}') >= 1
        # Reconcile spans landed on the global tracer (sync + nested gather).
        assert TRACER.spans("sync/gather")
        assert any(s.parent == "sync" for s in TRACER.spans("sync/gather"))

    def test_debug_traces_endpoint(self):
        from kubeflow_controller_tpu.cluster.apiserver import FakeAPIServer

        t = Tracer()
        with t.span("sync", key="default/x"):
            pass
        server = FakeAPIServer(tracer=t)
        url = server.start()
        try:
            with urllib.request.urlopen(f"{url}/debug/traces", timeout=10) as resp:
                doc = json.load(resp)
        finally:
            server.stop()
        assert [e["name"] for e in doc["traceEvents"]] == ["sync"]

    def test_global_registry_render_always_valid(self):
        # Whatever previous tests left on the global registry must render
        # as valid exposition (this is what GET /metrics serves).
        assert validate_exposition(REGISTRY.render()) == []
