"""Model zoo: MNIST parity behaviors and Llama forward/loss under meshes."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_controller_tpu.models import (
    LlamaConfig,
    llama_forward,
    llama_init,
    llama_loss,
    llama_param_pspecs,
    mlp_accuracy,
    mlp_apply,
    mlp_init,
    mlp_loss,
    softmax_apply,
    softmax_init,
)
from kubeflow_controller_tpu.parallel import MeshSpec, build_mesh
from kubeflow_controller_tpu.parallel.compat import set_mesh as compat_set_mesh


class TestMNIST:
    def test_softmax_shapes_and_zero_init(self):
        p = softmax_init(jax.random.PRNGKey(0))
        x = jnp.ones((32, 784))
        logits = softmax_apply(p, x)
        assert logits.shape == (32, 10)
        # zero init -> uniform logits, as the reference starts
        np.testing.assert_allclose(np.asarray(logits), 0.0)

    def test_mlp_learns_a_separable_problem(self):
        key = jax.random.PRNGKey(1)
        p = mlp_init(key)
        x = jax.random.normal(key, (256, 784))
        w_true = jax.random.normal(jax.random.PRNGKey(2), (784, 10))
        y = jnp.argmax(x @ w_true, axis=-1)

        @jax.jit
        def step(p):
            loss, g = jax.value_and_grad(mlp_loss)(p, x, y)
            return jax.tree.map(lambda a, b: a - 0.5 * b, p, g), loss

        loss0 = float(mlp_loss(p, x, y))
        for _ in range(60):
            p, loss = step(p)
        assert float(loss) < loss0 * 0.5
        assert float(mlp_accuracy(p, x, y)) > 0.7


class TestLlama:
    def test_forward_shapes(self):
        cfg = LlamaConfig.tiny()
        params = llama_init(jax.random.PRNGKey(0), cfg)
        tokens = jnp.zeros((2, 16), dtype=jnp.int32)
        logits = llama_forward(params, tokens, cfg)
        assert logits.shape == (2, 16, cfg.vocab_size)
        assert logits.dtype == jnp.float32

    def test_causality(self):
        """Changing a future token must not change past logits."""
        cfg = LlamaConfig.tiny()
        params = llama_init(jax.random.PRNGKey(0), cfg)
        t1 = jnp.array([[1, 2, 3, 4, 5, 6, 7, 8]], dtype=jnp.int32)
        t2 = t1.at[0, -1].set(99)
        l1 = llama_forward(params, t1, cfg)
        l2 = llama_forward(params, t2, cfg)
        np.testing.assert_allclose(
            np.asarray(l1[0, :-1]), np.asarray(l2[0, :-1]), atol=1e-5
        )
        assert not np.allclose(np.asarray(l1[0, -1]), np.asarray(l2[0, -1]))

    def test_gqa_matches_mha_when_kv_heads_equal(self):
        """n_kv_heads == n_heads is plain MHA; repeats==1 path."""
        cfg = LlamaConfig.tiny(n_kv_heads=4)
        params = llama_init(jax.random.PRNGKey(0), cfg)
        tokens = jnp.arange(32, dtype=jnp.int32).reshape(1, 32) % cfg.vocab_size
        logits = llama_forward(params, tokens, cfg)
        assert logits.shape == (1, 32, cfg.vocab_size)

    def test_loss_decreases_with_sgd(self):
        cfg = LlamaConfig.tiny()
        params = llama_init(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(3), (4, 32), 0, cfg.vocab_size)

        @jax.jit
        def step(p):
            loss, g = jax.value_and_grad(llama_loss)(p, tokens, cfg)
            return jax.tree.map(lambda a, b: a - 0.1 * b, p, g), loss

        _, loss0 = step(params)
        p = params
        for _ in range(10):
            p, loss = step(p)
        assert float(loss) < float(loss0)

    def test_sharded_forward_matches_unsharded(self):
        """FSDP+TP+SP sharded forward == single-device forward."""
        cfg = LlamaConfig.tiny(remat=False)
        params = llama_init(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(4), (2, 32), 0, cfg.vocab_size)
        ref = llama_forward(params, tokens, cfg)

        mesh = build_mesh(MeshSpec(dp=1, fsdp=2, sp=2, tp=2))
        pspecs = llama_param_pspecs(cfg)
        sharded_params = jax.tree.map(
            lambda a, s: jax.device_put(a, jax.sharding.NamedSharding(mesh, s)),
            params, pspecs,
        )
        with compat_set_mesh(mesh):
            out = jax.jit(
                lambda p, t: llama_forward(p, t, cfg, mesh=mesh)
            )(sharded_params, tokens)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-4, rtol=2e-4)

    def test_param_pspecs_tree_matches_params(self):
        cfg = LlamaConfig.tiny()
        params = llama_init(jax.random.PRNGKey(0), cfg)
        pspecs = llama_param_pspecs(cfg)
        # identical tree structure
        jax.tree.map(lambda a, s: None, params, pspecs)
        # every pspec rank matches its param rank
        def check(a, s):
            assert len(s) <= a.ndim, (a.shape, s)
        jax.tree.map(check, params, pspecs)


class TestFlashFallbackWarning:
    def test_explicit_flash_warns_once_when_no_legal_tile(self):
        """ADVICE round 5: an explicit attention="flash" request that
        silently degrades to the dense XLA path (flash_block()==0, e.g.
        T=12 f32 not a multiple of the 8-row sublane tile) must say so —
        once per shape/dtype, matching the MoE fallback discipline."""
        import warnings

        from kubeflow_controller_tpu.models import llama as llama_mod
        from kubeflow_controller_tpu.parallel.ring import flash_block

        t = 12
        assert flash_block(t, jnp.float32) == 0  # the degraded shape
        cfg = LlamaConfig(attention="flash")
        q = jnp.zeros((1, t, 2, 8), jnp.float32)
        llama_mod._FLASH_FALLBACK_WARNED.clear()
        with pytest.warns(UserWarning, match="dense"):
            out = llama_mod._flash_path(q, q, q, None, True, None, cfg)
        assert out is None  # fell back
        with warnings.catch_warnings():
            warnings.simplefilter("error")  # second call: silent
            assert llama_mod._flash_path(q, q, q, None, True, None, cfg) is None

    def test_auto_mode_stays_silent(self):
        import warnings

        from kubeflow_controller_tpu.models import llama as llama_mod

        cfg = LlamaConfig(attention="auto")
        q = jnp.zeros((1, 12, 2, 8), jnp.float32)
        llama_mod._FLASH_FALLBACK_WARNED.clear()
        with warnings.catch_warnings():
            warnings.simplefilter("error")
            assert llama_mod._flash_path(q, q, q, None, True, None, cfg) is None


class TestChunkedCE:
    """cfg.loss_chunks: the loss without the [B,T,vocab] logits tensor."""

    def _setup(self):
        import dataclasses

        cfg = LlamaConfig.tiny(max_seq_len=32)
        params = llama_init(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0,
                                    cfg.vocab_size)
        return cfg, dataclasses.replace(cfg, loss_chunks=4), params, tokens

    def test_matches_dense_loss(self):
        cfg, cfg_c, params, tokens = self._setup()
        dense = llama_loss(params, tokens, cfg)
        chunked = llama_loss(params, tokens, cfg_c)
        np.testing.assert_allclose(float(dense), float(chunked), rtol=2e-5)

    @pytest.mark.slow
    def test_grads_match_dense(self):
        cfg, cfg_c, params, tokens = self._setup()
        gd = jax.grad(lambda p: llama_loss(p, tokens, cfg))(params)
        gc = jax.grad(lambda p: llama_loss(p, tokens, cfg_c))(params)
        for a, b, name in ((gd["lm_head"], gc["lm_head"], "lm_head"),
                           (gd["embed"], gc["embed"], "embed"),
                           (gd["layers"]["wq"], gc["layers"]["wq"], "wq")):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-4, rtol=2e-3, err_msg=name)

    def test_sharded_matches(self):
        from jax.sharding import NamedSharding

        from kubeflow_controller_tpu.models.llama import llama_param_pspecs
        from kubeflow_controller_tpu.parallel import MeshSpec, build_mesh

        cfg, cfg_c, params, tokens = self._setup()
        dense = llama_loss(params, tokens, cfg)
        mesh = build_mesh(MeshSpec(dp=2, tp=2, fsdp=2))
        sharded = jax.tree.map(
            lambda a, s: jax.device_put(a, NamedSharding(mesh, s)),
            params, llama_param_pspecs(cfg))
        with compat_set_mesh(mesh):
            out = jax.jit(lambda p, t: llama_loss(p, t, cfg_c, mesh=mesh))(
                sharded, tokens)
        np.testing.assert_allclose(float(out), float(dense), rtol=5e-5)

    def test_indivisible_seq_raises(self):
        import dataclasses

        cfg, _, params, tokens = self._setup()
        bad = dataclasses.replace(cfg, loss_chunks=5)  # 32 % 5 != 0
        with pytest.raises(ValueError):
            llama_loss(params, tokens, bad)
