"""Vet fixture: violations only the WHOLE-PROGRAM lock graph can see
(the lock-graph rule) — every function is individually clean, the bugs
live across call edges no runtime test executes.

Variable names deliberately avoid the local lock-blocking-call rule's
name heuristic (*lock*/*cond*/*guard*): these findings must come from
vocabulary resolution, not from naming luck.
"""
import time

from kubeflow_controller_tpu.utils import locks


class Ledger:
    def __init__(self):
        self._accounts = locks.named_lock("fixture.accounts")
        self._audit = locks.named_lock("fixture.audit")

    # -- the inversion: accounts -> audit on one path, audit -> accounts
    # on another, each hop hidden behind a call -------------------------------

    def _append_audit(self):
        with self._audit:
            pass

    def post(self):
        with self._accounts:  # accounts -> audit (via _append_audit)
            self._append_audit()

    def _lock_accounts_and_fix(self):
        with self._accounts:
            pass

    def reconcile(self):
        with self._audit:  # audit -> accounts: the inversion (BAD)
            self._lock_accounts_and_fix()

    # -- blocking reached through a call hop ----------------------------------

    def _settle_remote(self):
        time.sleep(0.2)  # fine here: nothing held in THIS function

    def flush(self):
        with self._accounts:
            self._settle_remote()  # BAD: sleep reached under accounts
