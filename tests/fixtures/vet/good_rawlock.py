"""Vet fixture: the same locks routed through the named-lock facade."""
from kubeflow_controller_tpu.utils import locks

_module_level = locks.named_lock("fixture.module")


class Worker:
    def __init__(self):
        self._mu = locks.named_rlock("fixture.worker")
        self._cv = locks.named_condition("fixture.worker-cv")
        self._io = locks.named_lock("fixture.io", allow_blocking=True)
