"""Vet fixture: the same work with blocking calls OUTSIDE the lock."""
import queue
import socket
import subprocess
import threading
import time

_lock = threading.Lock()  # kctpu: vet-ok(raw-lock) - fixture prop
_q = queue.Queue()


def sleep_outside_lock():
    with _lock:
        deadline = time.time() + 0.1
    time.sleep(max(0.0, deadline - time.time()))


def queue_get_outside_lock():
    item = _q.get(timeout=1.0)
    with _lock:
        return item


def socket_outside_cond(cond):
    s = socket.socket()
    s.connect(("127.0.0.1", 80))
    with cond:
        return s


def deferred_under_lock_is_fine():
    with _lock:
        # A closure DEFINED under the lock runs later: not a finding.
        def later():
            time.sleep(0.1)
        return later


def subprocess_outside_lock():
    proc = subprocess.run(["true"])
    with _lock:
        return proc.returncode
