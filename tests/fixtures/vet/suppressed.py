"""Vet fixture: violations silenced with inline `# kctpu: vet-ok(rule)`
markers (docs/ANALYSIS.md)."""
import copy
import threading
import time

_lock = threading.Lock()  # kctpu: vet-ok(raw-lock)


def intentional_sleep_under_lock():
    with _lock:  # kctpu: vet-ok(lock-blocking-call)
        time.sleep(0.001)


def intentional_deepcopy(obj):
    return copy.deepcopy(obj)  # kctpu: vet-ok(hot-path-deepcopy)


def intentional_anonymous(worker):
    return threading.Thread(target=worker)  # kctpu: vet-ok(thread-hygiene)
