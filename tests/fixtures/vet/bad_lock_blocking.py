"""Vet fixture: blocking calls inside `with <lock>` bodies (all BAD)."""
import queue
import socket
import subprocess
import threading
import time

_lock = threading.Lock()  # kctpu: vet-ok(raw-lock) - fixture prop
_q = queue.Queue()


def sleep_under_lock():
    with _lock:
        time.sleep(0.1)  # BAD: lock held across sleep


def queue_get_under_lock():
    with _lock:
        return _q.get(timeout=1.0)  # BAD: lock held across a blocking pop


def socket_under_cond(cond):
    with cond:
        s = socket.socket()  # BAD: socket created in the critical section
        s.connect(("127.0.0.1", 80))  # BAD: lock held across connect


def subprocess_under_lock():
    with _lock:
        subprocess.run(["true"])  # BAD: lock held across a child process
