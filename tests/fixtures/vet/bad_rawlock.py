"""Vet fixture: bare threading primitives bypassing the named-lock
facade (all BAD — the raw-lock rule)."""
import threading
from threading import Lock

_module_level = threading.Lock()  # BAD: invisible to the analysis plane


class Worker:
    def __init__(self):
        self._mu = threading.RLock()  # BAD: bare RLock
        self._cv = threading.Condition()  # BAD: bare Condition (own RLock)
        self._imported = Lock()  # BAD: bare-imported ctor
