"""Vet fixture: tenancy resolved through the shared resolver (GOOD)."""
from kubeflow_controller_tpu.api.tenant import tenant_of, tenant_of_pod


def queue_key(job):
    return tenant_of(job)


def bill_to(pod):
    return tenant_of_pod(pod)


def stamp(md, job):
    # WRITING the annotation (the planner's job) is not a raw read.
    md.annotations["kctpu.io/tenant"] = tenant_of(job)
    return {"kctpu.io/tenant": tenant_of(job)}


def unrelated(job):
    # Non-tenant label reads stay out of scope.
    return (job.metadata.labels or {}).get("job-type", "")
