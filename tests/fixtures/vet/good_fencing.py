"""Fixture: the same store writes carrying the leader fencing token (or
an explicit provider) — the fencing-token rule must stay silent.  Reads
and non-store receivers are out of scope by design."""


def sync_job(store, job, lease):
    store.update("tfjobs", job, fence=lease.generation)
    store.update_status("tfjobs", job, fence=lease.generation)


def manage_children(self, pod):
    self._store.create("pods", pod, fence=self._fence())
    self._store.delete("pods", "default", "p-0", fence=self._fence())


def adopt(cluster, ns, name, fn, token):
    cluster.store.patch_meta("pods", ns, name, fn, fence=token)


def read_paths(store):
    store.get("pods", "default", "p-0")       # reads are never fenced
    store.list("pods", "default")
    store.watch("pods")


def typed_client_write(cluster, job):
    # Typed clients stamp the fence internally (cluster/client.py): the
    # rule keys on *store receivers, not client objects.
    cluster.tfjobs.update(job)
