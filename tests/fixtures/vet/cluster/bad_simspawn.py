"""Fixture: per-object Thread spawn in a simulated-path module (BAD).

The exact regression the `sim-thread-per-object` rule exists to catch: a
simulated kubelet quietly growing a thread per pod again.
"""

import threading


class BadSimKubelet:
    def start(self):
        # Fine: one fixed loop thread for the whole component.
        self._main = threading.Thread(target=self._run, name="sim-loop",
                                      daemon=True)
        self._main.start()

    def _run(self):
        pass

    def _spawn(self, pod):
        # BAD: one thread per pod — O(pods) threads.
        t = threading.Thread(target=self._drive, args=(pod,),
                             name="sim-pod", daemon=True)
        t.start()

    def _drive(self, pod):
        pass
