"""Fixture: simulated-path module with O(1) threads (GOOD).

Per-pod work is queued onto the component's single loop thread, which is
created in start() — the shape `sim-thread-per-object` allows.
"""

import threading


class GoodSimKubelet:
    def __init__(self):
        self._timers = []
        self._main = None

    def start(self):
        self._main = threading.Thread(target=self._run, name="sim-loop",
                                      daemon=True)
        self._main.start()

    def _spawn(self, pod):
        # Per-pod transitions become timer events, not threads.
        self._timers.append((0.0, pod))

    def _run(self):
        pass
