"""Vet fixture: deepcopy on a hot path, thread hygiene, metric prefix,
event-reason style (all BAD)."""
import copy
import threading


def hot_copy(obj):
    return copy.deepcopy(obj)  # BAD: use serde.deep_copy


def spawn_anonymous(worker):
    t = threading.Thread(target=worker)  # BAD: no name, no daemon
    t.start()
    return t


def spawn_non_daemon(worker):
    t = threading.Thread(target=worker, name="w", daemon=False)  # BAD
    t.start()
    return t


def register(registry):
    return registry.counter("sync_total", "syncs")  # BAD: no kctpu_ prefix


REASON_BAD_STYLE = "created pod"  # BAD: not CamelCase


def emit(recorder, job, n):
    recorder.event(job, "Normal", "created pod", "msg")  # BAD reason style
    recorder.event(job, "Normal", f"Restarted{n}", "msg")  # BAD dynamic reason
