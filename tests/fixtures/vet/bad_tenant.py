"""Vet fixture: raw tenant label/annotation reads outside the shared
resolver (all BAD — tenant-label)."""
from kubeflow_controller_tpu.api.labels import ANNOTATION_TENANT, LABEL_TENANT


def queue_key(job):
    # BAD: skips the label-override -> namespace-default chain.
    return (job.metadata.labels or {}).get(LABEL_TENANT, "default")


def bill_to(pod):
    return pod.metadata.annotations[ANNOTATION_TENANT]  # BAD: raw read


def throttle_bucket(job):
    return job.metadata.labels["tenant"]  # BAD: literal key, same bug
