"""Vet fixture: mutating store snapshots (shared immutable references)."""


def mutate_get_snapshot(store):
    obj = store.get_snapshot("pods", "default", "p0")
    obj.status.phase = "Running"  # BAD: shared reference mutated in place
    return obj


def mutate_list_snapshot(store):
    objs, rv = store.list_snapshot_with_rv("pods", "default")
    for o in objs:
        o.metadata.labels.update({"x": "y"})  # BAD: mutator on a snapshot
    return rv


def mutate_alias(store):
    snap = store.get_snapshot("pods", "default", "p0")
    alias = snap
    alias.metadata.name = "renamed"  # BAD: alias of a snapshot
