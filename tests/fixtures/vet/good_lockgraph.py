"""Vet fixture: the same shape with a consistent lock order and the
blocking call hoisted out of the critical section — lock-graph clean."""
import time

from kubeflow_controller_tpu.utils import locks


class Ledger:
    def __init__(self):
        self._accounts = locks.named_lock("fixture.accounts")
        self._audit = locks.named_lock("fixture.audit")

    def _append_audit(self):
        with self._audit:
            pass

    def post(self):
        with self._accounts:  # accounts -> audit everywhere
            self._append_audit()

    def reconcile(self):
        with self._accounts:  # same order on the second path
            self._append_audit()

    def _settle_remote(self):
        time.sleep(0.2)

    def flush(self):
        with self._accounts:
            pending = True
        if pending:
            self._settle_remote()  # blocking outside the critical section
