"""Fixture: workload reading slice identity / mesh shape from the runtime
env contract — what mesh-env requires.  $MEGASCALE_SLICE_ID /
$MEGASCALE_NUM_SLICES / $KCTPU_MESH (JobRuntime.slice_id / .num_slices /
.mesh) are stamped per generation by the materializer, already recomputed
for the gang's current width."""

import json
import os


def build_axes(rt):
    # GOOD: the mesh the scheduler actually placed, at the current width.
    if rt.mesh:
        return dict(rt.mesh)
    raw = os.environ.get("KCTPU_MESH", "")
    return json.loads(raw) if raw else {"dp": rt.num_slices}


def my_slice(rt):
    # GOOD: JobRuntime's fields ARE the env-derived values.
    n = int(os.environ.get("MEGASCALE_NUM_SLICES", "1"))
    return rt.slice_id if n > 1 else 0
