"""Fixture: workload deriving gang width from the SPEC — the exact bug
the gang-width-env rule exists for.  An elastic gang's runtime width is a
per-generation property (degrade/harvest/re-expand); spec.replicas is the
FULL width and mis-shards the degraded gang.  Path contains 'workloads/'
so the rule applies."""


def shard_for(job, index):
    # BAD: width from the job spec (the full width, not this
    # generation's) — a degraded gang of 2 would shard as if it were 3.
    width = job.spec.tf_replica_specs[0].replicas
    return index * (4096 // width)


def local_batch(spec, batch):
    # BAD: bare replica-spec read.
    return batch // spec.replicas
