"""Fixture: workload deriving gang width from the runtime env contract —
what gang-width-env requires.  $KCTPU_GANG_WIDTH (JobRuntime.gang_width)
is stamped per generation by the materializer, so data shards rebalance
across elastic re-shard transitions automatically."""

import os


def shard_for(rt, index):
    # GOOD: width from the per-generation runtime contract.
    width = rt.gang_width or int(os.environ.get("KCTPU_GANG_WIDTH", "1"))
    return index * (4096 // width)


def local_batch(rt, batch):
    # GOOD: the jax runtime's process count IS the runtime width.
    return batch // max(1, rt.num_processes)
