"""Fixture: workload recomputing its slice identity / mesh shape from the
SPEC — the exact bug the mesh-env rule exists for.  The slice set a
degraded gang actually spans differs from spec.tpu per generation
(elastic degrade removes whole pipeline replicas), so a spec-derived mesh
builds a different shape than the scheduler placed.  Path contains
'workloads/' so the rule applies."""


def build_axes(job):
    # BAD: slice count off the spec topology — the full count, not this
    # generation's; a degraded 2-of-4-slice gang would build a dp=4 mesh.
    n = job.spec.tf_replica_specs[0].tpu.num_slices
    return {"dp": n, "fsdp": 8}


def my_slice(spec, process_id, per_slice):
    # BAD: bare spec-shaped reads of the slice identity.
    if spec.tpu.num_slices > 1:
        return process_id // per_slice
    return spec.tpu.slice_id
