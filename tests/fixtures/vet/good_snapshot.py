"""Vet fixture: snapshot reads used correctly (read-only, or deep-copied
before mutation)."""

from kubeflow_controller_tpu.utils import serde


def read_snapshot(store):
    obj = store.get_snapshot("pods", "default", "p0")
    return obj.status.phase  # reads are fine


def copy_then_mutate(store):
    obj = serde.deep_copy(store.get_snapshot("pods", "default", "p0"))
    obj.status.phase = "Running"  # fine: our own copy
    return obj


def rebind_then_mutate(store):
    obj = store.get_snapshot("pods", "default", "p0")
    obj = serde.deep_copy(obj)
    obj.metadata.labels.update({"x": "y"})  # fine: rebound to a copy
    return obj


def plain_get_is_mutable(store):
    obj = store.get("pods", "default", "p0")  # get() returns a caller copy
    obj.status.phase = "Running"
    return obj
