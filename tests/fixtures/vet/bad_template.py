"""Vet fixture: the reference's shared-template mutation bug
(design_doc.md:262-268) — per-replica arg injection mutating the ONE
template object every other replica also builds from."""


def make_pod_buggy(spec, index):
    template = spec.template  # BAD binding: no deep copy
    template.spec.containers[0].args.append(f"--task_index={index}")
    return template


def inject_args_buggy(job, spec, index):
    # Direct mutation through the shared chain: every replica sees it.
    spec.template.metadata.labels["index"] = str(index)
    spec.template.spec.restart_policy = "Never"
