"""Vet fixture: per-replica materialization off a deep-copied template
(what planner/materialize.py actually does)."""

from kubeflow_controller_tpu.utils import serde


def make_pod_correct(spec, index):
    template = serde.deep_copy(spec.template)
    template.spec.containers[0].args.append(f"--task_index={index}")
    template.metadata.labels["index"] = str(index)
    return template


def read_only_is_fine(spec):
    restart = spec.template.spec.restart_policy if spec.template else "OnFailure"
    return restart
