"""Vet fixture: the same intents done right."""
import threading

from kubeflow_controller_tpu.utils import serde

REASON_GOOD_STYLE = "SuccessfulCreate"


def hot_copy(obj):
    return serde.deep_copy(obj)


def spawn_named_daemon(worker):
    t = threading.Thread(target=worker, name="fixture-worker", daemon=True)
    t.start()
    return t


def register(registry):
    return registry.counter("kctpu_fixture_total", "fixture counter")


def emit(recorder, job, n):
    recorder.event(job, "Normal", REASON_GOOD_STYLE, f"Created pod {n}")
