"""Fixture: store writes on controller sync paths WITHOUT a fencing
token — every write here must be flagged by the fencing-token rule.
A deposed leader running exactly this code after a failover corrupts
state the new leader already moved past (docs/HA.md)."""


def sync_job(store, job):
    store.update("tfjobs", job)                       # BAD: no fence
    store.update_status("tfjobs", job)                # BAD: no fence


def manage_children(self, pod):
    self._store.create("pods", pod)                   # BAD: no fence
    self._store.delete("pods", "default", "p-0")      # BAD: no fence


def adopt(cluster, ns, name, fn):
    cluster.store.patch_meta("pods", ns, name, fn)    # BAD: no fence
