"""Serving front door (gateway/): least-loaded routing, session
affinity, SLO-aware tiered admission, zero-drop drain re-homing,
informer-driven discovery, the shed-aware autoscale signal, and the
``gw/route`` -> ``serve/request`` causal trace edge.

Fake replicas (a submit callable + a gauges callable) drive the
admission/routing state machine deterministically; the drain and trace
tests run real ServeEngines over the SyntheticBackend.
"""

import json
import threading
import time

import pytest

from kubeflow_controller_tpu.api.core import PHASE_RUNNING, Pod
from kubeflow_controller_tpu.api.labels import (
    ANNOTATION_DRAIN,
    ANNOTATION_GATEWAY_STATS,
    LABEL_JOB_NAME,
    LABEL_JOB_TYPE,
)
from kubeflow_controller_tpu.api.meta import ObjectMeta
from kubeflow_controller_tpu.cluster import Cluster
from kubeflow_controller_tpu.controller import SharedInformer
from kubeflow_controller_tpu.gateway import (
    DECISION_ADMIT,
    DECISION_QUEUE,
    DECISION_SHED,
    GW_ROUTABLE_INDEX,
    Gateway,
    GatewayConfig,
    InformerDiscovery,
    Replica,
    engine_replica,
    job_stats_publisher,
    routable_pod,
)
from kubeflow_controller_tpu.obs import trace
from kubeflow_controller_tpu.serving.autoscale import gateway_signal
from kubeflow_controller_tpu.workloads.serve import (
    REFUSED_DRAINING,
    REFUSED_OVERLOADED,
    SUBMIT_OK,
    Request,
    ServeConfig,
    ServeEngine,
    SyntheticBackend,
)


def wait_for(fn, timeout=10.0, interval=0.005):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        v = fn()
        if v:
            return v
        time.sleep(interval)
    raise AssertionError("condition not met within timeout")


def instant_replica(name, gauges=None, refuse=None, log=None):
    """A replica whose submit completes the request immediately (or
    refuses with ``refuse``); ``log`` collects (replica, request id)."""

    def submit(req):
        refusal = refuse() if refuse is not None else None
        if refusal is not None:
            return refusal
        if log is not None:
            log.append((name, req.id))
        now = time.monotonic()
        req.admit_t = req.first_token_t = req.finish_t = now
        req.output[:] = [1]
        req.done.set()
        return SUBMIT_OK

    return Replica(name, submit,
                   gauges or (lambda: {"slots_total": 4}))


def mk_engine(slots=4, page_size=8, max_len=64, step_s=0.0):
    eng = ServeEngine(
        SyntheticBackend(step_s=step_s),
        ServeConfig(slots=slots, page_size=page_size, max_len=max_len,
                    prefill_buckets=(8, 16, 32), cont_batch=True,
                    prefix_cache=True, stats_window_s=2.0))
    eng.start()
    assert eng.wait_ready(30)
    return eng


def route_wait(gw, req, timeout=30.0):
    t = gw.route(req)
    assert req.done.wait(timeout), req.id
    return t


# ---------------------------------------------------------------------------
# Routing: least-loaded + session affinity
# ---------------------------------------------------------------------------

class TestRouting:
    def test_least_loaded_pick(self):
        log = []
        gw = Gateway(GatewayConfig())
        gw.register(instant_replica(
            "hot", gauges=lambda: {"queue_depth": 8, "slots_total": 4},
            log=log))
        gw.register(instant_replica(
            "cold", gauges=lambda: {"queue_depth": 0, "slots_total": 4},
            log=log))
        gw.start()
        try:
            for i in range(3):
                t = route_wait(gw, Request(id=f"r{i}", tokens=[1],
                                           max_new_tokens=1))
                assert t.decision == DECISION_ADMIT
            assert [n for n, _ in log] == ["cold", "cold", "cold"]
        finally:
            gw.stop()

    def test_session_affinity_pins_then_rehomes_on_deregister(self):
        log = []
        gw = Gateway(GatewayConfig())
        gw.register(instant_replica("a", log=log))
        gw.register(instant_replica("b", log=log))
        gw.start()
        try:
            for i in range(3):
                route_wait(gw, Request(id=f"r{i}", tokens=[1],
                                       max_new_tokens=1, session="conv"))
            pinned = log[0][0]
            assert [n for n, _ in log] == [pinned] * 3
            assert gw.stats().affinity_hits == 2  # first route pins (miss)
            gw.deregister(pinned)
            route_wait(gw, Request(id="r3", tokens=[1], max_new_tokens=1,
                                   session="conv"))
            other = {"a": "b", "b": "a"}[pinned]
            assert log[-1][0] == other
            # ...and the session is now pinned THERE.
            route_wait(gw, Request(id="r4", tokens=[1], max_new_tokens=1,
                                   session="conv"))
            assert log[-1][0] == other
        finally:
            gw.stop()

    def test_affinity_spills_off_overloaded_pin(self):
        """Cache locality must not defeat load balance: a pinned replica
        hotter than the coldest by more than the spill margin loses the
        session."""
        log = []
        load = {"a": 0}
        gw = Gateway(GatewayConfig(affinity_spill=2.0))
        gw.register(instant_replica(
            "a", gauges=lambda: {"queue_depth": load["a"],
                                 "slots_total": 4}, log=log))
        gw.register(instant_replica("b", log=log))
        gw.start()
        try:
            route_wait(gw, Request(id="r0", tokens=[1], max_new_tokens=1,
                                   session="conv"))
            if log[0][0] != "a":  # pin deterministically onto "a"
                gw.deregister("b")
                gw.register(instant_replica("b", log=log))
                log.clear()
                route_wait(gw, Request(id="r0b", tokens=[1],
                                       max_new_tokens=1, session="conv"))
            assert log[-1][0] == "a"
            # 3.0 load vs 0: past the 2.0 spill margin (but gateway-wide
            # pressure 12/8 = 1.5 stays under the standard queue band).
            load["a"] = 12
            route_wait(gw, Request(id="r1", tokens=[1], max_new_tokens=1,
                                   session="conv"))
            assert log[-1][0] == "b"
        finally:
            gw.stop()

    def test_draining_refusal_deregisters_and_retries(self):
        """REFUSED_DRAINING before the DRAIN-ACK: the replica leaves the
        routing set immediately and the request retries a sibling — the
        caller sees one admitted ticket, no error."""
        log = []
        gw = Gateway(GatewayConfig())
        gw.register(instant_replica(
            "a", refuse=lambda: REFUSED_DRAINING, log=log))
        gw.register(instant_replica("b", log=log))
        gw.start()
        try:
            t = route_wait(gw, Request(id="r0", tokens=[1],
                                       max_new_tokens=1))
            assert t.decision == DECISION_ADMIT and t.replica == "b"
            assert not t.request.error
            assert gw.replica_names() == ["b"]
        finally:
            gw.stop()

    def test_overloaded_refusal_queues_until_capacity(self):
        """REFUSED_OVERLOADED backs off into the gateway queue (no
        hammering); the pump dispatches once the replica accepts."""
        state = {"full": True}
        log = []
        gw = Gateway(GatewayConfig())
        gw.register(instant_replica(
            "a", refuse=lambda: REFUSED_OVERLOADED if state["full"] else None,
            log=log))
        gw.start()
        try:
            req = Request(id="r0", tokens=[1], max_new_tokens=1)
            t = gw.route(req)
            assert t.decision == DECISION_QUEUE
            assert not req.done.wait(0.05)
            state["full"] = False
            assert req.done.wait(10)
            assert not req.error and log == [("a", "r0")]
        finally:
            gw.stop()


# ---------------------------------------------------------------------------
# Admission: SLO-aware tier state machine
# ---------------------------------------------------------------------------

class TestAdmission:
    def overloaded_gateway(self, depth=8):
        """One replica whose published gauges put pressure at depth/4 —
        above batch's shed band, inside standard's queue band, below
        interactive's."""
        gw = Gateway(GatewayConfig())
        gw.register(instant_replica(
            "a", gauges=lambda: {"queue_depth": depth, "slots_total": 4}))
        return gw

    def test_tiers_shed_lowest_first(self):
        gw = self.overloaded_gateway(depth=8)  # pressure 2.0
        gw.start()
        try:
            batch = Request(id="b", tokens=[1], max_new_tokens=1,
                            tier="batch")
            tb = gw.route(batch)
            assert tb.decision == DECISION_SHED
            assert batch.done.is_set() and batch.error == "shed"
            ts = gw.route(Request(id="s", tokens=[1], max_new_tokens=1,
                                  tier="standard"))
            assert ts.decision == DECISION_QUEUE
            ti = route_wait(gw, Request(id="i", tokens=[1],
                                        max_new_tokens=1,
                                        tier="interactive"))
            assert ti.decision == DECISION_ADMIT
            st = gw.stats()
            assert st.shed == {"batch": 1}
        finally:
            gw.stop()

    def test_unknown_tier_routes_as_standard(self):
        gw = self.overloaded_gateway(depth=8)
        try:
            t = gw.route(Request(id="x", tokens=[1], max_new_tokens=1,
                                 tier="platinum"))
            assert t.tier == "standard" and t.decision == DECISION_QUEUE
        finally:
            gw.stop()

    def test_queue_overflow_sheds_youngest_lowest_tier(self):
        gw = Gateway(GatewayConfig(max_queue=2))
        gw.register(instant_replica(
            "a", gauges=lambda: {"queue_depth": 7, "slots_total": 4}))
        # pressure 1.75: standard queues (>=1.6), batch sheds at >=1.3 —
        # so queue a standard pair, then overflow with a third standard.
        reqs = [Request(id=f"s{i}", tokens=[1], max_new_tokens=1,
                        tier="standard") for i in range(3)]
        try:
            tickets = [gw.route(r) for r in reqs]
            assert [t.decision for t in tickets[:2]] == [DECISION_QUEUE] * 2
            # Overflow shed the YOUNGEST of the lowest queued tier.
            assert tickets[2].decision == DECISION_SHED
            assert reqs[2].error == "shed"
            assert not reqs[0].done.is_set()
        finally:
            gw.stop()

    def test_slo_burn_sheds_batch_before_interactive(self):
        """The pressure signal's second term: even with idle replicas, a
        windowed p99 TTFT past the objective sheds the low tier — the
        admission control the serving-ttft-p99 SLO feeds."""
        slow = {"on": True}

        def submit(req):
            now = time.monotonic()
            req.admit_t = now
            # 10 s observed TTFT while "slow": 5x the 2 s objective.
            req.first_token_t = (req.submit_t + 10.0 if slow["on"]
                                 else now)
            req.finish_t = now
            req.output[:] = [1]
            req.done.set()
            return SUBMIT_OK

        gw = Gateway(GatewayConfig(slo_ttft_ms=2000.0))
        gw.register(Replica("a", submit, lambda: {"slots_total": 4}))
        gw.start()
        try:
            for i in range(3):
                route_wait(gw, Request(id=f"w{i}", tokens=[1],
                                       max_new_tokens=1))
            wait_for(lambda: gw.pressure() >= 4.9)
            t = gw.route(Request(id="b", tokens=[1], max_new_tokens=1,
                                 tier="batch"))
            assert t.decision == DECISION_SHED
            ti = route_wait(gw, Request(id="i", tokens=[1],
                                        max_new_tokens=1,
                                        tier="interactive"))
            assert ti.decision == DECISION_ADMIT
        finally:
            gw.stop()


# ---------------------------------------------------------------------------
# Drain: zero drops, sessions re-home
# ---------------------------------------------------------------------------

class TestDrainRehome:
    def test_engine_drain_reroutes_queued_zero_drops(self):
        """Mid-burst drain of one of two real engines: unadmitted queue
        re-dispatches onto the survivor, in-flight finishes on the
        drained engine, every caller request completes clean, and the
        drained engine leaves the routing set."""
        e0 = mk_engine(slots=2, step_s=0.003)
        e1 = mk_engine(slots=2, step_s=0.003)
        # Admission bands off: this test is about drain re-homing, so
        # every request must dispatch straight into an engine's own
        # intake queue — the thing drain() hands back as "rerouted".
        wide = {t: 1e9 for t in ("interactive", "standard", "batch")}
        gw = Gateway(GatewayConfig(queue_at=dict(wide), shed_at=dict(wide)))
        gw.register(engine_replica("r0", e0))
        gw.register(engine_replica("r1", e1))
        gw.start()
        reqs = [Request(id=f"q{i}", tokens=[1 + i], max_new_tokens=6,
                        session=f"s{i % 4}") for i in range(12)]
        try:
            for r in reqs:
                gw.route(r)
            e0.drain()  # queued clones come back error=rerouted
            for r in reqs:
                assert r.done.wait(30), r.id
                assert not r.error, (r.id, r.error)
                assert len(r.output) == r.max_new_tokens
            wait_for(lambda: gw.replica_names() == ["r1"])
        finally:
            gw.stop()
            e0.stop()
            e1.stop()


# ---------------------------------------------------------------------------
# Informer-driven discovery
# ---------------------------------------------------------------------------

def mk_serving_pod(name, job="svc", ns="default", phase=PHASE_RUNNING):
    p = Pod(metadata=ObjectMeta(
        name=name, namespace=ns,
        labels={LABEL_JOB_TYPE: "Serving", LABEL_JOB_NAME: job}))
    p.status.phase = phase
    return p


class TestDiscovery:
    def test_routable_pod_predicate(self):
        p = mk_serving_pod("s0")
        assert routable_pod(p)
        drained = mk_serving_pod("s1")
        drained.metadata.annotations[ANNOTATION_DRAIN] = "1"
        assert not routable_pod(drained)
        pending = mk_serving_pod("s2", phase="Pending")
        assert not routable_pod(pending)
        deleting = mk_serving_pod("s3")
        deleting.metadata.deletion_timestamp = time.time()
        assert not routable_pod(deleting)
        trainer = mk_serving_pod("s4")
        trainer.metadata.labels[LABEL_JOB_TYPE] = "Worker"
        assert not routable_pod(trainer)

    def test_discovery_mirrors_routable_index(self):
        """Pods entering/leaving the routable index register/deregister;
        the DRAIN ANNOTATION alone pulls a replica from the routing set —
        before the replica ever acks."""
        c = Cluster()
        inf = SharedInformer(c.pods, resync_period_s=0, name="pods")
        inf.start()
        gw = Gateway(GatewayConfig())
        try:
            c.pods.create(mk_serving_pod("s0"))
            c.pods.create(mk_serving_pod("s1"))
            c.pods.create(mk_serving_pod("other", job="not-svc"))
            InformerDiscovery(gw, inf, "default", "svc",
                              lambda pod: instant_replica(pod.metadata.name))
            wait_for(lambda: gw.replica_names() == ["s0", "s1"])
            # Controller stamps the drain annotation -> leaves routing set.
            c.pods.patch_meta(
                "default", "s0",
                lambda m: m.annotations.update({ANNOTATION_DRAIN: "1"}))
            wait_for(lambda: gw.replica_names() == ["s1"])
            # A replacement appears -> joins.
            c.pods.create(mk_serving_pod("s2"))
            wait_for(lambda: gw.replica_names() == ["s1", "s2"])
            c.pods.delete("default", "s1")
            wait_for(lambda: gw.replica_names() == ["s2"])
        finally:
            inf.stop()


# ---------------------------------------------------------------------------
# Stats publication + the shed-aware autoscale signal
# ---------------------------------------------------------------------------

class TestStatsSignal:
    def test_stats_annotation_round_trip(self):
        gw = Gateway(GatewayConfig())
        gw.register(instant_replica("a"))
        gw.start()
        try:
            route_wait(gw, Request(id="r0", tokens=[1], max_new_tokens=1))
            doc = json.loads(gw.stats().as_annotation())
            assert doc["replicas"] == 1
            assert doc["weights"] == {"a": 1.0}
            assert doc["ts"] > 0
        finally:
            gw.stop()

    def test_publisher_writes_job_annotation(self):
        c = Cluster()
        from kubeflow_controller_tpu.api.tfjob import TFJob

        c.tfjobs.create(TFJob(metadata=ObjectMeta(name="svc",
                                                  namespace="default")))
        gw = Gateway(GatewayConfig(publish_s=0.01),
                     publisher=job_stats_publisher(c, "default", "svc"))
        gw.register(instant_replica("a"))
        gw.start()
        try:
            route_wait(gw, Request(id="r0", tokens=[1], max_new_tokens=1))

            def published():
                j = c.tfjobs.get("default", "svc")
                return j.metadata.annotations.get(ANNOTATION_GATEWAY_STATS)

            raw = wait_for(published)
            assert json.loads(raw)["replicas"] == 1
        finally:
            gw.stop()

    def test_gateway_signal_parses_queued_plus_shed(self):
        from kubeflow_controller_tpu.api.tfjob import TFJob

        job = TFJob(metadata=ObjectMeta(name="svc", namespace="default"))
        now = time.time()
        job.metadata.annotations[ANNOTATION_GATEWAY_STATS] = json.dumps(
            {"queued": 6, "shed_rps": 30.0, "ts": now})
        extra, why = gateway_signal(job, now)
        assert extra == 36.0 and "queued 6" in why and "30" in why

    def test_gateway_signal_ignores_stale_and_garbage(self):
        from kubeflow_controller_tpu.api.tfjob import TFJob

        job = TFJob(metadata=ObjectMeta(name="svc", namespace="default"))
        now = time.time()
        job.metadata.annotations[ANNOTATION_GATEWAY_STATS] = json.dumps(
            {"queued": 6, "shed_rps": 30.0, "ts": now - 60.0})
        assert gateway_signal(job, now) == (0.0, "")  # dead gateway
        job.metadata.annotations[ANNOTATION_GATEWAY_STATS] = "{not json"
        assert gateway_signal(job, now) == (0.0, "")

    def test_shedding_does_not_mask_scale_up(self):
        """The masking regression: a shedding gateway leaves replica
        queues EMPTY (the overload never reached them), so queue depth
        alone says "idle" at exactly the moment capacity is most needed.
        The gateway-queued + shed-rate term must force the scale-up."""
        from kubeflow_controller_tpu.api.core import (
            Container, PodProgress, PodTemplateSpec)
        from kubeflow_controller_tpu.api.tfjob import (
            AutoscaleSpec, ReplicaType, TFJob, TFReplicaSpec)
        from kubeflow_controller_tpu.serving.autoscale import (
            ServingAutoscaler)

        job = TFJob(metadata=ObjectMeta(name="svc", namespace="default",
                                        uid="u-svc"))
        job.spec.autoscale = AutoscaleSpec(
            min_replicas=1, max_replicas=4, target_queue_depth=4.0,
            tolerance=0.2, scale_down_stabilization_s=3.0)
        tmpl = PodTemplateSpec()
        tmpl.spec.containers.append(Container(name="srv", image="img"))
        job.spec.tf_replica_specs.append(TFReplicaSpec(
            replicas=1, tf_replica_type=ReplicaType.SERVING,
            template=tmpl))

        pod = Pod(metadata=ObjectMeta(name="svc-serving-0",
                                      namespace="default"))
        pod.status.phase = PHASE_RUNNING
        pod.status.progress = PodProgress(
            step=10, phase="serving", queue_depth=0, slots_used=0,
            slots_total=4, timestamp=time.time())

        now = time.time()
        a = ServingAutoscaler()
        # Control: no gateway stats, idle replica -> steady at min.
        d = a.assess("default/svc", job, [pod], now=now)
        assert d.target is None
        # Shedding gateway: queued 6 + 30/s shed = 36 depth-equivalents.
        job.metadata.annotations[ANNOTATION_GATEWAY_STATS] = json.dumps(
            {"queued": 6, "shed_rps": 30.0, "ts": now})
        d = a.assess("default/svc", job, [pod], now=now)
        assert d.target == 4  # ratio 9.0, clamped to max_replicas
        assert "gateway queued 6" in d.reason


# ---------------------------------------------------------------------------
# Causal trace: gw/route parents serve/request
# ---------------------------------------------------------------------------

class TestTraceEdge:
    def test_route_span_parents_serve_request(self):
        """One connected tree per request: the gateway's gw/route span is
        the causal parent of the engine's serve/request span, both on the
        caller's trace."""
        from kubeflow_controller_tpu.obs.trace import TRACER, TraceContext

        TRACER.clear()
        ctx = TraceContext(trace_id="t-front-door", span_id="root-span")
        with TRACER.context(ctx):
            eng = mk_engine(slots=2)   # engines capture ctx at construction
            gw = Gateway(GatewayConfig())
        gw.register(engine_replica("r0", eng))
        gw.start()
        try:
            route_wait(gw, Request(id="q0", tokens=[1, 2, 3],
                                   max_new_tokens=2))
            gw_span = wait_for(
                lambda: TRACER.spans(prefix="gw/route"))[0]
            srv_span = wait_for(
                lambda: TRACER.spans(prefix="serve/request"))[0]
            assert gw_span.trace_id == "t-front-door"
            assert gw_span.parent_id == "root-span"
            assert srv_span.trace_id == "t-front-door"
            assert srv_span.parent_id == gw_span.span_id
            assert gw_span.span_id and srv_span.span_id
        finally:
            gw.stop()
            eng.stop()
            TRACER.clear()
