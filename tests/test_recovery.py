"""Recovery plane tests: restart policy engine (backoff math, limits,
index-preserved re-create), checkpoint-resume (kill→restore ≡ uninterrupted,
corrupt-checkpoint fallback), gang-generation fan-out, restore-phase stall
hold, and ReplicaRestarted event dedup."""

import os
import random
import time

import pytest

from kubeflow_controller_tpu.api.core import (
    PHASE_FAILED,
    PHASE_RUNNING,
    Container,
    Pod,
    PodProgress,
    PodTemplateSpec,
)
from kubeflow_controller_tpu.api.labels import (
    ANNOTATION_GANG_GENERATION,
    LABEL_INDEX,
    LABEL_JOB_TYPE,
)
from kubeflow_controller_tpu.api.meta import ObjectMeta
from kubeflow_controller_tpu.api.tfjob import (
    ReplicaType,
    TFJob,
    TFJobConditionType,
    TFJobPhase,
    TFReplicaSpec,
)
from kubeflow_controller_tpu.checker import StallPolicy, StallTracker
from kubeflow_controller_tpu.recovery import (
    ACTION_BACKOFF,
    ACTION_EXHAUSTED,
    ACTION_NEVER,
    ACTION_REPLACE,
    RestartPolicyConfig,
    RestartTracker,
)
from kubeflow_controller_tpu.updater import compute_status


def mk_job(name="job", n=2, restart="OnFailure", typ=ReplicaType.WORKER,
           gang=False, backoff_limit=6):
    job = TFJob(metadata=ObjectMeta(name=name, namespace="default"))
    t = PodTemplateSpec()
    t.spec.containers.append(Container(name="c", image="img"))
    t.spec.restart_policy = restart
    job.spec.backoff_limit = backoff_limit
    job.spec.tf_replica_specs = [TFReplicaSpec(
        replicas=n, tf_replica_type=typ, template=t, gang_restart=gang)]
    return job


def mk_pod(name, typ="Worker", index=0, phase=PHASE_FAILED, reason="",
           job="job"):
    p = Pod(metadata=ObjectMeta(name=name, namespace="default"))
    p.metadata.labels = {LABEL_JOB_TYPE: typ, LABEL_INDEX: str(index),
                         "tf_job_name": job}
    p.status.phase = phase
    p.status.reason = reason
    return p


# ---------------------------------------------------------------------------
# Backoff schedule math (deterministic, injected clock)
# ---------------------------------------------------------------------------

class TestBackoffSchedule:
    def test_schedule_first_free_then_exponential_capped(self):
        tr = RestartTracker(RestartPolicyConfig(
            initial_backoff_s=1.0, backoff_factor=2.0, max_backoff_s=8.0,
            jitter=0.0))
        assert tr.backoff_schedule([1, 2, 3, 4, 5, 6, 7]) == \
            [0.0, 1.0, 2.0, 4.0, 8.0, 8.0, 8.0]

    def test_assess_applies_backoff_with_injected_clock(self):
        tr = RestartTracker(RestartPolicyConfig(
            initial_backoff_s=2.0, backoff_factor=2.0, max_backoff_s=60.0,
            jitter=0.0))
        job = mk_job()
        t0 = 1000.0
        # First failure: replace immediately (delay 0).
        pods = {ReplicaType.WORKER: [mk_pod("w0-a", index=0)]}
        a = tr.assess("default/job", job, pods, t0)
        d = a.decision_for(ReplicaType.WORKER, 0)
        assert d.action == ACTION_REPLACE and d.count == 1
        assert d.delay_s == 0.0 and a.requeue_after_s == 0.0
        # Second distinct failed pod: 2s backoff from the observation time.
        pods = {ReplicaType.WORKER: [mk_pod("w0-a", index=0),
                                     mk_pod("w0-b", index=0)]}
        a = tr.assess("default/job", job, pods, t0 + 10)
        d = a.decision_for(ReplicaType.WORKER, 0)
        assert d.action == ACTION_BACKOFF and d.count == 2
        assert d.delay_s == pytest.approx(2.0)
        assert d.remaining_s == pytest.approx(2.0)
        assert a.requeue_after_s == pytest.approx(2.0)
        # Mid-window: still waiting, remaining shrinks with the clock.
        a = tr.assess("default/job", job, pods, t0 + 11.5)
        d = a.decision_for(ReplicaType.WORKER, 0)
        assert d.action == ACTION_BACKOFF
        assert d.remaining_s == pytest.approx(0.5)
        # Window elapsed: replace.
        a = tr.assess("default/job", job, pods, t0 + 12.1)
        assert a.decision_for(ReplicaType.WORKER, 0).action == ACTION_REPLACE
        # Third failure: 4s (factor^1), seen at its own observation time.
        pods[ReplicaType.WORKER].append(mk_pod("w0-c", index=0))
        a = tr.assess("default/job", job, pods, t0 + 20)
        d = a.decision_for(ReplicaType.WORKER, 0)
        assert d.action == ACTION_BACKOFF and d.delay_s == pytest.approx(4.0)

    def test_jitter_is_deterministic_with_seeded_rng(self):
        def delays(seed):
            tr = RestartTracker(RestartPolicyConfig(
                initial_backoff_s=1.0, jitter=0.5),
                rng=random.Random(seed))
            job = mk_job()
            pods = {ReplicaType.WORKER: [mk_pod("a", index=0),
                                         mk_pod("b", index=0)]}
            a = tr.assess("default/job", job, pods, 0.0)
            return a.decision_for(ReplicaType.WORKER, 0).delay_s

        assert delays(42) == delays(42)
        d = delays(42)
        assert 1.0 <= d <= 1.5  # multiplicative jitter in [1, 1.5)x

    def test_streak_resets_after_healthy_running(self):
        tr = RestartTracker(RestartPolicyConfig(
            initial_backoff_s=1.0, jitter=0.0, reset_after_s=100.0))
        job = mk_job()
        key = "default/job"
        # Two failures -> streak 2.
        pods = {ReplicaType.WORKER: [mk_pod("a", index=0),
                                     mk_pod("b", index=0)]}
        tr.assess(key, job, pods, 0.0)
        # Replacement runs healthy past the reset window.
        run = {ReplicaType.WORKER: [mk_pod("c", index=0,
                                           phase=PHASE_RUNNING)]}
        tr.assess(key, job, run, 10.0)
        tr.assess(key, job, run, 200.0)  # >= reset_after_s of Running
        # Next failure: streak back to 1 -> immediate replace, but the
        # monotonic total keeps counting (status RESTARTS never decreases).
        pods = {ReplicaType.WORKER: [mk_pod("d", index=0)]}
        a = tr.assess(key, job, pods, 210.0)
        d = a.decision_for(ReplicaType.WORKER, 0)
        assert d.action == ACTION_REPLACE and d.streak == 1
        assert a.restarts_for(ReplicaType.WORKER) == 3

    def test_preempted_pods_are_exempt(self):
        tr = RestartTracker(RestartPolicyConfig(jitter=0.0))
        job = mk_job()
        pods = {ReplicaType.WORKER: [mk_pod(
            "a", index=0, reason="Preempted: evicted by gang x (class high)")]}
        a = tr.assess("default/job", job, pods, 0.0)
        assert a.decision_for(ReplicaType.WORKER, 0) is None
        assert a.restarts_for(ReplicaType.WORKER) == 0


# ---------------------------------------------------------------------------
# backoffLimit -> terminal Failed; restartPolicy Never -> terminal Failed
# ---------------------------------------------------------------------------

class TestTerminalPolicy:
    def test_backoff_limit_exceeded_fails_job_with_condition(self):
        tr = RestartTracker(RestartPolicyConfig(jitter=0.0))
        job = mk_job(backoff_limit=0)  # first failure is one too many
        pods = {ReplicaType.WORKER: [mk_pod("a", index=0),
                                     mk_pod("w1", index=1,
                                            phase=PHASE_RUNNING)]}
        a = tr.assess("default/job", job, pods, 0.0)
        d = a.decision_for(ReplicaType.WORKER, 0)
        assert d.action == ACTION_EXHAUSTED
        assert [(t, i) for t, i, _ in a.newly_exhausted] == \
            [(ReplicaType.WORKER, 0)]
        st = compute_status(job, pods, recovery=a)
        assert st.phase == TFJobPhase.FAILED
        assert st.reason.startswith("BackoffLimitExceeded")
        cond = next(c for c in st.conditions
                    if c.type == TFJobConditionType.RECOVERING)
        assert cond.status == "False"
        assert cond.reason == "BackoffLimitExceeded"
        # The edge only fires once: a second assess reports nothing new.
        a2 = tr.assess("default/job", job, pods, 1.0)
        assert a2.newly_exhausted == []

    def test_restart_policy_never_fails_with_policy_reason(self):
        job = mk_job(restart="Never", n=1)
        pods = {ReplicaType.WORKER: [mk_pod("a", index=0,
                                            reason="Error: exit 1: boom")]}
        st = compute_status(job, pods)
        assert st.phase == TFJobPhase.FAILED
        assert st.reason.startswith("RestartPolicyNever")
        cond = next(c for c in st.conditions
                    if c.type == TFJobConditionType.RECOVERING)
        assert cond.reason == "RestartPolicyNever"

    def test_restarts_surface_in_replica_status(self):
        tr = RestartTracker(RestartPolicyConfig(jitter=0.0))
        job = mk_job()
        pods = {ReplicaType.WORKER: [mk_pod("a", index=0),
                                     mk_pod("b", index=1)]}
        a = tr.assess("default/job", job, pods, 0.0)
        st = compute_status(job, pods, recovery=a)
        rs = next(r for r in st.tf_replica_statuses
                  if r.type == ReplicaType.WORKER)
        assert rs.restarts == 2


# ---------------------------------------------------------------------------
# Controller e2e: index-preserved re-create, events, gang generation
# ---------------------------------------------------------------------------

def wait_for(fn, timeout=15.0, interval=0.02):
    deadline = time.time() + timeout
    while time.time() < deadline:
        v = fn()
        if v:
            return v
        time.sleep(interval)
    raise AssertionError("condition not met within timeout")


@pytest.fixture
def rig():
    from kubeflow_controller_tpu.cluster import Cluster, FakeKubelet, PhasePolicy
    from kubeflow_controller_tpu.controller import Controller

    cluster = Cluster()
    kubelet = FakeKubelet(cluster, policy=PhasePolicy(run_s=3.0))
    ctrl = Controller(cluster, resync_period_s=0.5,
                      restart_config=RestartPolicyConfig(
                          initial_backoff_s=0.05, jitter=0.0))
    kubelet.start()
    ctrl.run(threadiness=2)
    yield cluster, ctrl, kubelet
    ctrl.stop()
    kubelet.stop()


def mk_sim_job(name, n=3, gang=False, backoff_limit=6):
    job = TFJob(metadata=ObjectMeta(name=name, namespace="default"))
    t = PodTemplateSpec()
    t.spec.containers.append(Container(name="c", image="img"))
    t.spec.restart_policy = "OnFailure"
    job.spec.backoff_limit = backoff_limit
    job.spec.tf_replica_specs = [TFReplicaSpec(
        replicas=n, tf_replica_type=ReplicaType.WORKER, template=t,
        gang_restart=gang)]
    return job


class TestControllerRecovery:
    def test_index_preserved_recreate_with_restart_event(self, rig):
        cluster, ctrl, kubelet = rig
        cluster.tfjobs.create(mk_sim_job("rec", n=3))
        wait_for(lambda: len(cluster.pods.list("default")) == 3)
        target = next(p for p in cluster.pods.list("default")
                      if p.metadata.labels[LABEL_INDEX] == "1")
        others = {p.metadata.name for p in cluster.pods.list("default")
                  if p.metadata.name != target.metadata.name}
        kubelet.set_phase("default", target.metadata.name, PHASE_FAILED,
                          reason="Error: exit 1: boom")

        def replaced():
            pods = [p for p in cluster.pods.list("default")
                    if p.metadata.labels[LABEL_INDEX] == "1"]
            return (pods and all(p.metadata.name != target.metadata.name
                                 for p in pods)) or None
        wait_for(replaced)
        # Index preserved, siblings untouched (no gang semantics here).
        assert others <= {p.metadata.name for p in cluster.pods.list("default")}
        evs = [e for e in ctrl.recorder.events_for("default", "rec")
               if e.reason == "ReplicaRestarted"]
        assert len(evs) == 1
        assert "Worker-1" in evs[0].message and "restart #1" in evs[0].message
        # RESTARTS lands on the status surface.
        wait_for(lambda: sum(
            rs.restarts for rs in cluster.tfjobs.get(
                "default", "rec").status.tf_replica_statuses) == 1)

    def test_restart_events_dedupe_per_index(self, rig):
        cluster, ctrl, kubelet = rig
        cluster.tfjobs.create(mk_sim_job("loop", n=2))
        wait_for(lambda: len(cluster.pods.list("default")) == 2)

        def fail_current_index0():
            pods = [p for p in cluster.pods.list("default")
                    if p.metadata.labels[LABEL_INDEX] == "0"
                    and p.status.phase == PHASE_RUNNING]
            if not pods:
                return None
            kubelet.set_phase("default", pods[0].metadata.name, PHASE_FAILED,
                              reason="Error: exit 1: crash loop")
            return pods[0].metadata.name

        first = wait_for(fail_current_index0)
        wait_for(lambda: next(
            (p for p in cluster.pods.list("default")
             if p.metadata.labels[LABEL_INDEX] == "0"
             and p.metadata.name != first
             and p.status.phase == PHASE_RUNNING), None))
        second = wait_for(fail_current_index0)
        assert second != first

        def one_aggregated_event():
            evs = [e for e in ctrl.recorder.events_for("default", "loop")
                   if e.reason == "ReplicaRestarted"]
            return (len(evs) == 1 and evs[0].count >= 2
                    and "restart #2" in evs[0].message) or None
        wait_for(one_aggregated_event)

    def test_backoff_limit_zero_terminal_failed_e2e(self, rig):
        cluster, ctrl, kubelet = rig
        cluster.tfjobs.create(mk_sim_job("spent", n=1, backoff_limit=0))
        wait_for(lambda: len(cluster.pods.list("default")) == 1)
        pod = cluster.pods.list("default")[0]
        kubelet.set_phase("default", pod.metadata.name, PHASE_FAILED,
                          reason="Error: exit 1: dead on arrival")
        wait_for(lambda: cluster.tfjobs.get("default", "spent").status.phase
                 == TFJobPhase.FAILED)
        j = cluster.tfjobs.get("default", "spent")
        assert j.status.reason.startswith("BackoffLimitExceeded")
        evs = [e for e in ctrl.recorder.events_for("default", "spent")
               if e.reason == "BackoffLimitExceeded"]
        assert len(evs) == 1
        # No replacement was created.
        assert len(cluster.pods.list("default")) == 1

    def test_gang_generation_bump_fans_out_to_replacements(self, rig):
        from kubeflow_controller_tpu.planner.materialize import (
            ENV_GANG_GENERATION,
        )

        cluster, ctrl, kubelet = rig
        cluster.tfjobs.create(mk_sim_job("gang", n=2, gang=True))
        wait_for(lambda: len([p for p in cluster.pods.list("default")
                              if p.status.phase == PHASE_RUNNING]) == 2)
        before = {p.metadata.name for p in cluster.pods.list("default")}
        victim = sorted(cluster.pods.list("default"),
                        key=lambda p: p.metadata.name)[0]
        kubelet.set_phase("default", victim.metadata.name, PHASE_FAILED,
                          reason="Error: exit -9: killed")

        def regenerated():
            pods = cluster.pods.list("default")
            fresh = [p for p in pods if p.metadata.name not in before]
            return len(fresh) == 2 or None
        wait_for(regenerated)
        # The WHOLE gang was replaced (gang semantics), the job's
        # generation annotation bumped, and every replacement carries it
        # as annotation + env.
        job = cluster.tfjobs.get("default", "gang")
        assert job.metadata.annotations[ANNOTATION_GANG_GENERATION] == "1"
        fresh = [p for p in cluster.pods.list("default")
                 if p.metadata.name not in before]
        assert len(fresh) == 2
        assert {p.metadata.labels[LABEL_INDEX] for p in fresh} == {"0", "1"}
        for p in fresh:
            assert p.metadata.annotations[ANNOTATION_GANG_GENERATION] == "1"
            env = {e.name: e.value for e in p.spec.containers[0].env}
            assert env[ENV_GANG_GENERATION] == "1"
        wait_for(lambda: cluster.tfjobs.get("default", "gang").status.phase
                 == TFJobPhase.SUCCEEDED, timeout=20.0)


# ---------------------------------------------------------------------------
# Checkpoint-resume: kill at step S ≡ uninterrupted; corrupt fallback
# ---------------------------------------------------------------------------

class TestCheckpointResume:
    def _setup(self):
        import jax
        import numpy as np

        from kubeflow_controller_tpu.models import mnist as m
        from kubeflow_controller_tpu.parallel import (
            AXIS_DATA,
            MeshSpec,
            build_mesh,
        )
        from kubeflow_controller_tpu.workloads import data as d
        from kubeflow_controller_tpu.workloads.trainer import (
            default_optimizer,
            global_batches,
            make_dist_step,
            numpy_opt_state,
            replicate_pytree,
        )

        mesh = build_mesh(MeshSpec(dp=-1, fsdp=1))
        opt = default_optimizer(5e-3)
        step = make_dist_step(lambda p, b: m.mlp_loss(p, b[0], b[1]), opt,
                              mesh, AXIS_DATA, donate=False)
        bs, spe = 16, 4
        x, y = d.synthetic_mnist_np(1, 64)
        idx = (np.arange(spe)[:, None] * bs
               + np.arange(bs)[None, :]) % x.shape[0]
        x_all, y_all = global_batches(
            mesh, AXIS_DATA, (x[idx], y[idx].astype(np.int32)), bs)

        def fresh_state():
            params = replicate_pytree(mesh, m.mlp_init(0))
            opt_state = replicate_pytree(
                mesh, numpy_opt_state(opt, m.mlp_init(0)))
            return params, opt_state

        return step, x_all, y_all, fresh_state, jax

    def test_kill_resume_matches_uninterrupted(self, tmp_path):
        import numpy as np

        from kubeflow_controller_tpu.workloads.checkpoint import (
            CheckpointManager,
        )
        from kubeflow_controller_tpu.workloads.trainer import (
            train_step_loop_dist,
        )

        step, x_all, y_all, fresh_state, jax = self._setup()
        steps, every, kill_at = 12, 5, 7

        # Uninterrupted run.
        p0, s0 = fresh_state()
        pa, _, _ = train_step_loop_dist(step, p0, s0, x_all, y_all, steps)

        # Interrupted run: train to the kill point with periodic saves...
        mgr = CheckpointManager(str(tmp_path / "ckpt"))
        p0, s0 = fresh_state()
        train_step_loop_dist(
            step, p0, s0, x_all, y_all, kill_at,
            checkpoint_every=every,
            checkpoint_fn=lambda s, p, o: mgr.save(s, p, o, wait=False))
        mgr.wait()
        # ...the process dies at step 7; the replacement restores the
        # latest checkpoint (step 5: lost steps <= the interval)...
        p1, s1 = fresh_state()
        p1, s1, start = mgr.restore(p1, s1)
        assert start == 5
        assert kill_at - start <= every  # lost work bounded by the interval
        # ...and resumes to completion: bitwise-identical final params.
        pb, _, _ = train_step_loop_dist(step, p1, s1, x_all, y_all, steps,
                                        start_step=start)
        for a, b in zip(jax.tree_util.tree_leaves(pa),
                        jax.tree_util.tree_leaves(pb)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_corrupt_latest_falls_back_to_previous_step(self, tmp_path):
        from kubeflow_controller_tpu.workloads.checkpoint import (
            CheckpointManager,
        )
        from kubeflow_controller_tpu.workloads.trainer import (
            train_step_loop_dist,
        )

        step, x_all, y_all, fresh_state, jax = self._setup()
        mgr = CheckpointManager(str(tmp_path / "ckpt"))
        p0, s0 = fresh_state()
        train_step_loop_dist(
            step, p0, s0, x_all, y_all, 11, checkpoint_every=5,
            checkpoint_fn=lambda s, p, o: mgr.save(s, p, o, wait=True))
        assert mgr.latest_step() == 10
        # Corrupt every file of the latest step (a SIGKILL-torn write).
        root = tmp_path / "ckpt" / "10"
        for dirpath, _, files in os.walk(root):
            for fn in files:
                with open(os.path.join(dirpath, fn), "wb") as fh:
                    fh.write(b"corrupt")
        p1, s1 = fresh_state()
        mgr2 = CheckpointManager(str(tmp_path / "ckpt"))
        p1, s1, start = mgr2.restore(p1, s1)
        assert start == 5          # fell back one interval
        assert not root.exists()   # the bad step was deleted, not retried

    def test_restore_raises_when_nothing_readable(self, tmp_path):
        from kubeflow_controller_tpu.workloads.checkpoint import (
            CheckpointManager,
        )

        step, x_all, y_all, fresh_state, jax = self._setup()
        mgr = CheckpointManager(str(tmp_path / "empty"))
        p, s = fresh_state()
        with pytest.raises(FileNotFoundError):
            mgr.restore(p, s)


# ---------------------------------------------------------------------------
# Stall detector: restore-phase hold
# ---------------------------------------------------------------------------

class TestRestoreHold:
    def _beat(self, step, t, phase="fit"):
        return PodProgress(step=step, phase=phase, timestamp=t)

    def test_step_decrease_enters_hold_until_forward_progress(self):
        tr = StallTracker(StallPolicy(heartbeat_deadline_s=0,
                                      step_deadline_s=10.0))
        k = "default/pod"
        assert tr.observe(k, self._beat(50, 0.0), now=0.0) is False
        # In-place restart: the counter jumps BACKWARD — not a stall.
        assert tr.observe(k, self._beat(5, 1.0), now=1.0) is False
        # Frozen at the restored step far past the deadline: still held
        # (mirrors the compile-phase hold; restore/rewind is not a wedge).
        assert tr.observe(k, self._beat(5, 30.0), now=30.0) is False
        assert tr.observe(k, self._beat(5, 60.0), now=60.0) is False
        # Forward progress releases the hold...
        assert tr.observe(k, self._beat(6, 61.0), now=61.0) is False
        # ...after which a genuine freeze past the deadline DOES fire.
        assert tr.observe(k, self._beat(6, 80.0), now=80.0) is True

    def test_restore_phase_holds_like_compile(self):
        tr = StallTracker(StallPolicy(heartbeat_deadline_s=0,
                                      step_deadline_s=10.0))
        k = "default/pod"
        assert tr.observe(k, self._beat(0, 0.0, "restore"), now=0.0) is False
        assert tr.observe(k, self._beat(0, 50.0, "restore"), now=50.0) is False
        # Training resumes, then freezes: the deadline applies again.
        assert tr.observe(k, self._beat(1, 51.0), now=51.0) is False
        assert tr.observe(k, self._beat(1, 70.0), now=70.0) is True

    def test_heartbeat_deadline_still_applies_during_restore(self):
        tr = StallTracker(StallPolicy(heartbeat_deadline_s=5.0,
                                      step_deadline_s=10.0))
        k = "default/pod"
        assert tr.observe(k, self._beat(0, 0.0, "restore"), now=0.0) is False
        # Beats STOPPED (stale timestamp): a dead restore is a stall.
        assert tr.observe(k, self._beat(0, 0.0, "restore"), now=30.0) is True


# ---------------------------------------------------------------------------
# Event recorder dedup_key
# ---------------------------------------------------------------------------

class TestEventDedup:
    def test_dedup_key_collapses_changing_messages(self):
        from kubeflow_controller_tpu.controller.events import EventRecorder

        rec = EventRecorder()
        job = mk_job("j1")
        rec.event(job, "Normal", "ReplicaRestarted",
                  "replica Worker-1 restart #1", dedup_key="Worker-1")
        rec.event(job, "Normal", "ReplicaRestarted",
                  "replica Worker-1 restart #2 after 0.25s backoff",
                  dedup_key="Worker-1")
        # A different replica is a different aggregate.
        rec.event(job, "Normal", "ReplicaRestarted",
                  "replica Worker-2 restart #1", dedup_key="Worker-2")
        evs = [e for e in rec.events_for("default", "j1")
               if e.reason == "ReplicaRestarted"]
        assert len(evs) == 2
        w1 = next(e for e in evs if e.dedup_key == "Worker-1")
        assert w1.count == 2
        assert "restart #2" in w1.message  # newest wording wins

    def test_without_dedup_key_distinct_messages_stay_distinct(self):
        from kubeflow_controller_tpu.controller.events import EventRecorder

        rec = EventRecorder()
        job = mk_job("j2")
        rec.event(job, "Normal", "X", "m1")
        rec.event(job, "Normal", "X", "m2")
        assert len(rec.events_for("default", "j2")) == 2


# ---------------------------------------------------------------------------
# Planner gating under decisions
# ---------------------------------------------------------------------------

class TestPlannerGate:
    def test_backoff_blocks_replacement_this_sync(self):
        from kubeflow_controller_tpu.planner import plan_job
        from kubeflow_controller_tpu.planner.types import Action
        from kubeflow_controller_tpu.recovery.policy import (
            RecoveryAssessment,
            RestartDecision,
        )

        job = mk_job(n=2)
        job.spec.runtime_id = "rid01"
        pods = {ReplicaType.WORKER: [mk_pod("a", index=0),
                                     mk_pod("w1", index=1,
                                            phase=PHASE_RUNNING)]}
        waiting = RecoveryAssessment(decisions={
            (ReplicaType.WORKER, 0): RestartDecision(ACTION_BACKOFF,
                                                     remaining_s=1.0)})
        plan = plan_job(job, pods, {}, waiting)
        assert [e for e in plan.events
                if e.action in (Action.ADD_POD, Action.DELETE_POD)] == []
        # Once the window closes the same plan replaces index-preserved.
        ready = RecoveryAssessment(decisions={
            (ReplicaType.WORKER, 0): RestartDecision(ACTION_REPLACE)})
        plan = plan_job(job, pods, {}, ready)
        acts = [(e.action, e.index) for e in plan.events
                if e.action in (Action.ADD_POD, Action.DELETE_POD)]
        assert (Action.DELETE_POD, 0) in acts and (Action.ADD_POD, 0) in acts
        assert (Action.ADD_POD, 1) not in acts

    def test_gang_waits_out_worst_member_and_exhausts_as_a_unit(self):
        from kubeflow_controller_tpu.planner import plan_job
        from kubeflow_controller_tpu.planner.types import Action
        from kubeflow_controller_tpu.recovery.policy import (
            RecoveryAssessment,
            RestartDecision,
        )

        job = mk_job(n=2, gang=True)
        job.spec.runtime_id = "rid02"
        pods = {ReplicaType.WORKER: [mk_pod("a", index=0),
                                     mk_pod("w1", index=1,
                                            phase=PHASE_RUNNING)]}
        waiting = RecoveryAssessment(decisions={
            (ReplicaType.WORKER, 0): RestartDecision(ACTION_BACKOFF,
                                                     remaining_s=1.0)})
        plan = plan_job(job, pods, {}, waiting)
        assert [e for e in plan.events
                if e.action in (Action.ADD_POD, Action.DELETE_POD)] == []
        spent = RecoveryAssessment(decisions={
            (ReplicaType.WORKER, 0): RestartDecision(ACTION_EXHAUSTED)})
        plan = plan_job(job, pods, {}, spent)
        assert [e for e in plan.events
                if e.action in (Action.ADD_POD, Action.DELETE_POD)] == []
        ready = RecoveryAssessment(decisions={
            (ReplicaType.WORKER, 0): RestartDecision(ACTION_REPLACE)})
        plan = plan_job(job, pods, {}, ready)
        dels = [e for e in plan.events if e.action == Action.DELETE_POD]
        adds = [e for e in plan.events if e.action == Action.ADD_POD]
        # Whole gang: the survivor is torn down too, both indices recreated.
        assert {e.name for e in dels} == {"a", "w1"}
        assert {e.index for e in adds} == {0, 1}


# ---------------------------------------------------------------------------
# Gang guard (rendezvous module)
# ---------------------------------------------------------------------------

class TestGangGuard:
    def test_peer_death_detected_clean_done_is_not(self, tmp_path):
        from kubeflow_controller_tpu.recovery import GangGuard

        broken = []
        g0 = GangGuard(str(tmp_path), "gang", member=0, peers=2,
                       interval_s=0.05, timeout_s=0.6,
                       on_broken=broken.append)
        g1 = GangGuard(str(tmp_path), "gang", member=1, peers=2,
                       interval_s=0.05, timeout_s=0.6,
                       on_broken=lambda m: None)
        g0.start(), g1.start()
        try:
            time.sleep(0.4)
            assert broken == []  # both beating: healthy
            # Member 1 finishes CLEANLY: silence after a done marker must
            # not read as death.
            g1.mark_done()
            time.sleep(0.9)
            assert broken == []
            # A new gang where the peer dies WITHOUT the marker: detected.
            broken2 = []
            h0 = GangGuard(str(tmp_path), "gang2", member=0, peers=2,
                           interval_s=0.05, timeout_s=0.3,
                           on_broken=broken2.append)
            h1 = GangGuard(str(tmp_path), "gang2", member=1, peers=2,
                           interval_s=0.05, timeout_s=0.3,
                           on_broken=lambda m: None)
            h0.start(), h1.start()
            time.sleep(0.2)
            h1.stop()  # heartbeat stops, no done marker — "SIGKILL"
            wait_for(lambda: broken2 == [1], timeout=5.0)
            h0.stop()
        finally:
            g0.stop(), g1.stop()

    def test_generation_scopes_the_files(self, tmp_path):
        from kubeflow_controller_tpu.recovery import GangGuard

        a = GangGuard(str(tmp_path), "g", member=0, peers=2, generation=0)
        b = GangGuard(str(tmp_path), "g", member=0, peers=2, generation=1)
        assert a.alive_file(0) != b.alive_file(0)
        assert "g1" in os.path.basename(b.alive_file(0))
