"""Sharded store (PR 6): per-kind lock shards, write-time snapshots with
copy-outside-the-lock reads, bounded watcher queues with overflow-resume,
single-acquisition list_with_rv, the serde fast copier, and the
FakeAPIServer's handler-level read concurrency.

The invariants under test are the ones the shard rebuild must NOT change:
everything in tests/test_watch_resume.py (replay exactly-once, per-kind
ordering, 410 semantics) plus the new ones it adds — cross-kind
independence, snapshot isolation, and zero-loss overflow recovery.
"""

import threading
import time

import pytest

from kubeflow_controller_tpu.api.core import Container, Pod, PodTemplateSpec
from kubeflow_controller_tpu.api.meta import ObjectMeta
from kubeflow_controller_tpu.api.tfjob import ReplicaType, TFJob, TFReplicaSpec
from kubeflow_controller_tpu.cluster.apiserver import FakeAPIServer
from kubeflow_controller_tpu.cluster.rest import Kubeconfig, RestCluster
from kubeflow_controller_tpu.cluster.store import ADDED, ObjectStore
from kubeflow_controller_tpu.utils import locks
from kubeflow_controller_tpu.obs.metrics import (
    REGISTRY,
    bucket_quantile,
    validate_exposition,
)
from kubeflow_controller_tpu.utils import serde


def mk_pod(name, ns="default", labels=None):
    pod = Pod(metadata=ObjectMeta(name=name, namespace=ns))
    pod.metadata.labels = labels or {}
    return pod


def mk_job(name):
    job = TFJob(metadata=ObjectMeta(name=name, namespace="default"))
    t = PodTemplateSpec()
    t.spec.containers.append(Container(name="tensorflow", image="img"))
    t.spec.restart_policy = "OnFailure"
    job.spec.tf_replica_specs.append(
        TFReplicaSpec(replicas=2, tf_replica_type=ReplicaType.WORKER,
                      template=t))
    return job


def wait_for(fn, timeout=15.0, interval=0.01):
    deadline = time.time() + timeout
    while time.time() < deadline:
        v = fn()
        if v:
            return v
        time.sleep(interval)
    raise AssertionError("condition not met within timeout")


# ---------------------------------------------------------------------------
# Shard independence
# ---------------------------------------------------------------------------


class TestShardIndependence:
    def test_cross_kind_writers_never_block_each_other(self):
        """A writer stalled inside one kind's critical section (patch_meta
        holds the shard lock through its callback) must not delay writes
        to another kind — the per-kind-locks contract, asserted on the
        clock."""
        s = ObjectStore()
        s.create("pods", mk_pod("p"))
        entered = threading.Event()

        def slow_patch(meta):
            entered.set()
            with locks.blocking_ok():  # deliberate stall under the shard lock
                time.sleep(0.5)
            meta.labels["patched"] = "yes"

        t = threading.Thread(
            target=lambda: s.patch_meta("pods", "default", "p", slow_patch),
            daemon=True)
        t.start()
        assert entered.wait(5.0)
        t0 = time.perf_counter()
        s.create("services", mk_pod("svc"))
        s.get("services", "default", "svc")
        s.list("services", "default")
        elapsed = time.perf_counter() - t0
        t.join(timeout=5.0)
        assert elapsed < 0.25, (
            f"cross-kind ops took {elapsed:.3f}s while pods shard was held")
        # Sanity: the slow patch did land.
        assert s.get("pods", "default", "p").metadata.labels["patched"] == "yes"

    def test_global_lock_baseline_does_serialize(self):
        """sharded=False is the pre-shard baseline: the same cross-kind
        write DOES wait for the stalled shard (one lock for everything) —
        the contrast store-smoke measures."""
        s = ObjectStore(sharded=False)
        s.create("pods", mk_pod("p"))
        entered = threading.Event()

        def slow_patch(meta):
            entered.set()
            with locks.blocking_ok():  # deliberate stall under the global lock
                time.sleep(0.4)

        t = threading.Thread(
            target=lambda: s.patch_meta("pods", "default", "p", slow_patch),
            daemon=True)
        t.start()
        assert entered.wait(5.0)
        t0 = time.perf_counter()
        s.create("services", mk_pod("svc"))
        elapsed = time.perf_counter() - t0
        t.join(timeout=5.0)
        assert elapsed > 0.2, "baseline store should have serialized"

    def test_rv_still_globally_monotonic_across_kinds(self):
        s = ObjectStore()
        rvs = []
        for i in range(5):
            rvs.append(int(s.create("pods", mk_pod(f"p{i}"))
                           .metadata.resource_version))
            rvs.append(int(s.create("services", mk_pod(f"s{i}"))
                           .metadata.resource_version))
        assert rvs == sorted(rvs) and len(set(rvs)) == len(rvs)

    def test_per_kind_replay_ordering_under_concurrent_cross_kind_writes(self):
        """Writers hammering two kinds concurrently: each kind's replay is
        exactly its own writes after the resume point, in per-kind write
        order — cross-kind interleaving never leaks into a shard's ring."""
        s = ObjectStore()
        s.create("pods", mk_pod("seed-pod"))
        s.create("services", mk_pod("seed-svc"))
        _, since_pods = s.list_with_rv("pods")
        _, since_svcs = s.list_with_rv("services")
        written = {"pods": [], "services": []}
        barrier = threading.Barrier(2)

        def writer(kind):
            barrier.wait()
            for i in range(40):
                out = s.create(kind, mk_pod(f"{kind}-{i:03d}"))
                written[kind].append(int(out.metadata.resource_version))

        threads = [threading.Thread(target=writer, args=(k,))
                   for k in ("pods", "services")]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=10.0)

        for kind, since in (("pods", since_pods), ("services", since_svcs)):
            w = s.watch(kind, since_rv=since)
            try:
                got = []
                while len(got) < 40:
                    ev = w.next(timeout=2.0)
                    assert ev is not None, f"{kind}: replay ended early"
                    got.append(int(ev.object.metadata.resource_version))
                assert got == written[kind], f"{kind}: replay != write order"
            finally:
                w.stop()


# ---------------------------------------------------------------------------
# Snapshot isolation
# ---------------------------------------------------------------------------


class TestSnapshotIsolation:
    def test_mutating_read_results_never_leaks_into_store(self):
        s = ObjectStore()
        s.create("pods", mk_pod("p", labels={"a": "1"}))
        got = s.get("pods", "default", "p")
        got.metadata.labels["evil"] = "yes"
        got.status.phase = "Hacked"
        listed = s.list("pods", "default")[0]
        listed.metadata.labels["evil2"] = "yes"
        fresh = s.get("pods", "default", "p")
        assert "evil" not in fresh.metadata.labels
        assert "evil2" not in fresh.metadata.labels
        assert fresh.status.phase != "Hacked"

    def test_mutating_caller_object_after_write_never_leaks(self):
        """Write-time copy: the store snapshots on create/update, so the
        caller keeping (and mutating) its handle cannot corrupt the store
        OR any watch event already fanned out."""
        s = ObjectStore()
        w = s.watch("pods")
        try:
            p = mk_pod("p")
            s.create("pods", p)
            p.metadata.labels["late"] = "mutation"
            ev = w.next(timeout=2.0)
            assert ev is not None and ev.type == ADDED
            assert "late" not in ev.object.metadata.labels
            assert "late" not in s.get("pods", "default", "p").metadata.labels
        finally:
            w.stop()

    def test_snapshot_reads_share_the_stored_object(self):
        """get_snapshot/list_snapshot_with_rv are the zero-copy wire reads:
        repeated calls hand back the SAME immutable snapshot (no copy),
        while get() copies every time."""
        s = ObjectStore()
        s.create("pods", mk_pod("p"))
        assert (s.get_snapshot("pods", "default", "p")
                is s.get_snapshot("pods", "default", "p"))
        assert s.get("pods", "default", "p") is not s.get("pods", "default", "p")
        snap_items, _ = s.list_snapshot_with_rv("pods", "default")
        assert snap_items[0] is s.get_snapshot("pods", "default", "p")
        # A write swaps in a NEW snapshot; the old reference stays frozen.
        old = s.get_snapshot("pods", "default", "p")
        upd = s.get("pods", "default", "p")
        upd.status.phase = "Running"
        s.update("pods", upd)
        assert old.status.phase != "Running"
        assert s.get_snapshot("pods", "default", "p") is not old

    def test_subresource_writes_are_copy_on_write(self):
        """update_status/patch_meta/mark_deleting must never mutate the
        stored snapshot in place — a reader holding the old reference sees
        the old world forever."""
        s = ObjectStore()
        s.create("pods", mk_pod("p"))
        before = s.get_snapshot("pods", "default", "p")
        rv_before = before.metadata.resource_version
        upd = s.get("pods", "default", "p")
        upd.status.phase = "Running"
        s.update_status("pods", upd)
        s.patch_meta("pods", "default", "p",
                     lambda m: m.labels.update({"x": "y"}))
        s.mark_deleting("pods", "default", "p")
        assert before.metadata.resource_version == rv_before
        assert before.status.phase != "Running"
        assert "x" not in before.metadata.labels
        assert before.metadata.deletion_timestamp is None


# ---------------------------------------------------------------------------
# list_with_rv: snapshot + RV under one acquisition
# ---------------------------------------------------------------------------


def test_list_with_rv_never_drifts_from_snapshot_under_concurrent_writes():
    """The RV must be a resume point for EXACTLY the returned snapshot:
    names(snapshot) + names(replay after rv) == everything ever written,
    with no overlap — for every interleaving a concurrent writer can
    produce.  (The old implementation re-entered the lock via nested
    list(), letting writes slip between snapshot and RV.)"""
    s = ObjectStore()
    stop = threading.Event()
    written = []
    n_max = 600  # stay inside the 1024-event watch cache so replays can't 410

    def writer():
        for i in range(n_max):
            if stop.is_set():
                return
            s.create("pods", mk_pod(f"w{i:04d}"))
            written.append(f"w{i:04d}")

    t = threading.Thread(target=writer, daemon=True)
    t.start()
    try:
        snapshots = []
        for _ in range(20):
            snapshots.append(s.list_with_rv("pods"))
            time.sleep(0.002)
    finally:
        stop.set()
        t.join(timeout=10.0)

    all_written = set(written)
    for items, rv in snapshots:
        names = {p.metadata.name for p in items}
        assert all(int(p.metadata.resource_version) <= int(rv) for p in items)
        w = s.watch("pods", since_rv=rv)
        try:
            replayed = set()
            while True:
                ev = w.next(timeout=0.05)
                if ev is None:
                    break
                replayed.add(ev.object.metadata.name)
        finally:
            w.stop()
        # Replay is verified after the writer stopped, so snapshot + replay
        # must partition everything ever written: overlap means the RV ran
        # ahead of the snapshot; a hole means a write slipped between them.
        assert names.isdisjoint(replayed), "RV replays events already listed"
        assert names | replayed == all_written, \
            "a write fell between the snapshot and its RV"


# ---------------------------------------------------------------------------
# Bounded watcher queues: overflow -> dropped stream -> resume, zero loss
# ---------------------------------------------------------------------------


class TestBoundedWatchQueues:
    def test_overflow_auto_resume_zero_loss_in_order(self):
        """A slow in-process consumer overflows its bounded queue: the
        store drops the stream, the next next() re-subscribes from the
        last delivered RV and the watch cache replays the window — every
        event arrives exactly once, in order, with no gap."""
        s = ObjectStore()
        w = s.watch("pods", max_queue=8)
        n = 100
        for i in range(n):
            s.create("pods", mk_pod(f"p{i:03d}"))
        got = []
        while len(got) < n:
            ev = w.next(timeout=2.0)
            if ev is None:
                break
            got.append(ev.object.metadata.name)
        w.stop()
        assert got == [f"p{i:03d}" for i in range(n)]
        assert w.gaps == 0
        stats = s.lock_wait_stats()["pods"]
        assert stats["overflows"] >= 1, "the bound never tripped"

    def test_overflow_past_watch_cache_becomes_gap(self):
        """If the overflow window outruns the bounded watch cache the
        resume is impossible (the in-process 410): `gaps` bumps so cache
        consumers know to re-list, then the stream is live again."""
        s = ObjectStore(watch_cache_size=4)
        w = s.watch("pods", max_queue=2)
        for i in range(30):
            s.create("pods", mk_pod(f"p{i:03d}"))
        seen = 0
        while w.next(timeout=0.2) is not None:
            seen += 1
        assert w.gaps >= 1
        assert seen < 30, "everything arrived despite an evicted window?"
        # Live again after the gap.
        s.create("pods", mk_pod("after-gap"))
        ev = wait_for(lambda: w.next(timeout=0.5))
        assert ev.object.metadata.name == "after-gap"
        w.stop()

    def test_overflow_closes_non_resuming_stream_for_client_driven_resume(self):
        """auto_resume=False (what the API server's stream handler uses):
        overflow drains the buffered prefix then ends the stream with
        `dropped` set; a NEW watch from the consumer's last RV replays the
        rest — the server half of the REST reconnect contract."""
        s = ObjectStore()
        w = s.watch("pods", max_queue=5, auto_resume=False)
        n = 40
        for i in range(n):
            s.create("pods", mk_pod(f"p{i:03d}"))
        first, last_rv = [], 0
        while True:
            ev = w.next(timeout=0.5)
            if ev is None:
                break
            first.append(ev.object.metadata.name)
            last_rv = int(ev.object.metadata.resource_version)
        assert w.dropped
        assert 0 < len(first) < n
        w.stop()
        w2 = s.watch("pods", since_rv=str(last_rv))
        rest = []
        while len(first) + len(rest) < n:
            ev = w2.next(timeout=2.0)
            assert ev is not None, "replay ended before recovering the window"
            rest.append(ev.object.metadata.name)
        w2.stop()
        assert first + rest == [f"p{i:03d}" for i in range(n)]

    @pytest.mark.slow
    def test_rest_e2e_server_overflow_reconnects_with_zero_loss(self):
        """Full wire e2e: a slow REST consumer backpressures TCP until the
        SERVER-side bounded watcher queue overflows; the server closes the
        stream, the RV-resuming client reconnects, the watch cache replays
        — every event exactly once, no informer-visible gap."""
        store = ObjectStore(watch_queue_size=8)
        server = FakeAPIServer(store)
        url = server.start()
        rest = RestCluster(Kubeconfig(server=url))
        w = rest.pods.watch("default")
        # Choke the client: its local queue now backpressures after 2
        # events, stalling the chunked read so TCP fills server-side.
        w.queue.maxsize = 2
        n, blob = 150, "x" * 40_000  # big events defeat socket buffering
        try:
            for i in range(n):
                store.create("pods", mk_pod(f"p{i:03d}",
                                            labels={"blob": blob}))
            wait_for(lambda: store.lock_wait_stats()["pods"]["overflows"] >= 1,
                     timeout=30.0)
            got = []
            while len(got) < n:
                ev = w.next(timeout=10.0)
                assert ev is not None, (
                    f"stream dried up at {len(got)}/{n} events")
                got.append(ev.object.metadata.name)
            assert got == [f"p{i:03d}" for i in range(n)]
            assert w.gaps == 0, "resume degraded to a gap"
        finally:
            w.stop()
            rest.close()
            server.stop()


# ---------------------------------------------------------------------------
# FakeAPIServer: handler-level read concurrency
# ---------------------------------------------------------------------------


def test_apiserver_parallel_lists_of_different_kinds_do_not_queue():
    """A LIST of one kind stalled behind that kind's shard (writer holding
    the lock) must not delay a LIST of another kind over HTTP — the
    handler threads share no store lock."""
    store = ObjectStore()
    server = FakeAPIServer(store)
    url = server.start()
    rest = RestCluster(Kubeconfig(server=url))
    try:
        store.create("tfjobs", mk_job("j"))
        for i in range(5):
            store.create("pods", mk_pod(f"p{i}"))
        entered = threading.Event()

        def slow_patch(meta):
            entered.set()
            with locks.blocking_ok():  # deliberate stall under the shard lock
                time.sleep(0.6)

        t = threading.Thread(
            target=lambda: store.patch_meta("tfjobs", "default", "j",
                                            slow_patch),
            daemon=True)
        t.start()
        assert entered.wait(5.0)
        t0 = time.perf_counter()
        pods = rest.pods.list("default")
        elapsed = time.perf_counter() - t0
        t.join(timeout=5.0)
        assert len(pods) == 5
        assert elapsed < 0.4, (
            f"pods LIST waited {elapsed:.3f}s behind the tfjobs shard")
    finally:
        rest.close()
        server.stop()


# ---------------------------------------------------------------------------
# Serde fast path
# ---------------------------------------------------------------------------


class TestSerdeFastPath:
    def _rich_job(self):
        job = mk_job("rich")
        job.metadata.labels = {"a": "1", "b": "2"}
        job.metadata.annotations = {"note": "x" * 100}
        job.spec.tf_replica_specs[0].template.spec.containers[0].command = [
            "python", "-m", "train"]
        return job

    def test_fast_copy_matches_deepcopy(self):
        job = self._rich_job()
        fast = serde.deep_copy(job)
        slow = serde.slow_deep_copy(job)
        assert serde.to_dict(fast) == serde.to_dict(slow) == serde.to_dict(job)

    def test_fast_copy_isolates_every_level(self):
        job = self._rich_job()
        cp = serde.deep_copy(job)
        cp.metadata.labels["a"] = "mutated"
        cp.spec.tf_replica_specs[0].replicas = 99
        cp.spec.tf_replica_specs[0].template.spec.containers[0].command.append(
            "--extra")
        assert job.metadata.labels["a"] == "1"
        assert job.spec.tf_replica_specs[0].replicas == 2
        assert (job.spec.tf_replica_specs[0].template.spec.containers[0]
                .command == ["python", "-m", "train"])

    def test_fast_copy_preserves_enum_identity(self):
        job = self._rich_job()
        cp = serde.deep_copy(job)
        assert cp.spec.tf_replica_specs[0].tf_replica_type is ReplicaType.WORKER

    def test_str_enum_still_serializes_to_value(self):
        # The to_dict scalar fast path must not catch str-subclassing enums.
        d = serde.to_dict(self._rich_job())
        assert d["spec"]["tfReplicaSpecs"][0]["tfReplicaType"] == "Worker"


# ---------------------------------------------------------------------------
# Lock-wait instrumentation
# ---------------------------------------------------------------------------


class TestLockWaitMetrics:
    def test_lock_wait_stats_shape_and_counts(self):
        s = ObjectStore()
        for i in range(10):
            s.create("pods", mk_pod(f"p{i}"))
        s.list("pods")
        stats = s.lock_wait_stats()
        assert "pods" in stats
        st = stats["pods"]
        assert st["acquires"] >= 11
        for key in ("contended", "overflows", "wait_sum_s", "wait_max_s",
                    "p50_s", "p99_s"):
            assert key in st

    def test_store_families_render_and_validate(self):
        s = ObjectStore()
        s.create("pods", mk_pod("p"))
        s.create("services", mk_pod("svc"))
        text = REGISTRY.render()
        assert validate_exposition(text) == [], validate_exposition(text)[:5]
        assert "kctpu_store_lock_wait_seconds_bucket" in text
        assert 'kctpu_store_shard_depth{kind="pods"}' in text
        assert "kctpu_watch_queue_depth" in text
        assert "kctpu_watch_queue_overflows_total" in text

    def test_bucket_quantile(self):
        uppers = (0.001, 0.01, 0.1)
        assert bucket_quantile(uppers, [0, 0, 0, 0], 0.5) == 0.0
        assert bucket_quantile(uppers, [10, 0, 0, 0], 0.99) == 0.001
        assert bucket_quantile(uppers, [50, 49, 0, 1], 0.5) == 0.001
        assert bucket_quantile(uppers, [50, 49, 0, 1], 0.99) == 0.01
        assert bucket_quantile(uppers, [0, 0, 0, 5], 0.5) == 0.2  # +Inf slot
