"""HA control plane: durable WAL store, lease-based leader election with
fencing, consistent-hash sharded controller workers (ISSUE 12).

Covers the acceptance surface:

- WAL replay exactness: RV-identical store rebuild, watch-cache resume
  still works post-restart, snapshot+compaction equivalence;
- torn/corrupt tail-record truncation (crash mid-append);
- split-brain rejection via the fencing token (in-process AND REST);
- ring rebalance loses zero jobs (handoff drains in-flight syncs and
  replays expectations);
- lease protocol edges (elect, renew, depose, release) and failover
  bounds;
- deterministic FakeAPIServer shutdown (streams closed, WAL flushed);
- the `kctpu vet` fencing-token rule against its paired fixtures;
- the crash-restart deterministic-simulation seed (PR-11 checkers across
  a recover boundary).
"""

import os
import struct
import time

import pytest

from kubeflow_controller_tpu.api.core import Container, Pod, PodTemplateSpec
from kubeflow_controller_tpu.api.meta import ObjectMeta
from kubeflow_controller_tpu.api.tfjob import (
    ReplicaType,
    TFJob,
    TFJobPhase,
    TFReplicaSpec,
)
from kubeflow_controller_tpu.cluster import Cluster, FakeKubelet, PhasePolicy
from kubeflow_controller_tpu.cluster.store import (
    FencingError,
    ObjectStore,
    TooOldResourceVersion,
)
from kubeflow_controller_tpu.ha.ring import HashRing, shard_of
from kubeflow_controller_tpu.ha.wal import MAGIC, WALRecord, WriteAheadLog
from kubeflow_controller_tpu.ha.lease import LeaseManager


def mk_pod(name, ns="default"):
    return Pod(metadata=ObjectMeta(name=name, namespace=ns))


def mk_sim_job(name):
    job = TFJob(metadata=ObjectMeta(name=name, namespace="default"))
    for typ, n in ((ReplicaType.PS, 1), (ReplicaType.WORKER, 2)):
        t = PodTemplateSpec()
        t.spec.containers.append(Container(name="tensorflow", image="img"))
        t.spec.restart_policy = "OnFailure"
        job.spec.tf_replica_specs.append(
            TFReplicaSpec(replicas=n, tf_replica_type=typ, template=t))
    return job


def wait_until(fn, timeout=10.0, every=0.01):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fn():
            return True
        time.sleep(every)
    return fn()


# ---------------------------------------------------------------------------
# WAL: replay exactness
# ---------------------------------------------------------------------------

class TestWALReplay:
    def _loaded_store(self, wal):
        s = ObjectStore(wal=wal)
        s.create("pods", mk_pod("a"))
        p = s.get("pods", "default", "a")
        p.status.phase = "Running"
        s.update("pods", p)
        s.create("services", mk_pod("svc-a"))
        s.create("pods", mk_pod("b"))
        s.delete("pods", "default", "b", cascade=False)
        s.patch_meta("pods", "default", "a",
                     lambda m: m.labels.__setitem__("k", "v"))
        return s

    def test_replay_rebuilds_rv_identical_store(self, tmp_path):
        wal = WriteAheadLog(str(tmp_path), fsync=True)
        s = self._loaded_store(wal)
        wal.flush()
        s2 = ObjectStore.recover(WriteAheadLog(str(tmp_path), fsync=False))
        assert s2.export_state() == s.export_state()
        assert s2._rv == s._rv and s2._uid == s._uid

    def test_uid_counter_restored_no_reuse(self, tmp_path):
        wal = WriteAheadLog(str(tmp_path), fsync=False)
        s = self._loaded_store(wal)
        uids = {s.get("pods", "default", "a").metadata.uid}
        s2 = ObjectStore.recover(WriteAheadLog(str(tmp_path), fsync=False))
        created = s2.create("pods", mk_pod("c"))
        assert created.metadata.uid not in uids
        assert int(created.metadata.uid[4:]) > max(
            int(u[4:]) for u in uids)

    def test_watch_resume_across_restart(self, tmp_path):
        """A client that saw rv N before the crash resumes against the
        recovered store and replays exactly the events after N."""
        wal = WriteAheadLog(str(tmp_path), fsync=False)
        s = ObjectStore(wal=wal)
        s.create("pods", mk_pod("a"))          # rv 1
        client_rv = int(s.get("pods", "default", "a").metadata.resource_version)
        p = s.get("pods", "default", "a")
        p.status.phase = "Running"
        s.update("pods", p)                    # rv 2 — client missed this
        s.create("pods", mk_pod("b"))          # rv 3 — and this
        s2 = ObjectStore.recover(WriteAheadLog(str(tmp_path), fsync=False))
        w = s2.watch("pods", since_rv=str(client_rv))
        got = []
        while True:
            ev = w.next(timeout=0.05)
            if ev is None:
                break
            got.append((int(ev.object.metadata.resource_version), ev.type))
        assert got == [(2, "MODIFIED"), (3, "ADDED")]
        # Live events keep flowing after the replayed prefix.
        s2.create("pods", mk_pod("c"))
        ev = w.next(timeout=1.0)
        assert ev is not None and ev.type == "ADDED"
        w.stop()

    def test_snapshot_compaction_equivalence(self, tmp_path):
        wal = WriteAheadLog(str(tmp_path), fsync=False)
        s = self._loaded_store(wal)
        full_state = s.export_state()
        kept = s.compact_wal()
        # Everything the snapshot covers left the log.
        assert kept == 0
        s.create("pods", mk_pod("post-compact"))
        after_state = s.export_state()
        s2 = ObjectStore.recover(WriteAheadLog(str(tmp_path), fsync=False))
        assert s2.export_state() == after_state
        assert s2.export_state() != full_state  # post-compact write present

    def test_resume_below_snapshot_is_410(self, tmp_path):
        wal = WriteAheadLog(str(tmp_path), fsync=False)
        s = self._loaded_store(wal)
        s.compact_wal()
        s2 = ObjectStore.recover(WriteAheadLog(str(tmp_path), fsync=False))
        with pytest.raises(TooOldResourceVersion):
            s2.watch("pods", since_rv="1")

    def test_unfenced_store_has_no_wal(self, tmp_path):
        s = ObjectStore()
        s.create("pods", mk_pod("a"))
        with pytest.raises(RuntimeError):
            s.compact_wal()
        s.flush_wal()  # no-op, must not raise


# ---------------------------------------------------------------------------
# WAL: torn/corrupt tails
# ---------------------------------------------------------------------------

class TestWALTornTail:
    def _write_three(self, tmp_path):
        wal = WriteAheadLog(str(tmp_path), fsync=False)
        s = ObjectStore(wal=wal)
        for name in ("a", "b", "c"):
            s.create("pods", mk_pod(name))
        wal.close()
        return os.path.join(str(tmp_path), "wal.log")

    def test_torn_tail_truncated_earlier_records_survive(self, tmp_path):
        path = self._write_three(tmp_path)
        size = os.path.getsize(path)
        with open(path, "r+b") as fh:
            fh.truncate(size - 7)  # crash mid-append: tear the last frame
        wal = WriteAheadLog(str(tmp_path), fsync=False)
        records = wal.replay()
        assert [r.obj["metadata"]["name"] for r in records] == ["a", "b"]
        # The file was truncated to the last good frame: a fresh append
        # after the tear parses cleanly.
        s2 = ObjectStore.recover(WriteAheadLog(str(tmp_path), fsync=False))
        s2.create("pods", mk_pod("d"))
        s2.flush_wal()
        names = [r.obj["metadata"]["name"]
                 for r in WriteAheadLog(str(tmp_path), fsync=False).replay()]
        assert names == ["a", "b", "d"]

    def test_corrupt_crc_tail_truncated(self, tmp_path):
        path = self._write_three(tmp_path)
        with open(path, "r+b") as fh:
            fh.seek(-3, os.SEEK_END)
            fh.write(b"\xff\xff\xff")  # flip payload bytes: CRC mismatch
        records = WriteAheadLog(str(tmp_path), fsync=False).replay()
        assert [r.obj["metadata"]["name"] for r in records] == ["a", "b"]

    def test_bad_magic_is_hard_error(self, tmp_path):
        path = os.path.join(str(tmp_path), "wal.log")
        with open(path, "wb") as fh:
            fh.write(b"NOTAWAL!!!")
        from kubeflow_controller_tpu.ha.wal import WALError

        with pytest.raises(WALError):
            WriteAheadLog(str(tmp_path), fsync=False).replay()

    def test_corrupt_snapshot_falls_back_to_older(self, tmp_path):
        wal = WriteAheadLog(str(tmp_path), fsync=False)
        s = ObjectStore(wal=wal)
        s.create("pods", mk_pod("a"))
        s.compact_wal()
        s.create("pods", mk_pod("b"))
        s.compact_wal()
        snaps = sorted(n for n in os.listdir(str(tmp_path))
                       if n.startswith("snap-"))
        assert len(snaps) == 2
        with open(os.path.join(str(tmp_path), snaps[-1]), "w") as fh:
            fh.write("{ not json")
        s2 = ObjectStore.recover(WriteAheadLog(str(tmp_path), fsync=False))
        # Older snapshot + nothing newer in the log: "b" was only in the
        # corrupt snapshot's window... but compaction keeps the records
        # after the OLDER snapshot in the log only until the second
        # compaction rewrote it.  What MUST hold: recovery neither crashes
        # nor invents state, and everything in the older snapshot is back.
        assert s2.get("pods", "default", "a").metadata.name == "a"

    def test_record_framing_roundtrip(self, tmp_path):
        wal = WriteAheadLog(str(tmp_path), fsync=False)
        pod = mk_pod("x")
        pod.metadata.resource_version = "7"
        wal.append(7, "ADDED", "pods", pod)
        (rec,) = wal.replay()
        assert isinstance(rec, WALRecord)
        assert (rec.rv, rec.ev, rec.kind) == (7, "ADDED", "pods")
        obj = rec.materialize()
        assert isinstance(obj, Pod) and obj.metadata.name == "x"
        with open(os.path.join(str(tmp_path), "wal.log"), "rb") as fh:
            assert fh.read(len(MAGIC)) == MAGIC
            n, crc = struct.unpack("<II", fh.read(8))
            assert n > 0 and crc != 0


# ---------------------------------------------------------------------------
# Fencing: split-brain rejection
# ---------------------------------------------------------------------------

class TestFencing:
    def test_stale_fence_rejected_fresh_accepted(self):
        s = ObjectStore()
        from kubeflow_controller_tpu.api.core import Lease, LeaseSpec

        s.create("leases", Lease(metadata=ObjectMeta(name="l", namespace="default"),
                                 spec=LeaseSpec(generation=3)))
        assert s.fence_floor == 3
        s.create("pods", mk_pod("ok"), fence=3)       # current leader
        s.create("pods", mk_pod("unfenced"))          # node agents etc.
        with pytest.raises(FencingError):
            s.create("pods", mk_pod("stale"), fence=2)
        with pytest.raises(FencingError):
            s.delete("pods", "default", "ok", fence=1)
        assert s.get("pods", "default", "ok")  # nothing was deleted

    def test_floor_monotonic(self):
        from kubeflow_controller_tpu.api.core import Lease, LeaseSpec

        s = ObjectStore()
        s.create("leases", Lease(metadata=ObjectMeta(name="l", namespace="d"),
                                 spec=LeaseSpec(generation=5)))
        lease = s.get("leases", "d", "l")
        lease.spec.generation = 2  # a replayed old lease write
        s.update("leases", lease)
        assert s.fence_floor == 5  # floor never regresses

    def test_split_brain_two_managers(self):
        shared = Cluster()
        a, b = Cluster(store=shared.store), Cluster(store=shared.store)
        ma = LeaseManager(a.leases, "a", duration_s=0.3)
        mb = LeaseManager(b.leases, "b", duration_s=0.3)
        a.set_fence_provider(ma.token)
        b.set_fence_provider(mb.token)
        ma.start()
        assert wait_until(lambda: ma.is_leader, 5)
        a.pods.create(mk_pod("from-a"))
        mb.start()
        ma.kill()  # SIGKILL: no release, zombie keeps its token
        assert wait_until(lambda: mb.is_leader, 5)
        with pytest.raises(FencingError):
            a.pods.create(mk_pod("zombie"))
        b.pods.create(mk_pod("from-b"))
        mb.stop()

    @pytest.mark.slow
    def test_fencing_over_rest(self):
        from kubeflow_controller_tpu.cluster.apiserver import FakeAPIServer
        from kubeflow_controller_tpu.cluster.rest import Kubeconfig, RestCluster
        from kubeflow_controller_tpu.cluster.store import Conflict

        store = ObjectStore()
        from kubeflow_controller_tpu.api.core import Lease, LeaseSpec

        store.create("leases", Lease(
            metadata=ObjectMeta(name="l", namespace="default"),
            spec=LeaseSpec(generation=4)))
        server = FakeAPIServer(store)
        url = server.start()
        rest = RestCluster(Kubeconfig(server=url))
        try:
            rest.set_fence_provider(lambda: 3)  # deposed generation
            with pytest.raises(Conflict):
                rest.pods.create(mk_pod("stale-over-rest"))
            rest.set_fence_provider(lambda: 4)
            assert rest.pods.create(mk_pod("fresh-over-rest"))
            # The lease itself is never fence-gated (it IS the authority).
            lease = rest.leases.get("default", "l")
            assert lease.spec.generation == 4
        finally:
            rest.close()
            server.stop()


# ---------------------------------------------------------------------------
# Lease protocol
# ---------------------------------------------------------------------------

class TestLease:
    def test_elect_renew_edges_fire_once(self):
        c = Cluster()
        edges = []
        m = LeaseManager(c.leases, "solo", duration_s=0.3,
                         on_elected=lambda g: edges.append(("up", g)),
                         on_lost=lambda: edges.append(("down",)))
        m.start()
        assert wait_until(lambda: m.is_leader, 5)
        time.sleep(0.5)  # several renew cycles: no spurious edges
        assert edges == [("up", 1)]
        lease = c.leases.get("default", "tfjob-controller")
        assert lease.spec.holder_identity == "solo"
        assert lease.spec.renew_time >= lease.spec.acquire_time
        m.stop()
        assert edges == [("up", 1), ("down",)]

    def test_failover_within_two_lease_intervals(self):
        c = Cluster()
        m1 = LeaseManager(c.leases, "one", duration_s=0.4)
        m2 = LeaseManager(c.leases, "two", duration_s=0.4)
        m1.start()
        assert wait_until(lambda: m1.is_leader, 5)
        m2.start()
        time.sleep(0.3)
        assert not m2.is_leader  # live leader is respected
        t0 = time.time()
        m1.kill()
        assert wait_until(lambda: m2.is_leader, 5)
        assert time.time() - t0 < 2 * 0.4 + 0.2
        assert m2.generation == m1.generation + 1
        m2.stop()

    def test_graceful_release_is_fast(self):
        c = Cluster()
        m1 = LeaseManager(c.leases, "one", duration_s=5.0)  # long lease
        m2 = LeaseManager(c.leases, "two", duration_s=5.0,
                          renew_every_s=0.05)
        m1.start()
        assert wait_until(lambda: m1.is_leader, 5)
        m2.start()
        m1.stop(release=True)  # empties the holder: no expiry wait
        assert wait_until(lambda: m2.is_leader, 2), \
            "release should hand over well before the 5s lease expires"
        m2.stop()


# ---------------------------------------------------------------------------
# Hash ring
# ---------------------------------------------------------------------------

class TestHashRing:
    def test_deterministic_and_covering(self):
        r1 = HashRing(["0", "1", "2"])
        r2 = HashRing(["0", "1", "2"])
        keys = [f"uid-{i}" for i in range(300)]
        assert [r1.owner(k) for k in keys] == [r2.owner(k) for k in keys]
        owners = {r1.owner(k) for k in keys}
        assert owners == {"0", "1", "2"}  # no starved member at 300 keys

    def test_rebalance_moves_only_a_fraction(self):
        r = HashRing(["0", "1", "2", "3"])
        keys = [f"uid-{i}" for i in range(1000)]
        before = {k: r.owner(k) for k in keys}
        r.remove("3")
        after = {k: r.owner(k) for k in keys}
        moved = sum(1 for k in keys if before[k] != after[k])
        # Exactly the removed member's keys move, nothing else shuffles.
        assert moved == sum(1 for k in keys if before[k] == "3")
        assert all(after[k] == before[k] for k in keys if before[k] != "3")
        assert 150 < moved < 400  # ~1/4 of the keyspace

    def test_shard_of_matches_ring_convention(self):
        for uid in ("uid-1", "uid-42", "abcdef"):
            assert shard_of(uid, 4) == int(HashRing(
                [str(i) for i in range(4)]).owner(uid))
        assert shard_of("x", 0) is None

    def test_empty_ring(self):
        assert HashRing().owner("anything") is None


# ---------------------------------------------------------------------------
# Sharded controller: e2e + rebalance loses zero jobs
# ---------------------------------------------------------------------------

class TestShardedController:
    @pytest.mark.slow
    def test_sharded_run_and_rebalance_loses_zero_jobs(self):
        from kubeflow_controller_tpu.controller import Controller

        cluster = Cluster()
        kubelet = FakeKubelet(cluster, policy=PhasePolicy(run_s=0.05))
        ctrl = Controller(cluster, resync_period_s=1.0, controller_shards=3)
        kubelet.start()
        ctrl.run(threadiness=1)
        names = [f"reb-{i:03d}" for i in range(15)]
        try:
            for n in names:
                cluster.tfjobs.create(mk_sim_job(n))
            time.sleep(0.3)
            ctrl.set_controller_shards(2)   # shrink mid-storm (handoff)
            time.sleep(0.2)
            ctrl.set_controller_shards(4)   # grow mid-storm (new workers)

            def all_done():
                return all(
                    j.status.phase == TFJobPhase.SUCCEEDED
                    for j in cluster.tfjobs.list("default"))

            assert wait_until(all_done, 60), [
                (j.metadata.name, j.status.phase)
                for j in cluster.tfjobs.list("default")
                if j.status.phase != TFJobPhase.SUCCEEDED]
            assert ctrl.metrics.snapshot()["sync_errors"] == 0
        finally:
            ctrl.stop()
            kubelet.stop()

    def test_sharded_queue_routes_consistently(self):
        from kubeflow_controller_tpu.controller.workqueue import ShutDown
        from kubeflow_controller_tpu.ha.shards import ShardedWorkQueue

        q = ShardedWorkQueue(3, name="t-route", uid_fn=lambda k: f"uid-{k}")
        keys = [f"default/job-{i}" for i in range(30)]
        for k in keys:
            q.add(k)
        seen = {}
        for s in range(3):
            while True:
                k = q.get_shard(s, timeout=0.05)
                if k is None:
                    break
                seen[k] = s
                q.done(k)
        assert set(seen) == set(keys)
        # Same key re-added lands on the same shard (per-job ordering).
        for k in keys:
            q.add(k)
        for s in range(3):
            while True:
                k = q.get_shard(s, timeout=0.05)
                if k is None:
                    break
                assert seen[k] == s
                q.done(k)
        q.shut_down()
        with pytest.raises(ShutDown):
            q.get_shard(0, timeout=0.05)

    def test_handoff_replays_expectations_and_preserves_delays(self):
        from kubeflow_controller_tpu.ha.shards import ShardedWorkQueue

        handed_off = []
        q = ShardedWorkQueue(4, name="t-handoff",
                             uid_fn=lambda k: f"uid-{k}")
        q._on_handoff = handed_off.append
        keys = [f"default/job-{i}" for i in range(40)]
        for k in keys[:30]:
            q.add(k)
        for k in keys[30:]:
            q.add_after(k, 0.4)  # delayed adds must survive the move
        before = {k: q._route_locked(k) for k in keys}
        q.set_shards(2)
        after = {k: q._route_locked(k) for k in keys}
        moved = {k for k in keys if before[k] != after[k]}
        assert moved, "shrinking 4->2 must move someone"
        assert moved == set(handed_off)
        # Nothing lost: every ready key pops from its NEW shard...
        popped = set()
        for s in range(2):
            while True:
                k = q.get_shard(s, timeout=0.05)
                if k is None:
                    break
                popped.add(k)
                q.done(k)
        assert popped == set(keys[:30])
        # ...and the delayed ones fire later, also on the new shards.
        time.sleep(0.6)
        for s in range(2):
            while True:
                k = q.get_shard(s, timeout=0.05)
                if k is None:
                    break
                popped.add(k)
                q.done(k)
        assert popped == set(keys)
        q.shut_down()

    def test_inflight_sync_drains_before_handoff(self):
        """A key being processed during a rebalance is never handed to the
        new shard's worker until the old sync completes."""
        import threading

        from kubeflow_controller_tpu.ha.shards import ShardedWorkQueue

        q = ShardedWorkQueue(2, name="t-drain", uid_fn=lambda k: f"uid-{k}")
        key = "default/busy"
        q.add(key)
        owner = next(s for s in range(2)
                     if q._route_locked(key) == s)
        got = q.get_shard(owner, timeout=1.0)
        assert got == key  # in flight now
        q.add(key)         # goes dirty behind the in-flight sync

        done_evt = threading.Event()

        def finish_later():
            time.sleep(0.15)
            q.done(key)
            done_evt.set()

        t = threading.Thread(target=finish_later, name="t-drain-finisher",
                             daemon=True)
        t.start()
        t0 = time.time()
        q.set_shards(1)  # must block on the in-flight sync
        assert done_evt.is_set(), \
            "rebalance returned before the in-flight sync drained"
        assert time.time() - t0 >= 0.1
        assert q.get_shard(0, timeout=1.0) == key  # the dirty re-add moved
        q.done(key)
        q.shut_down()


# ---------------------------------------------------------------------------
# FakeAPIServer deterministic shutdown
# ---------------------------------------------------------------------------

class TestServerShutdown:
    @pytest.mark.slow
    def test_stop_closes_streams_and_flushes_wal(self, tmp_path):
        from kubeflow_controller_tpu.cluster.apiserver import FakeAPIServer
        from kubeflow_controller_tpu.cluster.rest import Kubeconfig, RestCluster

        wal = WriteAheadLog(str(tmp_path), fsync=False)
        store = ObjectStore(wal=wal)
        server = FakeAPIServer(store)
        url = server.start()
        rest = RestCluster(Kubeconfig(server=url))
        w = rest.pods.watch()
        rest.pods.create(mk_pod("seen"))
        ev = w.next(timeout=2.0)
        assert ev is not None
        t0 = time.time()
        server.stop()
        stop_s = time.time() - t0
        assert stop_s < 2.0, f"shutdown took {stop_s:.2f}s (stream poll race)"
        w.stop()
        rest.close()
        # The WAL tail was flushed on stop: a recovered store is complete
        # without leaning on the torn-tail truncation path.
        s2 = ObjectStore.recover(WriteAheadLog(str(tmp_path), fsync=False))
        assert s2.get("pods", "default", "seen").metadata.name == "seen"
        assert s2.export_state() == store.export_state()


# ---------------------------------------------------------------------------
# vet: fencing-token rule fixtures
# ---------------------------------------------------------------------------

class TestFencingVetRule:
    FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures", "vet")

    def _run(self, name):
        from kubeflow_controller_tpu.analysis import vet

        return vet.run([os.path.join(self.FIXTURES, name)],
                       skip_catalogue=True)

    def test_bad_fixture_all_writes_flagged(self):
        findings = self._run("bad_fencing.py")
        rules = {f.rule for f in findings}
        assert rules == {"fencing-token"}
        assert len(findings) == 5  # every write in the fixture

    def test_good_fixture_clean(self):
        assert [f for f in self._run("good_fencing.py")
                if f.rule == "fencing-token"] == []

    def test_repo_is_fencing_clean(self):
        from kubeflow_controller_tpu.analysis import vet

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        findings = [
            f for f in vet.run(root=repo, skip_catalogue=True)
            if f.rule == "fencing-token"
        ]
        assert findings == [], [f.render() for f in findings]


# ---------------------------------------------------------------------------
# Crash-restart model check (PR-11 checkers across the recover boundary)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_crash_restart_simulation_seed_clean():
    from kubeflow_controller_tpu.analysis import simcheck

    out = simcheck.run_crash_restart_seed(11, duration_s=0.3)
    assert out["rv_identical"]
    assert out["resumed_consumers"] >= len(simcheck.KINDS) * 3  # all resumed
    assert out["violations"] == [], [v.render() for v in out["violations"]]
    assert out["wal_records"] > 0 and out["ops"] > 0
