"""Write-path fan-out tests: slow-start batched plan execution
(controller/slowstart.py wired through Controller._manage_inner), the
expectation accounting that keeps a mid-batch failure consistent, and the
pooled keep-alive REST transport underneath it (cluster/rest.py).

The load-bearing contract (ISSUE 4 acceptance): a create that fails mid-
batch must leave ``ControllerExpectations`` exact — failed and skipped
events lower their own expectations, so the NEXT sync re-plans exactly the
missing children instead of waiting out the 5-minute TTL or double-creating
the survivors."""

import threading
import time
from concurrent.futures import ThreadPoolExecutor

import pytest

from kubeflow_controller_tpu.api.core import Container, Pod, PodTemplateSpec
from kubeflow_controller_tpu.api.meta import ObjectMeta
from kubeflow_controller_tpu.api.tfjob import (
    ReplicaType,
    TFJob,
    TFJobPhase,
    TFReplicaSpec,
)
from kubeflow_controller_tpu.cluster import Cluster, FakeKubelet, PhasePolicy
from kubeflow_controller_tpu.cluster.apiserver import FakeAPIServer
from kubeflow_controller_tpu.cluster.rest import (
    ConnectionPool,
    Kubeconfig,
    RestCluster,
)
from kubeflow_controller_tpu.cluster.store import APIError
from kubeflow_controller_tpu.controller import Controller
from kubeflow_controller_tpu.controller.expectations import ControllerExpectations
from kubeflow_controller_tpu.controller.slowstart import (
    ManageError,
    slow_start_batch,
)


def mk_job(name, *types_and_replicas):
    job = TFJob(metadata=ObjectMeta(name=name, namespace="default"))
    for typ, n in types_and_replicas:
        t = PodTemplateSpec()
        t.spec.containers.append(Container(name="tensorflow", image="img"))
        t.spec.restart_policy = "OnFailure"
        job.spec.tf_replica_specs.append(
            TFReplicaSpec(replicas=n, tf_replica_type=typ, template=t))
    return job


def wait_for(fn, timeout=15.0, interval=0.02):
    deadline = time.time() + timeout
    while time.time() < deadline:
        v = fn()
        if v:
            return v
        time.sleep(interval)
    raise AssertionError("condition not met within timeout")


# ---------------------------------------------------------------------------
# slow_start_batch: the unit


class TestSlowStartBatch:
    def test_batches_grow_exponentially(self):
        sizes = []
        done, errors, skipped = slow_start_batch(
            list(range(13)), lambda i: None,
            batch_cm=lambda n: sizes.append(n) or _null())
        assert (done, errors, skipped) == (13, [], [])
        # 1, 2, 4, 8 — the last batch clamps to what remains.
        assert sizes == [1, 2, 4, 6]

    def test_serial_inline_preserves_order(self):
        calls = []
        done, errors, skipped = slow_start_batch(
            list(range(9)), calls.append, executor=None)
        assert (done, errors, skipped) == (9, [], [])
        assert calls == list(range(9))

    def test_first_failure_skips_the_tail(self):
        """A persistently failing call costs O(log n) attempts, not n: the
        1-item probe batch fails and nothing else launches."""
        attempts = []

        def fail(i):
            attempts.append(i)
            raise RuntimeError(f"boom {i}")

        done, errors, skipped = slow_start_batch(list(range(16)), fail)
        assert done == 0
        assert len(errors) == 1
        assert attempts == [0]
        assert skipped == list(range(1, 16))

    def test_failing_batch_drains_in_flight(self):
        """Items already dispatched in the failing batch complete (their
        side effects are real); only NEW batches stop."""
        attempted = []
        lock = threading.Lock()

        def fn(i):
            with lock:
                attempted.append(i)
            if i == 4:
                raise RuntimeError("boom")

        with ThreadPoolExecutor(max_workers=4) as pool:
            done, errors, skipped = slow_start_batch(
                list(range(15)), fn, executor=pool)
        # Batches 1, 2, 4 launched; item 4 (in the 4-wide batch) failed but
        # items 3, 5, 6 of that batch still ran; the 8-wide tail never did.
        assert sorted(attempted) == list(range(7))
        assert done == 6
        assert len(errors) == 1
        assert skipped == list(range(7, 15))

    def test_every_error_in_the_batch_is_kept(self):
        def fn(i):
            if i in (3, 5):
                raise RuntimeError(f"boom {i}")

        with ThreadPoolExecutor(max_workers=4) as pool:
            done, errors, skipped = slow_start_batch(
                list(range(7)), fn, executor=pool)
        assert done == 5  # 0; 1,2; 4,6 succeed, 3,5 fail
        assert sorted(str(e) for e in errors) == ["boom 3", "boom 5"]
        assert skipped == []

    def test_wide_batch_actually_runs_concurrently(self):
        """The 4-wide batch must overlap on the pool — a gate that only
        opens when all 4 calls are inside fn proves it (a serialized
        executor would deadlock and trip the barrier timeout)."""
        barrier = threading.Barrier(4, timeout=5.0)

        def fn(i):
            if i >= 3:  # the four members of the third batch
                barrier.wait()

        with ThreadPoolExecutor(max_workers=4) as pool:
            done, errors, skipped = slow_start_batch(
                list(range(7)), fn, executor=pool)
        assert (done, errors, skipped) == (7, [], [])

    def test_manage_error_message_counts(self):
        err = ManageError([RuntimeError("a"), RuntimeError("b")],
                          attempted=5, skipped=3)
        assert "2/5 plan events failed" in str(err)
        assert "(3 skipped)" in str(err)
        assert len(err.errors) == 2


class _null:
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


# ---------------------------------------------------------------------------
# ControllerExpectations under concurrent raise/lower (manage workers +
# watch handlers hit it from many threads at once)


class TestExpectationsConcurrency:
    def _hammer(self, fn_a, fn_b, rounds=200, threads=4):
        workers = []
        for fn in (fn_a, fn_b):
            for _ in range(threads):
                workers.append(threading.Thread(
                    target=lambda f=fn: [f() for _ in range(rounds)]))
        for w in workers:
            w.start()
        for w in workers:
            w.join()

    def test_no_lost_or_double_counted_adds(self):
        exp = ControllerExpectations()
        exp.expect("default/j", adds=8 * 200, dels=0)
        # Half the decrements arrive as watch observations, half as failed-
        # create lowers — exactly the parallel manage path's mix.
        self._hammer(lambda: exp.creation_observed("default/j"),
                     lambda: exp.lower_expectations("default/j", add_delta=1))
        e = exp._store["default/j"]
        assert e.adds == 0  # exact: not negative, not positive
        assert exp.satisfied_expectations("default/j")

    def test_no_lost_or_double_counted_dels(self):
        exp = ControllerExpectations()
        exp.expect("default/j", adds=0, dels=8 * 200)
        self._hammer(lambda: exp.deletion_observed("default/j"),
                     lambda: exp.lower_expectations("default/j", del_delta=1))
        assert exp._store["default/j"].dels == 0
        assert exp.satisfied_expectations("default/j")

    def test_unsatisfied_until_every_delta_lands(self):
        exp = ControllerExpectations()
        exp.expect("default/j", adds=3, dels=0)
        exp.creation_observed("default/j")
        exp.creation_observed("default/j")
        assert not exp.satisfied_expectations("default/j")
        exp.lower_expectations("default/j", add_delta=1)
        assert exp.satisfied_expectations("default/j")


# ---------------------------------------------------------------------------
# Mid-batch create failure: expectations stay consistent, the next sync
# re-plans exactly the missing children (ISSUE 4 acceptance criterion),
# and surviving events for other replicas are still attempted (satellite:
# the old _manage_inner raised on the first failure and dropped the rest).


class FlakyPods:
    """Wraps the pod client: create fails ``fail_times`` times for the pod
    whose generateName starts with ``prefix``; every attempt is logged by
    its replica identity (generateName, stable across retries — the final
    object name gets a random suffix per attempt)."""

    def __init__(self, pods, prefix, fail_times):
        self._pods = pods
        self._prefix = prefix
        self._left = fail_times
        self.lock = threading.Lock()
        self.attempts = []

    def create(self, pod):
        ident = pod.metadata.generate_name or pod.metadata.name
        with self.lock:
            self.attempts.append(ident)
            if ident.startswith(self._prefix) and self._left > 0:
                self._left -= 1
                raise APIError("injected create failure")
        return self._pods.create(pod)

    def __getattr__(self, attr):  # delegate list/get/delete/watch/...
        return getattr(self._pods, attr)


@pytest.mark.parametrize("manage_workers", [1, 4])
def test_mid_batch_create_failure_replans_exactly_missing(manage_workers):
    cluster = Cluster()
    flaky = FlakyPods(cluster.pods, prefix="wide-worker-1-", fail_times=1)
    cluster.pods = flaky
    kubelet = FakeKubelet(cluster, policy=PhasePolicy(run_s=0.2))
    ctrl = Controller(cluster, resync_period_s=0.5,
                      manage_workers=manage_workers)
    kubelet.start()
    ctrl.run(threadiness=2)
    try:
        cluster.tfjobs.create(mk_job("wide", (ReplicaType.WORKER, 4)))
        wait_for(lambda: len(cluster.pods.list("default")) == 4)
        wait_for(lambda: phase(cluster, "wide") in
                 (TFJobPhase.RUNNING, TFJobPhase.SUCCEEDED))
        with flaky.lock:
            attempts = list(flaky.attempts)
    finally:
        ctrl.stop()
        kubelet.stop()

    by_name = {n: attempts.count(n) for n in set(attempts)}
    # The failed child was re-planned (original + exactly one retry)...
    assert by_name.pop("wide-worker-1-") == 2
    # ...and ONLY it: every other child was created exactly once — the
    # failing sync still attempted its batch siblings (no abort-on-first),
    # and the re-plan did not double-create survivors (expectations were
    # lowered for the failed event, so the next sync saw exact state).
    assert by_name == {f"wide-worker-{i}-": 1 for i in (0, 2, 3)}
    # The retry happened in well under the 5-minute expectations TTL —
    # i.e. the failed event's expectation was lowered, not leaked.
    assert ctrl.metrics.snapshot()["sync_errors"] >= 1


def phase(cluster, name):
    return cluster.tfjobs.get("default", name).status.phase


def test_persistent_failure_skips_tail_then_converges():
    """A wide plan whose probe batch keeps failing wastes O(log n) calls
    per sync (not n), and still converges once the fault clears."""
    cluster = Cluster()
    flaky = FlakyPods(cluster.pods, prefix="wide-worker-0-", fail_times=2)
    cluster.pods = flaky
    kubelet = FakeKubelet(cluster, policy=PhasePolicy(run_s=0.2))
    ctrl = Controller(cluster, resync_period_s=0.3, manage_workers=4)
    kubelet.start()
    ctrl.run(threadiness=2)
    try:
        cluster.tfjobs.create(mk_job("wide", (ReplicaType.WORKER, 8)))
        wait_for(lambda: len(cluster.pods.list("default")) == 8)
        with flaky.lock:
            attempts = list(flaky.attempts)
    finally:
        ctrl.stop()
        kubelet.stop()
    # Every child created exactly once, except the faulty one: 2 failures
    # + the success.  No child was created twice.
    by_name = {n: attempts.count(n) for n in set(attempts)}
    assert by_name.pop("wide-worker-0-") == 3
    assert set(by_name.values()) == {1}


# ---------------------------------------------------------------------------
# Pooled keep-alive REST transport


@pytest.fixture
def server():
    srv = FakeAPIServer()
    url = srv.start()
    yield srv, url
    srv.stop()


@pytest.fixture
def rest(server):
    _, url = server
    c = RestCluster(Kubeconfig(server=url))
    yield c
    c.close()


class TestConnectionPool:
    def test_sequential_requests_reuse_one_connection(self, rest):
        pool = rest.transport.pool
        d0, r0 = pool._c_dials.value, pool._c_reuses.value
        for _ in range(5):
            rest.pods.list("default")
        assert pool._c_dials.value - d0 == 1
        assert pool._c_reuses.value - r0 == 4
        assert pool.idle_count == 1

    def test_stale_pooled_socket_reconnects_transparently(self, rest):
        rest.pods.list("default")  # park one keep-alive connection
        pool = rest.transport.pool
        assert pool.idle_count == 1
        # Kill the idle socket under the pool (a server idle-timeout does
        # exactly this); the next request must notice and redial, not fail.
        pool._idle[0].sock.close()
        assert rest.pods.list("default") == []

    def test_pool_bounds_idle_connections(self, server):
        _, url = server
        c = RestCluster(Kubeconfig(server=url), pool_size=2)
        try:
            results = []

            def hit():
                results.append(c.pods.list("default"))

            threads = [threading.Thread(target=hit) for _ in range(6)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert len(results) == 6
            assert c.transport.pool.idle_count <= 2
        finally:
            c.close()

    def test_concurrent_creates_all_land(self, server):
        """The write path the slow-start batches drive: parallel creates
        through one pooled transport, server must tolerate them all."""
        srv, url = server
        c = RestCluster(Kubeconfig(server=url), pool_size=8)
        try:
            errs = []

            def create(i):
                p = Pod()
                p.metadata.namespace = "default"
                p.metadata.name = f"p{i}"
                try:
                    c.pods.create(p)
                except Exception as e:  # noqa: BLE001 - recorded for assert
                    errs.append(e)

            threads = [threading.Thread(target=create, args=(i,))
                       for i in range(16)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert errs == []
            assert len(srv.store.list("pods", "default")) == 16
            # A cold burst may dial per-thread (maxsize bounds idle
            # retention, not burst width) — but the NEXT round must ride
            # the retained keep-alive connections, not dial again.
            dials_after_burst = c.transport.pool._c_dials.value
            for i in range(16, 24):
                p = Pod()
                p.metadata.namespace = "default"
                p.metadata.name = f"p{i}"
                c.pods.create(p)
            assert c.transport.pool._c_dials.value == dials_after_burst
        finally:
            c.close()


class _BrokenOnce:
    """Stands in for a fresh connection whose request dies transiently."""

    sock = object()

    def request(self, *a, **k):
        raise ConnectionResetError("transient")

    def close(self):
        pass


class _FlakyCheckoutPool:
    """First checkout hands back a connection that fails its request (as a
    FRESH dial, reused=False — the case the safe-verb retry exists for);
    later checkouts delegate to the real pool."""

    def __init__(self, real):
        self._real = real
        self._tripped = False

    def checkout(self, timeout=None):
        if not self._tripped:
            self._tripped = True
            return _BrokenOnce(), False
        return self._real.checkout(timeout)

    def __getattr__(self, attr):  # dial/checkin/discard/close/...
        return getattr(self._real, attr)


class TestSafeVerbRetry:
    def test_get_retries_once_on_transient_error(self, rest):
        rest.transport.pool = _FlakyCheckoutPool(rest.transport.pool)
        assert rest.pods.list("default") == []  # retried, not raised

    def test_post_does_not_retry_on_fresh_socket(self, server):
        srv, url = server
        c = RestCluster(Kubeconfig(server=url))
        try:
            c.transport.pool = _FlakyCheckoutPool(c.transport.pool)
            p = Pod()
            p.metadata.namespace = "default"
            p.metadata.name = "once"
            with pytest.raises(APIError):
                c.pods.create(p)
            # The request was NOT replayed: nothing reached the store.
            assert srv.store.list("pods", "default") == []
        finally:
            c.close()


# ---------------------------------------------------------------------------
# End-to-end over HTTP: controller with parallel manage on the pooled
# transport (the exact stack `bench.py --replicas` measures)


def test_wide_job_over_rest_with_parallel_manage():
    cluster = Cluster()
    srv = FakeAPIServer(cluster.store)
    url = srv.start()
    rest = RestCluster(Kubeconfig(server=url), pool_size=4)
    kubelet = FakeKubelet(cluster, policy=PhasePolicy(run_s=0.1))
    ctrl = Controller(rest, resync_period_s=1.0, manage_workers=4)
    kubelet.start()
    ctrl.run(threadiness=2)
    try:
        rest.tfjobs.create(mk_job("wide", (ReplicaType.WORKER, 8)))
        wait_for(lambda: len(cluster.pods.list("default")) == 8, timeout=30.0)
        wait_for(lambda: rest.tfjobs.get("default", "wide").status.phase
                 == TFJobPhase.SUCCEEDED, timeout=30.0)
        snap = ctrl.metrics.snapshot()
        assert snap["creates"] >= 16  # 8 pods + 8 services
        assert snap["sync_errors"] == 0
        assert snap["create_latency_p99_s"] > 0.0
    finally:
        ctrl.stop()
        kubelet.stop()
        rest.close()
        srv.stop()


def test_batch_size_histogram_observed():
    """kctpu_manage_batch_size records the slow-start ramp."""
    from kubeflow_controller_tpu.obs.metrics import REGISTRY

    cluster = Cluster()
    kubelet = FakeKubelet(cluster, policy=PhasePolicy(run_s=0.1))
    ctrl = Controller(cluster, resync_period_s=1.0, manage_workers=4)
    h = REGISTRY.histogram(
        "kctpu_manage_batch_size",
        "Plan events dispatched per slow-start batch",
        buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512))
    before = h.count
    kubelet.start()
    ctrl.run(threadiness=2)
    try:
        cluster.tfjobs.create(mk_job("wide", (ReplicaType.WORKER, 4)))
        wait_for(lambda: len(cluster.pods.list("default")) == 4)
    finally:
        ctrl.stop()
        kubelet.stop()
    # 4 services + 4 pods in slow-start batches (1,2,1 / 1,2,1 at minimum).
    assert h.count > before


def test_pool_close_idempotent_and_checkout_after_close_dials():
    pool = ConnectionPool("http://127.0.0.1:1")  # never actually connected
    pool.close()
    pool.close()
    assert pool.idle_count == 0
