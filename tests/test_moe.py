"""MoE: capacity dispatch vs dense oracle, llama integration, ep sharding."""

import jax
import jax.numpy as jnp
import numpy as np

from kubeflow_controller_tpu.models import LlamaConfig, llama_init, llama_loss, llama_forward
from kubeflow_controller_tpu.models.generate import forward_with_cache, init_cache
from kubeflow_controller_tpu.models.llama import llama_param_pspecs
from kubeflow_controller_tpu.models.moe import moe_ffn, moe_ffn_reference
from kubeflow_controller_tpu.parallel import MeshSpec, build_mesh


def _weights(key, D=16, E=4, F=32):
    ks = jax.random.split(key, 4)
    return (
        jax.random.normal(ks[0], (D, E)) * 0.3,
        jax.random.normal(ks[1], (E, D, F)) * 0.1,
        jax.random.normal(ks[2], (E, D, F)) * 0.1,
        jax.random.normal(ks[3], (E, F, D)) * 0.1,
    )


class TestMoEFFN:
    def test_matches_dense_oracle_with_ample_capacity(self):
        """With capacity >= T*k no token drops, so the einsum dispatch must
        reproduce the dense computation exactly."""
        router, wg, wu, wd = _weights(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 8, 16))
        out = moe_ffn(x, router, wg, wu, wd, top_k=2, capacity_factor=100.0)
        ref = moe_ffn_reference(x, router, wg, wu, wd, top_k=2)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)

    def test_capacity_drops_are_bounded(self):
        """Tight capacity zeroes some tokens' outputs but never corrupts the
        kept ones (each kept slot still matches the oracle's per-slot term)."""
        router, wg, wu, wd = _weights(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (1, 16, 16))
        out_tight = moe_ffn(x, router, wg, wu, wd, top_k=1, capacity_factor=0.5)
        out_full = moe_ffn(x, router, wg, wu, wd, top_k=1, capacity_factor=100.0)
        # Tight output is a per-token subset: each token either matches the
        # full result or is exactly zero (dropped).
        o_t, o_f = np.asarray(out_tight[0]), np.asarray(out_full[0])
        for t in range(16):
            assert (
                np.allclose(o_t[t], o_f[t], atol=1e-5)
                or np.allclose(o_t[t], 0.0, atol=1e-6)
            ), t

    def test_grads_flow(self):
        router, wg, wu, wd = _weights(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 8, 16))

        def loss(w):
            return jnp.sum(moe_ffn(x, w[0], w[1], w[2], w[3]) ** 2)

        g = jax.grad(loss)((router, wg, wu, wd))
        assert all(float(jnp.linalg.norm(gi)) > 0 for gi in g)


class TestMoELlama:
    def cfg(self):
        return LlamaConfig.tiny(n_experts=4, moe_top_k=2)

    def test_forward_and_loss(self):
        cfg = self.cfg()
        params = llama_init(jax.random.PRNGKey(0), cfg)
        assert params["layers"]["w_gate"].shape == (2, 4, 64, 128)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab_size)
        logits = llama_forward(params, tokens, cfg)
        assert logits.shape == (2, 16, cfg.vocab_size)
        loss = llama_loss(params, tokens, cfg)
        assert float(loss) > 0

    def test_decode_matches_dense(self):
        cfg = self.cfg()
        params = llama_init(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(2), (1, 8), 0, cfg.vocab_size)
        dense = llama_forward(params, tokens, cfg)
        cache = init_cache(cfg, 1, 8)
        cached, _ = forward_with_cache(params, tokens, cache, 0, cfg)
        # MoE routing depends on position within the forward batch; prefill
        # processes the same 8 tokens in one block, so results must agree.
        np.testing.assert_allclose(np.asarray(cached), np.asarray(dense),
                                   atol=2e-4, rtol=2e-4)

    def test_ep_sharded_matches_unsharded(self):
        cfg = LlamaConfig.tiny(n_experts=4, moe_top_k=2, remat=False)
        params = llama_init(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(3), (2, 16), 0, cfg.vocab_size)
        ref = llama_forward(params, tokens, cfg)
        mesh = build_mesh(MeshSpec(dp=1, fsdp=2, ep=2, tp=2, sp=1))
        pspecs = llama_param_pspecs(cfg)
        sharded = jax.tree.map(
            lambda a, s: jax.device_put(a, jax.sharding.NamedSharding(mesh, s)),
            params, pspecs,
        )
        with jax.set_mesh(mesh):
            out = jax.jit(lambda p, t: llama_forward(p, t, cfg, mesh=mesh))(
                sharded, tokens)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-4, rtol=2e-4)
