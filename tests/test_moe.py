"""MoE: capacity dispatch vs dense oracle, llama integration, ep sharding."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_controller_tpu.models import LlamaConfig, llama_init, llama_loss, llama_forward
from kubeflow_controller_tpu.models.generate import forward_with_cache, init_cache
from kubeflow_controller_tpu.models.llama import llama_param_pspecs
from kubeflow_controller_tpu.models.moe import moe_ffn, moe_ffn_reference
from kubeflow_controller_tpu.parallel import MeshSpec, build_mesh
from kubeflow_controller_tpu.parallel.compat import set_mesh as compat_set_mesh


def _weights(key, D=16, E=4, F=32):
    ks = jax.random.split(key, 4)
    return (
        jax.random.normal(ks[0], (D, E)) * 0.3,
        jax.random.normal(ks[1], (E, D, F)) * 0.1,
        jax.random.normal(ks[2], (E, D, F)) * 0.1,
        jax.random.normal(ks[3], (E, F, D)) * 0.1,
    )


class TestMoEFFN:
    @pytest.mark.slow
    def test_matches_dense_oracle_with_ample_capacity(self):
        """With capacity >= T*k no token drops, so the einsum dispatch must
        reproduce the dense computation exactly."""
        router, wg, wu, wd = _weights(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 8, 16))
        out = moe_ffn(x, router, wg, wu, wd, top_k=2, capacity_factor=100.0)
        ref = moe_ffn_reference(x, router, wg, wu, wd, top_k=2)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)

    def test_capacity_drops_are_bounded(self):
        """Tight capacity zeroes some tokens' outputs but never corrupts the
        kept ones (each kept slot still matches the oracle's per-slot term)."""
        router, wg, wu, wd = _weights(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (1, 16, 16))
        out_tight = moe_ffn(x, router, wg, wu, wd, top_k=1, capacity_factor=0.5)
        out_full = moe_ffn(x, router, wg, wu, wd, top_k=1, capacity_factor=100.0)
        # Tight output is a per-token subset: each token either matches the
        # full result or is exactly zero (dropped).
        o_t, o_f = np.asarray(out_tight[0]), np.asarray(out_full[0])
        for t in range(16):
            assert (
                np.allclose(o_t[t], o_f[t], atol=1e-5)
                or np.allclose(o_t[t], 0.0, atol=1e-6)
            ), t

    def test_grads_flow(self):
        router, wg, wu, wd = _weights(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 8, 16))

        def loss(w):
            return jnp.sum(moe_ffn(x, w[0], w[1], w[2], w[3]) ** 2)

        g = jax.grad(loss)((router, wg, wu, wd))
        assert all(float(jnp.linalg.norm(gi)) > 0 for gi in g)


class TestRouterAuxLosses:
    def test_balanced_vs_collapsed_aux(self):
        """aux_loss is ~1 for a balanced router and approaches E when the
        router collapses onto one expert."""
        from kubeflow_controller_tpu.models.moe import moe_ffn_stats

        _, wg, wu, wd = _weights(jax.random.PRNGKey(0))
        # Positive activations so a router column with large positive weights
        # wins for EVERY token (logits = x @ W would flip sign with zero-mean x).
        x = jax.random.uniform(jax.random.PRNGKey(1), (4, 32, 16),
                               minval=0.5, maxval=1.5)
        # Near-zero router weights -> near-uniform softmax, balanced top-k.
        balanced_router = jax.random.normal(jax.random.PRNGKey(2), (16, 4)) * 1e-3
        _, s_bal = moe_ffn_stats(x, balanced_router, wg, wu, wd, top_k=2,
                                 capacity_factor=100.0)
        # A router biased hard toward expert 0 for every token.
        collapsed_router = jnp.zeros((16, 4)).at[:, 0].set(10.0)
        _, s_col = moe_ffn_stats(x, collapsed_router, wg, wu, wd, top_k=2,
                                 capacity_factor=100.0)
        assert 0.9 < float(s_bal["aux_loss"]) < 1.3
        assert float(s_col["aux_loss"]) > 1.8  # E=4, top-2 collapse -> ~2
        assert float(s_col["aux_loss"]) > float(s_bal["aux_loss"])

    def test_overflow_fraction(self):
        from kubeflow_controller_tpu.models.moe import moe_ffn_stats

        router, wg, wu, wd = _weights(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (1, 16, 16))
        _, ample = moe_ffn_stats(x, router, wg, wu, wd, top_k=1,
                                 capacity_factor=100.0)
        _, tight = moe_ffn_stats(x, router, wg, wu, wd, top_k=1,
                                 capacity_factor=0.5)
        assert float(ample["overflow_frac"]) == 0.0
        assert 0.0 < float(tight["overflow_frac"]) < 1.0
        assert float(ample["z_loss"]) >= 0.0

    def test_aux_loss_balances_training(self):
        """Descending the aux loss from a collapsed router spreads hard
        assignments back across experts — the property that prevents expert
        collapse in real MoE training."""
        import optax

        from kubeflow_controller_tpu.models.moe import moe_ffn_stats

        _, wg, wu, wd = _weights(jax.random.PRNGKey(0))
        x = jax.random.uniform(jax.random.PRNGKey(1), (4, 32, 16),
                               minval=0.5, maxval=1.5)  # see balanced test
        router = jax.random.normal(jax.random.PRNGKey(2), (16, 4)) * 0.01
        # Mild collapse onto expert 0: every token still picks it first, but
        # the softmax is not saturated (a +2.0 bias puts router gradients at
        # ~1e-14 where adam's epsilon nulls the update).
        router = router.at[:, 0].add(0.3)

        def aux(r):
            _, s = moe_ffn_stats(x, r, wg, wu, wd, top_k=2,
                                 capacity_factor=100.0)
            return s["aux_loss"]

        opt = optax.adam(5e-2)
        state = opt.init(router)
        first = float(aux(router))

        @jax.jit
        def step(r, s):
            g = jax.grad(aux)(r)
            u, s = opt.update(g, s, r)
            return optax.apply_updates(r, u), s

        for _ in range(40):
            router, state = step(router, state)
        last = float(aux(router))
        assert first > 1.8  # started collapsed
        assert last < 1.3, f"aux did not rebalance: {first} -> {last}"

    @pytest.mark.slow
    def test_llama_loss_includes_aux_terms(self):
        cfg = LlamaConfig.tiny(n_experts=4, moe_top_k=2)
        params = llama_init(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                                    cfg.vocab_size)
        loss_with = llama_loss(params, tokens, cfg)
        import dataclasses

        cfg_no_aux = dataclasses.replace(cfg, moe_aux_coef=0.0, moe_z_coef=0.0)
        loss_without = llama_loss(params, tokens, cfg_no_aux)
        # Aux terms are positive, so the full loss must be strictly larger.
        assert float(loss_with) > float(loss_without)
        # And forward exposes the averaged stats.
        _, aux = llama_forward(params, tokens, cfg, return_aux=True)
        assert set(aux) == {"aux_loss", "z_loss", "overflow_frac"}
        assert float(aux["aux_loss"]) > 0


class TestMoELlama:
    def cfg(self):
        return LlamaConfig.tiny(n_experts=4, moe_top_k=2)

    def test_forward_and_loss(self):
        cfg = self.cfg()
        params = llama_init(jax.random.PRNGKey(0), cfg)
        assert params["layers"]["w_gate"].shape == (2, 4, 64, 128)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab_size)
        logits = llama_forward(params, tokens, cfg)
        assert logits.shape == (2, 16, cfg.vocab_size)
        loss = llama_loss(params, tokens, cfg)
        assert float(loss) > 0

    def test_decode_matches_dense(self):
        cfg = self.cfg()
        params = llama_init(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(2), (1, 8), 0, cfg.vocab_size)
        dense = llama_forward(params, tokens, cfg)
        cache = init_cache(cfg, 1, 8)
        cached, _ = forward_with_cache(params, tokens, cache, 0, cfg)
        # MoE routing depends on position within the forward batch; prefill
        # processes the same 8 tokens in one block, so results must agree.
        np.testing.assert_allclose(np.asarray(cached), np.asarray(dense),
                                   atol=2e-4, rtol=2e-4)

    def test_ep_sharded_matches_unsharded(self):
        cfg = LlamaConfig.tiny(n_experts=4, moe_top_k=2, remat=False)
        params = llama_init(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(3), (2, 16), 0, cfg.vocab_size)
        ref = llama_forward(params, tokens, cfg)
        mesh = build_mesh(MeshSpec(dp=1, fsdp=2, ep=2, tp=2, sp=1))
        pspecs = llama_param_pspecs(cfg)
        sharded = jax.tree.map(
            lambda a, s: jax.device_put(a, jax.sharding.NamedSharding(mesh, s)),
            params, pspecs,
        )
        with compat_set_mesh(mesh):
            out = jax.jit(lambda p, t: llama_forward(p, t, cfg, mesh=mesh))(
                sharded, tokens)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-4, rtol=2e-4)


class TestDispatchModes:
    """scatter and (k-folded) einsum dispatch compute the same function —
    including under capacity overflow — so the TPU-measured default can
    change per backend without touching semantics."""

    @pytest.mark.parametrize("cap", [100.0, 0.5])
    def test_scatter_matches_einsum(self, cap):
        from kubeflow_controller_tpu.models.moe import moe_ffn_stats

        router, wg, wu, wd = _weights(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 16, 16))
        ye, se = moe_ffn_stats(x, router, wg, wu, wd, top_k=2,
                               capacity_factor=cap, dispatch="einsum")
        ys, ss = moe_ffn_stats(x, router, wg, wu, wd, top_k=2,
                               capacity_factor=cap, dispatch="scatter")
        np.testing.assert_allclose(np.asarray(ye), np.asarray(ys),
                                   atol=1e-5, rtol=1e-5)
        for k in se:
            np.testing.assert_allclose(float(se[k]), float(ss[k]), rtol=1e-6)

    def test_scatter_grads_match(self):
        from kubeflow_controller_tpu.models.moe import moe_ffn_stats

        router, wg, wu, wd = _weights(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 16, 16))

        def loss(r, mode):
            return jnp.sum(moe_ffn_stats(x, r, wg, wu, wd,
                                         dispatch=mode)[0] ** 2)

        ge = jax.grad(lambda r: loss(r, "einsum"))(router)
        gs = jax.grad(lambda r: loss(r, "scatter"))(router)
        np.testing.assert_allclose(np.asarray(ge), np.asarray(gs),
                                   atol=1e-5, rtol=1e-4)

    def test_unknown_dispatch_raises(self):
        from kubeflow_controller_tpu.models.moe import moe_ffn_stats

        router, wg, wu, wd = _weights(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (1, 8, 16))
        with pytest.raises(ValueError):
            moe_ffn_stats(x, router, wg, wu, wd, dispatch="sort")


class TestMoERematPolicy:
    @pytest.mark.slow
    def test_moe_policy_grads_match_full_remat(self):
        """remat_policy='moe' (saves the tagged expert-FFN matmuls and
        dispatch intermediates) must produce the same gradients as plain
        full remat — it changes what the backward recomputes, not the
        math.  Locks in the moe_x/moe_y/ffn_* checkpoint_name markers."""
        import dataclasses

        from kubeflow_controller_tpu.models import llama_init, llama_loss

        base = LlamaConfig.tiny(n_experts=4, moe_top_k=2, remat=True,
                                remat_policy="full")
        moe_pol = dataclasses.replace(base, remat_policy="moe")
        params = llama_init(jax.random.PRNGKey(0), base)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                                    base.vocab_size)
        g_full = jax.grad(lambda p: llama_loss(p, tokens, base))(params)
        g_moe = jax.grad(lambda p: llama_loss(p, tokens, moe_pol))(params)
        for a, b in zip(jax.tree.leaves(g_full), jax.tree.leaves(g_moe)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-5, rtol=1e-5)


class TestGroupedDispatch:
    """The megablocks-style grouped path (ops/grouped_matmul.py) — dropless,
    so the oracle is moe_ffn_reference, not the capacity paths.  Off-TPU
    the kernels run under interpret=True, so shapes must satisfy the TPU
    tiling grain (last dims multiples of (8, 128))."""

    def _big_weights(self, key, D=128, E=4, F=256):
        ks = jax.random.split(key, 4)
        return (
            jax.random.normal(ks[0], (D, E)) * 0.1,
            jax.random.normal(ks[1], (E, D, F)) * 0.05,
            jax.random.normal(ks[2], (E, D, F)) * 0.05,
            jax.random.normal(ks[3], (E, F, D)) * 0.05,
        )

    def test_gmm_kernel_and_grads_match_reference(self):
        from kubeflow_controller_tpu.ops.grouped_matmul import gmm, gmm_reference

        M, K, N, E, bm = 64, 128, 256, 4, 8
        ks = jax.random.split(jax.random.PRNGKey(0), 3)
        lhs = jax.random.normal(ks[0], (M, K), jnp.float32)
        rhs = jax.random.normal(ks[1], (E, K, N), jnp.float32)
        te = jnp.sort(jax.random.randint(ks[2], (M // bm,), 0, E)).astype(jnp.int32)
        np.testing.assert_allclose(
            np.asarray(gmm(lhs, rhs, te, None, bm, 128, 128)),
            np.asarray(gmm_reference(lhs, rhs, te, bm)),
            atol=1e-4, rtol=1e-4)

        def l_k(l, r):
            return jnp.sum(gmm(l, r, te, None, bm, 128, 128) ** 2)

        def l_r(l, r):
            return jnp.sum(gmm_reference(l, r, te, bm) ** 2)

        gk = jax.grad(l_k, argnums=(0, 1))(lhs, rhs)
        gr = jax.grad(l_r, argnums=(0, 1))(lhs, rhs)
        for a, b in zip(gk, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-3, rtol=1e-3)

    def test_grouped_matches_dropless_oracle(self):
        from kubeflow_controller_tpu.models.moe import moe_ffn_stats

        router, wg, wu, wd = self._big_weights(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 16, 128))
        y, stats = moe_ffn_stats(x, router, wg, wu, wd, top_k=2,
                                 dispatch="grouped")
        ref = moe_ffn_reference(x, router, wg, wu, wd, top_k=2)
        np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                                   atol=2e-4, rtol=2e-4)
        assert float(stats["overflow_frac"]) == 0.0  # dropless by design

    @pytest.mark.slow
    def test_grouped_grads_match_oracle(self):
        from kubeflow_controller_tpu.models.moe import moe_ffn_stats

        router, wg, wu, wd = self._big_weights(jax.random.PRNGKey(2))
        x = jax.random.normal(jax.random.PRNGKey(3), (2, 16, 128))

        def l_g(x, r, wg_, wu_, wd_):
            return jnp.sum(moe_ffn_stats(x, r, wg_, wu_, wd_, top_k=2,
                                         dispatch="grouped")[0] ** 2)

        def l_r(x, r, wg_, wu_, wd_):
            return jnp.sum(
                moe_ffn_reference(x, r, wg_, wu_, wd_, top_k=2) ** 2)

        gg = jax.grad(l_g, argnums=(0, 1, 2, 3, 4))(x, router, wg, wu, wd)
        gr = jax.grad(l_r, argnums=(0, 1, 2, 3, 4))(x, router, wg, wu, wd)
        for a, b in zip(gg, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=5e-3, rtol=5e-3)

    def test_block_m_below_sublane_tile_falls_back(self):
        """ADVICE round 5: block_m smaller than the dtype's sublane tile
        (8 rows for f32) cannot form a legal Mosaic tile — the eligibility
        gate must route to the einsum fallback, not crash the kernel."""
        from kubeflow_controller_tpu.models.moe import moe_ffn_stats

        router, wg, wu, wd = self._big_weights(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 16, 128))
        with pytest.warns(UserWarning, match="falling back to 'einsum'"):
            y, _ = moe_ffn_stats(x, router, wg, wu, wd, top_k=2,
                                 dispatch="grouped", block_m=4)
        ye, _ = moe_ffn_stats(x, router, wg, wu, wd, top_k=2,
                              dispatch="einsum")
        np.testing.assert_allclose(np.asarray(y), np.asarray(ye),
                                   atol=1e-6, rtol=1e-6)

    def test_block_m_non_power_of_two_rounds_down(self):
        """ADVICE round 5: a non-power-of-two block_m (300) used to halve
        through odd/sub-tile sizes (300->75->...) and fail Mosaic; it now
        rounds down to a power of two (256) and the grouped path still
        matches the dropless oracle."""
        from kubeflow_controller_tpu.models.moe import moe_ffn_stats

        router, wg, wu, wd = self._big_weights(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 16, 128))
        y, stats = moe_ffn_stats(x, router, wg, wu, wd, top_k=2,
                                 dispatch="grouped", block_m=300)
        ref = moe_ffn_reference(x, router, wg, wu, wd, top_k=2)
        np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                                   atol=2e-4, rtol=2e-4)
        assert float(stats["overflow_frac"]) == 0.0

    def test_grouped_falls_back_below_tile_grain(self):
        from kubeflow_controller_tpu.models.moe import moe_ffn_stats

        router, wg, wu, wd = _weights(jax.random.PRNGKey(0))  # D=16 < 128
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 16, 16))
        with pytest.warns(UserWarning, match="falling back to 'einsum'"):
            y, _ = moe_ffn_stats(x, router, wg, wu, wd, top_k=2,
                                 dispatch="grouped")
        ye, _ = moe_ffn_stats(x, router, wg, wu, wd, top_k=2,
                              dispatch="einsum")
        np.testing.assert_allclose(np.asarray(y), np.asarray(ye),
                                   atol=1e-6, rtol=1e-6)

    def test_grouped_runs_sharded_under_mesh(self):
        """Dropless grouped dispatch under an active dp/fsdp/ep/tp mesh:
        no fallback warning, matches the dense dropless oracle."""
        import warnings

        from kubeflow_controller_tpu.models.moe import (
            moe_ffn_reference,
            moe_ffn_stats,
        )

        router, wg, wu, wd = self._big_weights(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 16, 128))
        ref = moe_ffn_reference(x, router, wg, wu, wd, top_k=2)
        mesh = build_mesh(MeshSpec(dp=1, fsdp=2, ep=2, tp=2))
        with compat_set_mesh(mesh):
            with warnings.catch_warnings():
                warnings.simplefilter("error")  # any fallback = test failure
                y, stats = jax.jit(
                    lambda x: moe_ffn_stats(x, router, wg, wu, wd, top_k=2,
                                            dispatch="grouped"))(x)
        np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                                   atol=1e-4, rtol=1e-4)
        assert float(stats["overflow_frac"]) == 0.0

    def test_grouped_sharded_grads_match_dense_oracle(self):
        from kubeflow_controller_tpu.models.moe import (
            moe_ffn_reference,
            moe_ffn_stats,
        )

        router, wg, wu, wd = self._big_weights(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 16, 128))

        def loss_ref(w, x):
            return jnp.sum(moe_ffn_reference(x, router, w, wu, wd,
                                             top_k=2) ** 2)

        def loss_grp(w, x):
            return jnp.sum(moe_ffn_stats(x, router, w, wu, wd, top_k=2,
                                         dispatch="grouped")[0] ** 2)

        gw_ref, gx_ref = jax.grad(loss_ref, argnums=(0, 1))(wg, x)
        mesh = build_mesh(MeshSpec(dp=1, fsdp=2, ep=2, tp=2))
        with compat_set_mesh(mesh):
            gw, gx = jax.jit(jax.grad(loss_grp, argnums=(0, 1)))(wg, x)
        np.testing.assert_allclose(np.asarray(gw), np.asarray(gw_ref),
                                   atol=2e-4, rtol=2e-4)
        np.testing.assert_allclose(np.asarray(gx), np.asarray(gx_ref),
                                   atol=2e-4, rtol=2e-4)

    def test_grouped_runs_under_pp_mesh(self):
        """Round-5: grouped no longer falls back under a pp>1 mesh — its
        manual region excludes pp from axis_names (tokens/weights are
        simply replicated over pp here; under a real pipeline the region
        nests inside the stage body's manual-over-pp shard_map, covered by
        test_pipeline + the dryrun)."""
        import warnings

        from kubeflow_controller_tpu.models.moe import moe_ffn_stats

        router, wg, wu, wd = self._big_weights(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 16, 128))
        mesh = build_mesh(MeshSpec(pp=2, ep=2, fsdp=2))
        with compat_set_mesh(mesh):
            with warnings.catch_warnings():
                warnings.simplefilter("error")  # any fallback warning fails
                # jit required: partial-manual shard_map (pp left auto) has
                # no eager impl in jax 0.9.
                y, stats = jax.jit(
                    lambda x: moe_ffn_stats(x, router, wg, wu, wd, top_k=2,
                                            dispatch="grouped"))(x)
        # Dropless: the oracle is moe_ffn_reference (einsum would differ on
        # exactly the ~3% of tokens its capacity limit drops).
        ref = moe_ffn_reference(x, router, wg, wu, wd, top_k=2)
        assert float(stats["overflow_frac"]) == 0.0  # dropless
        np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                                   atol=1e-4, rtol=1e-4)

    def test_gmm_valid_tiles_skip(self):
        from kubeflow_controller_tpu.ops.grouped_matmul import (
            gmm,
            gmm_reference,
        )

        M, K, N, E, bm = 64, 128, 256, 4, 8
        ks = jax.random.split(jax.random.PRNGKey(7), 3)
        lhs = jax.random.normal(ks[0], (M, K), jnp.float32)
        rhs = jax.random.normal(ks[1], (E, K, N), jnp.float32)
        te = jnp.sort(jax.random.randint(ks[2], (M // bm,), 0, E)).astype(
            jnp.int32)
        valid = jnp.asarray([5], jnp.int32)
        out = gmm(lhs, rhs, te, valid, bm, 128, 128)
        ref = gmm_reference(lhs, rhs, te, bm)
        np.testing.assert_allclose(np.asarray(out[: 5 * bm]),
                                   np.asarray(ref[: 5 * bm]),
                                   atol=1e-4, rtol=1e-4)
        np.testing.assert_allclose(np.asarray(out[5 * bm:]), 0.0)

        # Gradients: cotangent on the skipped region must not leak into
        # dlhs or drhs.
        cot = jax.random.normal(ks[0], (M, N), jnp.float32)

        def f(l, r):
            return jnp.sum(gmm(l, r, te, valid, bm, 128, 128) * cot)

        def f_ref(l, r):
            mask = (jnp.arange(M) < 5 * bm)[:, None]
            return jnp.sum(gmm_reference(l, r, te, bm) * (cot * mask))

        gl, gr = jax.grad(f, argnums=(0, 1))(lhs, rhs)
        gl_ref, gr_ref = jax.grad(f_ref, argnums=(0, 1))(lhs, rhs)
        np.testing.assert_allclose(np.asarray(gl), np.asarray(gl_ref),
                                   atol=1e-4, rtol=1e-4)
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gr_ref),
                                   atol=1e-4, rtol=1e-4)
