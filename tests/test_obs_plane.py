"""Observability-plane tests: causal trace context (propagation, sampling,
cross-process reassembly), the retained-series TSDB (retention, downsample,
series budget, query surface), SLO burn-rate edge exactness, scrape-time
histogram quantiles, and flight-recorder bundle completeness on a chaos
kill (ISSUE 16; docs/OBSERVABILITY.md)."""

import json
import os
import subprocess
import sys
import time

import pytest

from kubeflow_controller_tpu.obs import flight
from kubeflow_controller_tpu.obs import trace as trace_mod
from kubeflow_controller_tpu.obs.metrics import Registry
from kubeflow_controller_tpu.obs.slo import (
    KIND_HISTOGRAM_QUANTILE,
    Objective,
    SLOEngine,
    default_objectives,
)
from kubeflow_controller_tpu.obs.trace import (
    TRACE_CONTEXT_ENV,
    TRACE_DIR_ENV,
    TRACE_SAMPLE_ENV,
    TraceContext,
    Tracer,
    causal_tree,
    event_ids,
    events_for_trace,
    merge_trace_dir,
    orphan_events,
)
from kubeflow_controller_tpu.obs.tsdb import TSDB


# ---------------------------------------------------------------------------
# Trace context
# ---------------------------------------------------------------------------

class TestTraceContext:
    def test_encode_decode_roundtrip(self):
        ctx = TraceContext.for_job("uid-123")
        back = TraceContext.decode(ctx.encode())
        assert back is not None
        assert back.trace_id == ctx.trace_id
        assert back.span_id == ctx.span_id

    def test_for_job_is_deterministic(self):
        a, b = TraceContext.for_job("uid-x"), TraceContext.for_job("uid-x")
        assert a.trace_id == b.trace_id and a.span_id == b.span_id
        assert TraceContext.for_job("uid-y").trace_id != a.trace_id

    @pytest.mark.parametrize("junk", ["", "abc", ":b:01", "::", "x" * 200])
    def test_decode_damaged_returns_none(self, junk):
        assert TraceContext.decode(junk) is None

    def test_root_span_has_no_self_edge(self):
        """Emitting the root span (span_id == ctx.span_id) must not default
        a parent edge onto itself — the tree walk would loop."""
        t = Tracer()
        ctx = TraceContext.for_job("uid-root")
        sp = t.add_span("job/submit", 1.0, 0.5, ctx=ctx, span_id=ctx.span_id)
        assert sp is not None
        assert sp.span_id == ctx.span_id
        assert sp.parent_id == ""

    def test_ctx_spans_parent_to_context_root(self):
        t = Tracer()
        ctx = TraceContext.for_job("uid-p")
        sp = t.add_span("sched/queue_wait", 1.0, 0.1, ctx=ctx)
        assert sp.trace_id == ctx.trace_id
        assert sp.parent_id == ctx.span_id

    def test_causal_tree_tolerates_self_edge(self):
        """A damaged event whose parent_id == span_id is treated as a root,
        not an infinite loop."""
        evs = [{"name": "broken", "ts": 0, "args": {
            "trace_id": "t1", "span_id": "s1", "parent_id": "s1"}}]
        roots, children = causal_tree(evs)
        assert len(roots) == 1 and not children.get("s1")

    def test_sampling_drops_ctx_spans_only(self, monkeypatch):
        monkeypatch.setenv(TRACE_SAMPLE_ENV, "0.0")
        t = Tracer()
        ctx = TraceContext.for_job("uid-sampled-out")
        assert t.add_span("dropped", 1.0, 0.1, ctx=ctx) is None
        assert t.add_span("kept", 1.0, 0.1) is not None
        names = [s.name for s in t.spans()]
        assert names == ["kept"]

    def test_sample_rate_one_keeps_everything(self, monkeypatch):
        monkeypatch.setenv(TRACE_SAMPLE_ENV, "1.0")
        t = Tracer()
        ctx = TraceContext.for_job("uid-kept")
        assert t.add_span("kept", 1.0, 0.1, ctx=ctx) is not None


class TestCrossProcessReassembly:
    def test_subprocess_spans_join_one_connected_tree(self, tmp_path):
        """The e2e contract: a workload process that inherits
        $KCTPU_TRACE_CONTEXT emits spans, dumps them to $KCTPU_TRACE_DIR,
        and the merged document is ONE connected tree — single trace_id,
        two pids, zero orphans."""
        ctx = TraceContext.for_job("uid-e2e")
        parent = Tracer()
        parent.add_span("job/submit", time.time(), 0.01,
                        ctx=ctx, span_id=ctx.span_id, job="e2e")

        child_code = (
            "import time\n"
            "from kubeflow_controller_tpu.obs import trace\n"
            "ctx = trace.process_context()\n"
            "assert ctx is not None, 'context not inherited from env'\n"
            "sp = trace.add_span('workload/first_step', time.time(), 0.01,\n"
            "                    ctx=ctx)\n"
            "trace.add_span('workload/io', time.time(), 0.005, ctx=ctx,\n"
            "               parent_id=sp.span_id)\n"
            "path = trace.dump_to_env_dir()\n"
            "assert path, 'dump_to_env_dir wrote nothing'\n"
        )
        env = dict(os.environ)
        env[TRACE_CONTEXT_ENV] = ctx.encode()
        env[TRACE_DIR_ENV] = str(tmp_path)
        env.pop(TRACE_SAMPLE_ENV, None)
        subprocess.run([sys.executable, "-c", child_code], env=env,
                       check=True, timeout=60)

        doc = merge_trace_dir(str(tmp_path), tracer=parent)
        evs = events_for_trace(doc["traceEvents"], ctx.trace_id)
        assert len(evs) == 3
        assert len({e["pid"] for e in evs}) == 2
        assert orphan_events(evs) == []
        by_name = {e["name"]: e for e in evs}
        # The child's top span hangs off the job root; its sub-span off it.
        assert event_ids(by_name["workload/first_step"])[2] == ctx.span_id
        assert (event_ids(by_name["workload/io"])[2]
                == event_ids(by_name["workload/first_step"])[1])

    def test_merge_dedups_double_dumps(self, tmp_path):
        """A process may dump twice (explicit end-of-main + the zygote
        safety net); the merged tree must carry each span once."""
        t = Tracer()
        ctx = TraceContext.for_job("uid-dup")
        t.add_span("work", time.time(), 0.01, ctx=ctx, span_id=ctx.span_id)
        os.environ[TRACE_DIR_ENV] = str(tmp_path)
        try:
            assert trace_mod.dump_to_env_dir(t)
            assert trace_mod.dump_to_env_dir(t)
        finally:
            del os.environ[TRACE_DIR_ENV]
        evs = merge_trace_dir(str(tmp_path))["traceEvents"]
        assert len(evs) == 1


# ---------------------------------------------------------------------------
# TSDB
# ---------------------------------------------------------------------------

def mk_tsdb(**kw):
    reg = Registry()
    g = reg.gauge("kctpu_x", "test gauge", ("job",))
    kw.setdefault("retention_s", 10.0)
    kw.setdefault("coarse_step_s", 5.0)
    kw.setdefault("coarse_retention_s", 60.0)
    return reg, g, TSDB(registry=reg, **kw)


class TestTSDB:
    def test_raw_points_within_retention(self):
        reg, g, db = mk_tsdb()
        for i in range(5):
            g.labels("a").set(float(i))
            db.sample_once(1000.0 + i)
        pts = db.points("kctpu_x", {"job": "a"}, 1000.0, 1004.0)
        assert [v for _, v in pts] == [0.0, 1.0, 2.0, 3.0, 4.0]

    def test_downsample_past_raw_horizon(self):
        """Points aging out of the raw ring land in the coarse ring — ONE
        point per coarse step, the newest sample in the step winning."""
        reg, g, db = mk_tsdb()  # retention 10s, coarse step 5s
        for i in range(30):
            g.labels("a").set(float(i))
            db.sample_once(1000.0 + i)
        # Raw ring holds only the last 10s.
        s = db._get("kctpu_x", {"job": "a"})
        assert all(ts >= 1029.0 - 10.0 for ts, _ in s.raw)
        # Aged points collapsed to one per 5s step, newest-in-step value.
        steps = [ts for ts, _ in s.coarse]
        assert steps == sorted(set(steps)), "one point per coarse step"
        by_step = dict(s.coarse)
        assert by_step[1000.0] == 4.0  # samples 1000-1004 -> newest (value 4)

    def test_coarse_retention_evicts(self):
        reg, g, db = mk_tsdb(coarse_retention_s=20.0)
        for i in range(100):
            g.labels("a").set(float(i))
            db.sample_once(1000.0 + i)
        s = db._get("kctpu_x", {"job": "a"})
        assert all(ts >= 1099.0 - 20.0 for ts, _ in s.coarse)

    def test_series_budget_drops_overflow(self):
        reg, g, db = mk_tsdb(max_series=4)
        for i in range(10):
            g.labels(f"job-{i}").set(1.0)
        db.sample_once(1000.0)
        assert db.series_count() == 4
        # The drop counter is part of the sampled registry's catalogue.
        fams = {f.name: f for f in reg.families()}
        assert fams["kctpu_tsdb_series_dropped_total"].samples[0].value > 0

    def test_rate_over_window(self):
        reg, g, db = mk_tsdb(retention_s=100.0)
        for i in range(11):
            g.labels("a").set(float(i * 10))  # +10/s
            db.sample_once(1000.0 + i)
        r = db.rate("kctpu_x", {"job": "a"}, 10.0, now=1010.0)
        assert r == pytest.approx(10.0, rel=1e-6)

    def test_query_surface(self):
        reg, g, db = mk_tsdb(retention_s=100.0)
        g.labels("a").set(7.0)
        db.sample_once(1000.0)
        out = db.query({"op": "latest", "name": "kctpu_x",
                        "labels": json.dumps({"job": "a"})})
        assert out["point"][1] == 7.0
        assert "error" in db.query({"op": "nope", "name": "kctpu_x"})
        assert "error" in db.query({"op": "latest", "name": ""})
        assert "error" in db.query({"op": "latest", "name": "kctpu_x",
                                    "labels": "[1,2]"})
        names = db.query({"op": "series"})["series"]
        assert "kctpu_x" in names

    def test_avg_over_time(self):
        reg, g, db = mk_tsdb(retention_s=100.0)
        for i, v in enumerate([1.0, 2.0, 3.0, 4.0]):
            g.labels("a").set(v)
            db.sample_once(1000.0 + i)
        avg = db.avg_over_time("kctpu_x", {"job": "a"}, 10.0, now=1003.0)
        assert avg == pytest.approx(2.5)

    def test_rate_counter_reset_clamps_to_zero(self):
        """A counter reset (process restart: cumulative value drops) must
        not read as a huge negative rate — the goodput badput counters
        feed burn-rate SLOs through exactly this path."""
        reg, g, db = mk_tsdb(retention_s=100.0)
        for i in range(6):
            g.labels("a").set(float(i * 10))   # climbs to 50
            db.sample_once(1000.0 + i)
        g.labels("a").set(5.0)                  # restart: 50 -> 5
        db.sample_once(1006.0)
        # Window [1003, 1006]: 30 -> 5 across the reset.
        r = db.rate("kctpu_x", {"job": "a"}, 3.0, now=1006.0)
        assert r == 0.0                         # clamped, never negative

    def test_rate_after_reset_resumes(self):
        """Once the window no longer straddles the reset, the rate is the
        honest post-restart slope again."""
        reg, g, db = mk_tsdb(retention_s=100.0)
        g.labels("a").set(50.0)
        db.sample_once(1000.0)
        for i in range(11):
            g.labels("a").set(float(i * 2))     # reset, then +2/s
            db.sample_once(1001.0 + i)
        r = db.rate("kctpu_x", {"job": "a"}, 10.0, now=1011.0)
        assert r == pytest.approx(2.0, rel=1e-6)

    def test_avg_over_time_spans_reset_without_poisoning(self):
        """avg_over_time is a plain mean of window points — a counter
        reset inside the window lowers it but can never make it negative
        or blow it up (what the DIRECTION_BELOW goodput SLOs consume)."""
        reg, g, db = mk_tsdb(retention_s=100.0)
        for i, v in enumerate([0.9, 0.9, 0.1, 0.1]):   # ratio collapse
            g.labels("a").set(v)
            db.sample_once(1000.0 + i)
        avg = db.avg_over_time("kctpu_x", {"job": "a"}, 10.0, now=1003.0)
        assert avg == pytest.approx(0.5)
        assert 0.0 <= avg <= 1.0


# ---------------------------------------------------------------------------
# SLO burn-rate engine
# ---------------------------------------------------------------------------

def mk_slo_rig(objective=None):
    reg = Registry()
    g = reg.gauge("kctpu_serve_ttft_p99_ms", "test", ("namespace", "tfjob"))
    db = TSDB(registry=reg, retention_s=300.0)
    obj = objective or Objective(
        name="ttft", description="p99 ttft <= 2s",
        metric="kctpu_serve_ttft_p99_ms", threshold=2000.0,
        error_budget=0.05, fast_window_s=10.0, slow_window_s=30.0,
        burn_threshold=2.0)
    edges = []
    eng = SLOEngine(db, objectives=[obj], registry=reg,
                    notifier=lambda st, fired: edges.append(
                        (fired, st.series_label())))
    return g, db, eng, edges


class TestSLOBurn:
    def drive(self, g, db, eng, t0, n, value):
        for i in range(n):
            g.labels("default", "j").set(value)
            db.sample_once(t0 + i)
            eng.evaluate_once(t0 + i)
        return t0 + n

    def test_fire_and_resolve_edges_are_exact(self):
        g, db, eng, edges = mk_slo_rig()
        t = self.drive(g, db, eng, 1000.0, 30, 100.0)   # healthy
        assert edges == []
        t = self.drive(g, db, eng, t, 40, 5000.0)        # sustained breach
        assert edges == [(True, "namespace=default,tfjob=j")]
        t = self.drive(g, db, eng, t, 40, 100.0)         # recovery
        assert edges == [(True, "namespace=default,tfjob=j"),
                         (False, "namespace=default,tfjob=j")]
        st = [s for s in eng.alerts(active_only=False) if s["slo"] == "ttft"]
        assert st and st[0]["transitions"] == 1 and not st[0]["active"]

    def test_blip_does_not_fire(self):
        """One violating sample trips the fast window but not the slow one
        — the multi-window rule holds the alert back."""
        g, db, eng, edges = mk_slo_rig()
        t = self.drive(g, db, eng, 1000.0, 30, 100.0)
        t = self.drive(g, db, eng, t, 1, 5000.0)   # 1/31 in slow window
        self.drive(g, db, eng, t, 5, 100.0)
        assert edges == []

    def test_no_refire_while_active(self):
        g, db, eng, edges = mk_slo_rig()
        t = self.drive(g, db, eng, 1000.0, 10, 5000.0)
        self.drive(g, db, eng, t, 100, 5000.0)  # stays bad for a long time
        assert [f for f, _ in edges] == [True]

    def test_alert_gauges_follow_state(self):
        g, db, eng, edges = mk_slo_rig()
        t = self.drive(g, db, eng, 1000.0, 40, 5000.0)
        fams = {f.name: f for f in eng.registry.families()}
        active = {tuple(sorted(s.labels.items())): s.value
                  for s in fams["kctpu_slo_alert_active"].samples}
        key = (("series", "namespace=default,tfjob=j"), ("slo", "ttft"))
        assert active[key] == 1.0
        self.drive(g, db, eng, t, 40, 100.0)
        fams = {f.name: f for f in eng.registry.families()}
        active = {tuple(sorted(s.labels.items())): s.value
                  for s in fams["kctpu_slo_alert_active"].samples}
        assert active[key] == 0.0

    def test_histogram_quantile_objective(self):
        reg = Registry()
        h = reg.histogram("kctpu_lat_seconds", "test", ("tfjob",),
                          buckets=(0.1, 1.0, 10.0))
        db = TSDB(registry=reg, retention_s=300.0)
        obj = Objective(
            name="lat-p99", description="p99 <= 1s",
            metric="kctpu_lat_seconds", threshold=1.0,
            kind=KIND_HISTOGRAM_QUANTILE, q=0.99,
            error_budget=0.05, fast_window_s=10.0, slow_window_s=30.0,
            burn_threshold=2.0, subject_labels=("tfjob",))
        edges = []
        eng = SLOEngine(db, objectives=[obj], registry=reg,
                        notifier=lambda st, f: edges.append(f))
        for i in range(40):
            h.labels("j").observe(5.0)   # p99 lands in the 10s bucket
            db.sample_once(1000.0 + i)
            eng.evaluate_once(1000.0 + i)
        assert edges == [True]

    def test_set_objectives_resets_state(self):
        g, db, eng, edges = mk_slo_rig()
        self.drive(g, db, eng, 1000.0, 40, 5000.0)
        assert [f for f, _ in edges] == [True]
        eng.set_objectives([])
        assert eng.alerts(active_only=False) == []

    def test_default_catalogue_shape(self):
        objs = {o.name for o in default_objectives()}
        assert {"serving-ttft-p99", "job-ttfs", "job-stall-rate",
                "failover-time", "sched-queue-wait"} <= objs


# ---------------------------------------------------------------------------
# Scrape-time histogram quantiles (Registry.histogram_quantile)
# ---------------------------------------------------------------------------

class TestScrapeTimeQuantiles:
    def test_quantile_from_live_histogram(self):
        reg = Registry()
        h = reg.histogram("kctpu_d_seconds", "test", ("job",),
                          buckets=(0.1, 1.0, 10.0))
        for _ in range(9):
            h.labels("a").observe(0.05)
        h.labels("a").observe(5.0)  # rank q*10=9.9 -> the 10s bucket
        p50 = reg.histogram_quantile("kctpu_d_seconds", {"job": "a"}, 0.5)
        p99 = reg.histogram_quantile("kctpu_d_seconds", {"job": "a"}, 0.99)
        assert p50 <= 0.1
        assert 1.0 < p99 <= 10.0

    def test_quantile_missing_family_is_zero(self):
        reg = Registry()
        assert reg.histogram_quantile("nope", {}, 0.99) == 0.0


# ---------------------------------------------------------------------------
# Flight recorder
# ---------------------------------------------------------------------------

class TestFlightRecorder:
    def test_disabled_without_dir(self, monkeypatch):
        monkeypatch.delenv(flight.DEBUG_DIR_ENV, raising=False)
        assert flight.record_flight("default", "j") is None

    def test_bundle_completeness(self, tmp_path):
        reg = Registry()
        g = reg.gauge("kctpu_y", "test")
        db = TSDB(registry=reg, retention_s=300.0)
        g.set(3.0)
        db.sample_once(1000.0)
        path = flight.record_flight(
            "default", "j", reason="Test", trace_id="",
            events=[{"type": "Warning", "reason": "X", "message": "m"}],
            progress={"p0": {"step": 7}},
            status_history=[{"from": "Created", "to": "Running", "at": 1.0}],
            status={"phase": "Failed"},
            goodput={"ratio": 0.8, "buckets": {"train": 80.0}},
            tsdb=db, out_dir=str(tmp_path), now=1000.0)
        assert path is not None
        bundle = flight.read_bundle(path)
        assert set(bundle) == {"manifest.json", "trace.json", "events.json",
                               "progress.json", "status.json", "tsdb.json",
                               "goodput.json"}
        m = bundle["manifest.json"]
        assert m["reason"] == "Test" and m["events"] == 1
        assert set(m["files"]) == set(bundle)
        assert bundle["status.json"]["history"][0]["to"] == "Running"
        assert bundle["progress.json"]["p0"]["step"] == 7
        assert bundle["goodput.json"]["buckets"]["train"] == 80.0
        tsdb_names = {s["name"] for s in bundle["tsdb.json"]["series"]}
        assert "kctpu_y" in tsdb_names

    def test_read_bundle_skips_damage(self, tmp_path):
        (tmp_path / "good.json").write_text('{"a": 1}')
        (tmp_path / "bad.json").write_text("{nope")
        out = flight.read_bundle(str(tmp_path))
        assert out == {"good.json": {"a": 1}}


@pytest.mark.slow
class TestFlightRecorderE2E:
    def test_chaos_kill_cuts_complete_bundle(self, tmp_path, monkeypatch):
        """A restart_policy Never job chaos-killed mid-run must leave a
        postmortem bundle: causal trace, event ring, status history."""
        from kubeflow_controller_tpu.api.core import (
            Container, PodTemplateSpec)
        from kubeflow_controller_tpu.api.meta import ObjectMeta
        from kubeflow_controller_tpu.api.tfjob import (
            ReplicaType, TFJob, TFJobPhase, TFReplicaSpec)
        from kubeflow_controller_tpu.cluster import (
            Cluster, FakeKubelet, PhasePolicy)
        from kubeflow_controller_tpu.controller import Controller

        monkeypatch.setenv(flight.DEBUG_DIR_ENV, str(tmp_path))
        cluster = Cluster()
        kubelet = FakeKubelet(cluster, policy=PhasePolicy(run_s=60.0))
        ctrl = Controller(cluster, resync_period_s=0.5)
        kubelet.start()
        ctrl.run(threadiness=2)
        try:
            t = PodTemplateSpec()
            t.spec.containers.append(Container(name="w", image="img"))
            t.spec.restart_policy = "Never"
            job = TFJob(metadata=ObjectMeta(name="doomed",
                                            namespace="default"))
            job.spec.tf_replica_specs.append(TFReplicaSpec(
                replicas=1, tf_replica_type=ReplicaType.WORKER, template=t))
            cluster.tfjobs.create(job)

            def wait_for(cond, timeout=15.0):
                deadline = time.time() + timeout
                while time.time() < deadline:
                    if cond():
                        return True
                    time.sleep(0.05)
                return False

            def running_pod():
                for p in cluster.pods.list("default"):
                    if (p.metadata.name.startswith("doomed-")
                            and p.status.phase == "Running"):
                        return p
                return None

            assert wait_for(lambda: running_pod() is not None)
            victim = running_pod().metadata.name
            assert kubelet.chaos_kill("default", victim) == "simulated"
            assert wait_for(
                lambda: cluster.tfjobs.get("default", "doomed").status.phase
                == TFJobPhase.FAILED)

            def bundle_dir():
                return [d for d in os.listdir(str(tmp_path))
                        if d.startswith("default-doomed-")]

            assert wait_for(lambda: bool(bundle_dir()))
            bundle = flight.read_bundle(
                os.path.join(str(tmp_path), bundle_dir()[0]))
            assert {"manifest.json", "trace.json", "events.json",
                    "progress.json", "status.json",
                    "tsdb.json"} <= set(bundle)
            m = bundle["manifest.json"]
            assert m["reason"] == "JobFailed"
            assert m["trace_id"], "bundle must name the job's trace"
            # The causal trace made it into the bundle and is connected.
            evs = bundle["trace.json"]["traceEvents"]
            assert evs and orphan_events(evs) == []
            assert all(event_ids(e)[0] == m["trace_id"] for e in evs)
            # Event ring captured the lifecycle (SuccessfulCreate at least).
            assert any(e["reason"] == "SuccessfulCreate"
                       for e in bundle["events.json"])
            # Status history recorded the terminal transition.
            hist = bundle["status.json"]["history"]
            assert any(h["to"] == "Failed" for h in hist)
        finally:
            ctrl.stop()
            kubelet.stop()
