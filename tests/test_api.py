"""API-layer tests: serde round-trips, validation, classifiers, helpers.

Modeled on the reference's only first-party unit test — the table-driven
replica-type classifier test (pkg/checker/checker_test.go:26-54) — then
extended to the full schema surface.
"""

import pytest

from kubeflow_controller_tpu.api import (
    API_VERSION,
    Container,
    Pod,
    PodTemplateSpec,
    ReplicaType,
    ResourceRequirements,
    TFJob,
    TFJobSpec,
    TFReplicaSpec,
    TPUSpec,
    validate_tfjob,
)
from kubeflow_controller_tpu.api.core import (
    PHASE_FAILED,
    PHASE_RUNNING,
    PHASE_SUCCEEDED,
    filter_active_pods,
    get_status,
)
from kubeflow_controller_tpu.api.meta import ObjectMeta, key_of, split_key
from kubeflow_controller_tpu.api.tfjob import (
    ValidationError,
    is_local_job,
    is_tpu_job,
    replica_spec_for,
    tpu_slice_chips,
    tpu_slice_hosts,
)
from kubeflow_controller_tpu.utils import serde
from kubeflow_controller_tpu.utils.names import generate_name, generate_runtime_id


def mk_template() -> PodTemplateSpec:
    t = PodTemplateSpec()
    t.spec.containers.append(Container(name="tensorflow", image="img", args=["a"]))
    return t


def mk_job(*types_and_replicas) -> TFJob:
    job = TFJob(metadata=ObjectMeta(name="dist-mnist", namespace="default", uid="u1"))
    for typ, n in types_and_replicas:
        spec = TFReplicaSpec(replicas=n, tf_replica_type=typ, template=mk_template())
        if typ == ReplicaType.TPU:
            spec.tpu = TPUSpec(accelerator_type="v5e-8")
        job.spec.tf_replica_specs.append(spec)
    return job


# ---- classifier (table-driven, mirroring checker_test.go:26-54) ----

@pytest.mark.parametrize(
    "types,expect_local",
    [
        ([ReplicaType.LOCAL], True),
        ([ReplicaType.WORKER], False),
        ([ReplicaType.PS, ReplicaType.WORKER], False),
        ([ReplicaType.WORKER, ReplicaType.PS], False),
        ([ReplicaType.TPU], False),
    ],
)
def test_is_local_job(types, expect_local):
    job = mk_job(*[(t, 1) for t in types])
    assert is_local_job(job) == expect_local


def test_is_tpu_job():
    assert is_tpu_job(mk_job((ReplicaType.TPU, 2)))
    assert not is_tpu_job(mk_job((ReplicaType.WORKER, 2)))


# ---- serde ----

def test_serde_round_trip_camel_case():
    job = mk_job((ReplicaType.PS, 2), (ReplicaType.WORKER, 4))
    job.spec.model_dir = "/ckpt"
    d = serde.to_dict(job)
    assert d["apiVersion"] == API_VERSION
    assert d["spec"]["modelDir"] == "/ckpt"
    assert d["spec"]["tfReplicaSpecs"][0]["tfReplicaType"] == "PS"
    assert d["spec"]["tfReplicaSpecs"][1]["replicas"] == 4
    back = serde.from_dict(TFJob, d)
    assert back.spec.tf_replica_specs[1].tf_replica_type == ReplicaType.WORKER
    assert back.spec.tf_replica_specs[1].replicas == 4
    assert back.spec.model_dir == "/ckpt"


def test_serde_omits_none_and_ignores_unknown():
    d = serde.to_dict(TFJob(metadata=ObjectMeta(name="x")))
    assert "deletionTimestamp" not in d["metadata"]
    back = serde.from_dict(TFJob, {"metadata": {"name": "x", "futureField": 1}})
    assert back.metadata.name == "x"


def test_deep_copy_isolates_template_mutation():
    # The reference's shared-template mutation bug (distributed.go:120-128).
    job = mk_job((ReplicaType.WORKER, 2))
    cp = serde.deep_copy(job)
    cp.spec.tf_replica_specs[0].template.spec.containers[0].args.append("--task_index=1")
    assert job.spec.tf_replica_specs[0].template.spec.containers[0].args == ["a"]


# ---- validation ----

def test_validate_ok():
    validate_tfjob(mk_job((ReplicaType.PS, 2), (ReplicaType.WORKER, 4)))
    validate_tfjob(mk_job((ReplicaType.LOCAL, 1)))
    validate_tfjob(mk_job((ReplicaType.TPU, 2)))  # v5e-8 = 2 hosts


def test_validate_rejects_tpu_replicas_contradicting_topology():
    with pytest.raises(ValidationError, match="contradicts host count"):
        validate_tfjob(mk_job((ReplicaType.TPU, 4)))  # v5e-8 derives 2 hosts


def test_validate_rejects_indivisible_chips_per_host():
    job = mk_job((ReplicaType.TPU, 1))
    job.spec.tf_replica_specs[0].tpu = TPUSpec(accelerator_type="v5e-8", chips_per_host=3)
    with pytest.raises(ValidationError, match="not divisible"):
        validate_tfjob(job)


def test_validate_rejects_overlong_name():
    job = mk_job((ReplicaType.WORKER, 1))
    job.metadata.name = "x" * 100
    with pytest.raises(ValidationError, match="63-char"):
        validate_tfjob(job)


@pytest.mark.parametrize(
    "mutate,msg",
    [
        (lambda j: setattr(j.metadata, "name", ""), "name"),
        (lambda j: j.spec.tf_replica_specs.clear(), "non-empty"),
        (lambda j: setattr(j.spec.tf_replica_specs[0], "replicas", -1), "replicas"),
        (lambda j: setattr(j.spec.tf_replica_specs[0], "template", None), "template"),
    ],
)
def test_validate_rejects(mutate, msg):
    job = mk_job((ReplicaType.WORKER, 2))
    mutate(job)
    with pytest.raises(ValidationError, match=msg):
        validate_tfjob(job)


def test_validate_rejects_local_mixed_and_multi():
    with pytest.raises(ValidationError):
        validate_tfjob(mk_job((ReplicaType.LOCAL, 1), (ReplicaType.WORKER, 1)))
    with pytest.raises(ValidationError):
        validate_tfjob(mk_job((ReplicaType.LOCAL, 2)))


def test_validate_rejects_gpu_on_tpu_replica():
    job = mk_job((ReplicaType.TPU, 2))
    job.spec.tf_replica_specs[0].template.spec.containers[0].resources = (
        ResourceRequirements(limits={"nvidia.com/gpu": "1"})
    )
    with pytest.raises(ValidationError, match="nvidia.com/gpu"):
        validate_tfjob(job)


def test_validate_rejects_duplicate_types():
    with pytest.raises(ValidationError, match="duplicate"):
        validate_tfjob(mk_job((ReplicaType.WORKER, 1), (ReplicaType.WORKER, 2)))


# ---- TPU topology ----

@pytest.mark.parametrize(
    "accel,hosts,chips",
    [("v5e-8", 2, 8), ("v5e-16", 4, 16), ("v5p-32", 8, 32), ("v4-8", 2, 8)],
)
def test_tpu_slice_derivation(accel, hosts, chips):
    spec = TPUSpec(accelerator_type=accel, chips_per_host=4)
    assert tpu_slice_hosts(spec) == hosts
    assert tpu_slice_chips(spec) == chips


def test_tpu_slice_explicit_hosts_wins():
    # Single-host v5e-8: 1 host x 8 chips/host.
    spec = TPUSpec(accelerator_type="v5e-8", num_hosts=1, chips_per_host=8)
    assert tpu_slice_hosts(spec) == 1
    assert tpu_slice_chips(spec) == 8


def test_validate_rejects_inconsistent_tpu_topology():
    job = mk_job((ReplicaType.TPU, 1))
    # v5e-8 has 8 chips but 1 host x 4 chips/host = 4: contradiction.
    job.spec.tf_replica_specs[0].tpu = TPUSpec(
        accelerator_type="v5e-8", num_hosts=1, chips_per_host=4
    )
    with pytest.raises(ValidationError, match="inconsistent TPU topology"):
        validate_tfjob(job)


def test_validate_chief_index_in_range():
    from kubeflow_controller_tpu.api import ChiefSpec, TerminationPolicySpec

    job = mk_job((ReplicaType.PS, 1), (ReplicaType.WORKER, 2))
    job.spec.tf_replica_specs[1].termination_policy = TerminationPolicySpec(
        chief=ChiefSpec(tf_replica_name="Worker", tf_replica_index=10)
    )
    with pytest.raises(ValidationError, match="out of range"):
        validate_tfjob(job)
    job.spec.tf_replica_specs[1].termination_policy.chief.tf_replica_index = 1
    validate_tfjob(job)


def test_validate_generate_name_prefix():
    job = mk_job((ReplicaType.WORKER, 1))
    job.metadata.name = ""
    job.metadata.generate_name = "My_Job-"
    with pytest.raises(ValidationError, match="DNS-1123 prefix"):
        validate_tfjob(job)
    job.metadata.generate_name = "my-job-"
    validate_tfjob(job)


def test_serde_enum_dict_keys_round_trip():
    from kubeflow_controller_tpu.api import TFReplicaState, TFReplicaStatus

    st = TFReplicaStatus(tf_replicas_states={TFReplicaState.RUNNING: 3, TFReplicaState.FAILED: 1})
    d = serde.to_dict(st)
    assert d["tfReplicasStates"] == {"Running": 3, "Failed": 1}
    back = serde.from_dict(TFReplicaStatus, d)
    assert back.tf_replicas_states[TFReplicaState.RUNNING] == 3
    assert all(isinstance(k, TFReplicaState) for k in back.tf_replicas_states)


# ---- helpers ----

def test_replica_spec_for_any_order():
    job = mk_job((ReplicaType.WORKER, 4), (ReplicaType.PS, 2))
    assert replica_spec_for(job, ReplicaType.PS).replicas == 2
    assert replica_spec_for(job, ReplicaType.WORKER).replicas == 4
    assert replica_spec_for(job, ReplicaType.TPU) is None


def test_pod_status_helpers():
    pods = [Pod() for _ in range(4)]
    pods[0].status.phase = PHASE_SUCCEEDED
    pods[1].status.phase = PHASE_FAILED
    pods[2].status.phase = PHASE_RUNNING
    pods[3].metadata.deletion_timestamp = 123.0
    assert get_status(pods) == (1, 1)
    active = filter_active_pods(pods)
    assert len(active) == 1 and active[0] is pods[2]


def test_keys_and_names():
    m = ObjectMeta(name="j", namespace="ns")
    assert key_of(m) == "ns/j"
    assert split_key("ns/j") == ("ns", "j")
    assert split_key("j") == ("", "j")
    n = generate_name("base-")
    assert n.startswith("base-") and len(n) == len("base-") + 5
    assert len(generate_runtime_id()) == 5
    assert len(generate_name("x" * 100)) == 63


# ---- Multislice (DCN) topology ----

def test_multislice_total_hosts():
    from kubeflow_controller_tpu.api.tfjob import tpu_total_hosts

    spec = TPUSpec(accelerator_type="v5e-8", chips_per_host=4, num_slices=2)
    assert tpu_slice_hosts(spec) == 2
    assert tpu_total_hosts(spec) == 4


def test_multislice_replicas_must_agree():
    job = mk_job((ReplicaType.TPU, 4))
    job.spec.tf_replica_specs[0].tpu = TPUSpec(
        accelerator_type="v5e-8", chips_per_host=4, num_slices=2)
    validate_tfjob(job)  # 2 slices x 2 hosts = 4 == replicas
    job.spec.tf_replica_specs[0].replicas = 2  # per-slice count: wrong
    with pytest.raises(ValidationError):
        validate_tfjob(job)


def test_multislice_num_slices_positive():
    job = mk_job((ReplicaType.TPU, 1))
    job.spec.tf_replica_specs[0].tpu = TPUSpec(
        accelerator_type="v5e-8", chips_per_host=4, num_slices=0)
    with pytest.raises(ValidationError):
        validate_tfjob(job)
