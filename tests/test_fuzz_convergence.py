"""Seeded randomized convergence fuzz for the reconcile loop.

The scripted stress test (test_controller.py) exercises known interleavings;
this one drives ARBITRARY seeded interleavings of the chaos the controller
claims to absorb — pod/service phase flips and deletions, job rescales and
deletions, whole-slice failures, orphan adoption bait, new jobs mid-chaos —
then stops injecting and asserts the system CONVERGES:

- every surviving job reaches a terminal phase (Succeeded/Failed);
- deleted jobs are actually gone, along with their children (cascade GC
  through the finalizer path — no orphaned pods/services);
- terminal jobs hold no services (terminal recycle);
- no leaked controller expectations (all fulfilled or expired);
- no leaked slice bindings (every healthy slice is free again).

The semantics under test are the reference's level-triggered reconcile
contract (ref: pkg/controller/controller.go:264-357) hardened with the
delete handlers it stubbed (controller.go:522-524).
"""

import random
import time

import pytest

from kubeflow_controller_tpu.api.core import (
    PHASE_FAILED,
    PHASE_SUCCEEDED,
)
from kubeflow_controller_tpu.api.tfjob import ReplicaType, TFJobPhase
from kubeflow_controller_tpu.cluster import (
    Cluster,
    FakeKubelet,
    PhasePolicy,
    TPUInventory,
    TPUSlice,
)
from kubeflow_controller_tpu.controller import Controller

from test_controller import mk_job, wait_for


@pytest.mark.parametrize("transport,seed", [
    ("memory", 0), ("memory", 1), ("memory", 2),
    # The same chaos through the REAL transport: controller and chaos both
    # speak HTTP to the API server (serialization, watch streams, optimistic
    # concurrency over the wire), plus forced watch drops mid-chaos so the
    # reflector's gap re-list path runs under concurrent writes.  Marked
    # slow: a real HTTP server + 150s convergence deadlines don't belong in
    # the quick job's budget; the full-coverage CI job runs them.
    pytest.param("rest", 0, marks=pytest.mark.slow),
    pytest.param("rest", 1, marks=pytest.mark.slow),
])
def test_randomized_chaos_converges(transport, seed):
    rng = random.Random(seed)
    inventory = TPUInventory(
        [TPUSlice(f"fz-slice-{i}", "v5e-8", num_hosts=2) for i in range(4)])
    srv = None
    if transport == "rest":
        from kubeflow_controller_tpu.cluster.apiserver import FakeAPIServer
        from kubeflow_controller_tpu.cluster.rest import Kubeconfig, RestCluster
        from kubeflow_controller_tpu.cluster.store import ObjectStore

        store = ObjectStore()
        substrate = Cluster(store=store)
        # The kubelet is a node agent against the shared store; the
        # controller AND the chaos loop go over HTTP.
        kubelet = FakeKubelet(substrate, policy=PhasePolicy(run_s=0.2),
                              inventory=inventory)
        srv = FakeAPIServer(store)
        cluster = RestCluster(Kubeconfig(server=srv.start()))
    else:
        cluster = Cluster()
        kubelet = FakeKubelet(cluster, policy=PhasePolicy(run_s=0.2),
                              inventory=inventory)
    ctrl = Controller(cluster, inventory=inventory, resync_period_s=0.3)
    kubelet.start()
    ctrl.run(threadiness=2)
    try:
        jobs = {}
        deleted = set()

        def mk(name):
            kind = rng.choice(["local", "dist", "tpu"])
            if kind == "local":
                job = mk_job(name, (ReplicaType.LOCAL, 1))
            elif kind == "dist":
                job = mk_job(name, (ReplicaType.PS, rng.randint(1, 2)),
                             (ReplicaType.WORKER, rng.randint(1, 3)))
            else:
                job = mk_job(name, (ReplicaType.TPU, 2))
            cluster.tfjobs.create(job)
            jobs[name] = kind

        for i in range(4):
            mk(f"fuzz-{seed}-{i}")

        for step in range(60):
            roll = rng.random()
            pods = cluster.pods.list("default")
            live = [n for n in jobs if n not in deleted]
            try:
                if roll < 0.25 and pods:
                    p = rng.choice(pods)
                    kubelet.set_phase("default", p.metadata.name,
                                      rng.choice([PHASE_FAILED,
                                                  PHASE_SUCCEEDED]))
                elif roll < 0.40 and pods:
                    p = rng.choice(pods)
                    cluster.pods.delete("default", p.metadata.name)
                elif roll < 0.50:
                    svcs = cluster.services.list("default")
                    if svcs:
                        cluster.services.delete(
                            "default", rng.choice(svcs).metadata.name)
                elif roll < 0.60:
                    cands = [n for n in live if jobs[n] == "dist"]
                    if cands:
                        j = cluster.tfjobs.get("default", rng.choice(cands))
                        for spec in j.spec.tf_replica_specs:
                            if spec.tf_replica_type == ReplicaType.WORKER:
                                spec.replicas = rng.randint(1, 4)
                        cluster.tfjobs.update(j)
                elif roll < 0.64:
                    kubelet.fail_slice(rng.choice(list(inventory.slices)))
                elif roll < 0.68:
                    if srv is not None:
                        # Force a watch gap: every informer stream closes
                        # and must reconnect + re-list mid-chaos.
                        srv.drop_watches()
                    else:
                        kubelet.fail_slice(
                            rng.choice(list(inventory.slices)))
                elif roll < 0.78 and live:
                    n = rng.choice(live)
                    cluster.tfjobs.delete("default", n)
                    deleted.add(n)
                elif roll < 0.88 and live:
                    # Orphan adoption bait: a pod wearing a live job's
                    # labels with no owner ref — the ref manager must
                    # either adopt it cleanly or leave it alone, never
                    # wedge the sync loop.
                    src = [p for p in pods
                           if p.metadata.owner_references] or None
                    if src:
                        import copy

                        orphan = copy.deepcopy(rng.choice(src))
                        orphan.metadata.name = f"orphan-{seed}-{step}"
                        orphan.metadata.owner_references = []
                        orphan.metadata.resource_version = ""
                        orphan.metadata.uid = ""
                        cluster.pods.create(orphan)
                else:
                    mk(f"fuzz-{seed}-n{step}")
            except Exception:
                # Chaos racing the controller (NotFound/Conflict on objects
                # the reconciler just replaced) is part of the test, not a
                # failure; the INVARIANTS below are what must hold.
                pass
            time.sleep(rng.uniform(0, 0.04))

        # --- quiescence: no more chaos; everything must converge ---
        # Restore capacity first: chaos may have quarantined EVERY slice
        # (seed + host-timing dependent — the branch taken per roll depends
        # on what pods exist at that instant), and a TPU job created after
        # that can never bind — correctly Pending forever, like a real
        # cluster out of capacity.  Healing the quarantine mirrors capacity
        # returning, and convergence from there additionally exercises the
        # level-triggered retry path (Pending gangs must bind without any
        # new event).
        for s in inventory.slices.values():
            s.healthy = True
        survivors = [n for n in jobs if n not in deleted]

        def all_terminal():
            for n in survivors:
                try:
                    j = cluster.tfjobs.get("default", n)
                except Exception:
                    return False
                if j.status.phase not in (TFJobPhase.SUCCEEDED,
                                          TFJobPhase.FAILED):
                    return False
            return True

        # Generous deadline: chaos interleavings are wall-clock
        # dependent and a loaded CI host starves the controller's
        # threads long before the engine is actually wedged.
        try:
            wait_for(all_terminal, timeout=150.0)
        except AssertionError:
            # Diagnostics: WHICH job is non-terminal and why — a timeout
            # here is rare and load-dependent, so the failure must carry
            # the state needed to debug it post-hoc.
            state = []
            for n in survivors:
                try:
                    j = cluster.tfjobs.get("default", n)
                    state.append(
                        f"{n}: phase={j.status.phase} "
                        f"reason={j.status.reason!r} "
                        f"replicas={[(str(rs.tf_replica_type), rs.replicas) for rs in j.spec.tf_replica_specs]}")
                except Exception as e:
                    state.append(f"{n}: GET failed: {e!r}")
            slices = {k: (s.healthy, s.bound_gang)
                      for k, s in inventory.slices.items()}
            pods = [(p.metadata.name, p.status.phase)
                    for p in cluster.pods.list("default")]
            raise AssertionError(
                "convergence timeout; non-terminal state:\n  "
                + "\n  ".join(state)
                + f"\nslices(healthy,bound)={slices}\npods={pods}")

        def deleted_gone():
            for n in deleted:
                try:
                    cluster.tfjobs.get("default", n)
                    return False
                except Exception:
                    continue
            return True

        wait_for(deleted_gone, timeout=60.0)

        # Cascade GC: no child may reference a deleted job.
        def no_orphaned_children():
            live_uids = set()
            for n in survivors:
                live_uids.add(cluster.tfjobs.get("default", n).metadata.uid)
            for obj in (cluster.pods.list("default")
                        + cluster.services.list("default")):
                for ref in obj.metadata.owner_references:
                    if ref.uid and ref.uid not in live_uids:
                        return False
            return True

        wait_for(no_orphaned_children, timeout=60.0)
        # Terminal recycle: no services survive once every job is terminal.
        wait_for(lambda: cluster.services.list("default") == [], timeout=60.0)

        # No leaked slice bindings: healthy slices are all free again
        # (quarantined slices stay unhealthy AND unbound).
        def slices_free():
            return all(not s.bound_gang for s in inventory.slices.values())

        wait_for(slices_free, timeout=60.0)

        # No leaked expectations: whatever remains in the cache must be
        # fulfilled or expired — an unfulfilled live expectation would mean
        # a job sync is wedged waiting for a create/delete that never comes.
        def expectations_clear():
            return all(
                ctrl.expectations.satisfied_expectations(k)
                for k in list(ctrl.expectations._store))

        wait_for(expectations_clear, timeout=60.0)
    finally:
        ctrl.stop()
        kubelet.stop()
        if srv is not None:
            srv.stop()
