"""Resumable watch plane: the server watch cache (store), RV-resumed REST
watch reconnects + bookmarks (apiserver/rest), the informer's 410-only
re-list fallback, and the O(1) deque workqueue + spread resync satellites.

The semantics under test are client-go reflector / kube-apiserver watch
cache parity: a client that lost its stream resumes from its last-seen
resourceVersion and the server replays exactly the missed events — no
loss, no duplicates, full re-list only on a genuine 410-too-old.
"""

import threading
import time

import pytest

from kubeflow_controller_tpu.api.core import Container, Pod, PodTemplateSpec
from kubeflow_controller_tpu.api.meta import ObjectMeta
from kubeflow_controller_tpu.api.tfjob import ReplicaType, TFJob, TFReplicaSpec
from kubeflow_controller_tpu.cluster import Cluster
from kubeflow_controller_tpu.cluster.apiserver import FakeAPIServer
from kubeflow_controller_tpu.cluster.rest import Kubeconfig, RestCluster
from kubeflow_controller_tpu.cluster.store import (
    ADDED,
    BOOKMARK,
    DELETED,
    MODIFIED,
    ObjectStore,
    TooOldResourceVersion,
)
from kubeflow_controller_tpu.controller.informer import SharedInformer
from kubeflow_controller_tpu.controller.workqueue import RateLimitingQueue
from kubeflow_controller_tpu.obs.metrics import REGISTRY

def mk_job(name, *types_and_replicas):
    job = TFJob(metadata=ObjectMeta(name=name, namespace="default"))
    for typ, n in types_and_replicas:
        t = PodTemplateSpec()
        t.spec.containers.append(Container(name="tensorflow", image="img"))
        t.spec.restart_policy = "OnFailure"
        job.spec.tf_replica_specs.append(
            TFReplicaSpec(replicas=n, tf_replica_type=typ, template=t))
    return job


def wait_for(fn, timeout=15.0, interval=0.02):
    deadline = time.time() + timeout
    while time.time() < deadline:
        v = fn()
        if v:
            return v
        time.sleep(interval)
    raise AssertionError("condition not met within timeout")


def mk_pod(name, ns="default", labels=None):
    pod = Pod(metadata=ObjectMeta(name=name, namespace=ns))
    pod.metadata.labels = labels or {}
    return pod


def counter_value(name: str) -> float:
    return REGISTRY.counter(name, "").value


def drain(w, timeout=0.2):
    out = []
    while True:
        ev = w.next(timeout=timeout)
        if ev is None:
            return out
        out.append(ev)


# ---------------------------------------------------------------------------
# Store-level: the watch cache
# ---------------------------------------------------------------------------


class TestStoreWatchCache:
    def test_replay_exactly_after_since_rv(self):
        s = ObjectStore()
        created = [s.create("pods", mk_pod(f"p{i}")) for i in range(5)]
        since = created[1].metadata.resource_version
        w = s.watch("pods", since_rv=since)
        try:
            evs = drain(w)
            assert [e.object.metadata.name for e in evs] == ["p2", "p3", "p4"]
            assert all(e.type == ADDED for e in evs)
            # The stream is live after the replay.
            s.create("pods", mk_pod("p5"))
            ev = w.next(timeout=2.0)
            assert ev is not None and ev.object.metadata.name == "p5"
        finally:
            w.stop()

    def test_replay_includes_modifies_and_deletes(self):
        s = ObjectStore()
        obj = s.create("pods", mk_pod("p"))
        since = obj.metadata.resource_version
        obj.status.phase = "Running"
        s.update("pods", obj)
        s.delete("pods", "default", "p")
        w = s.watch("pods", since_rv=since)
        try:
            evs = drain(w)
            assert [e.type for e in evs] == [MODIFIED, DELETED]
            # The DELETED event got its own RV (strictly after the update's),
            # so a client resuming from the MODIFIED would still see it.
            rvs = [int(e.object.metadata.resource_version) for e in evs]
            assert rvs == sorted(rvs) and len(set(rvs)) == len(rvs)
        finally:
            w.stop()

    def test_replay_no_loss_no_dup_interleaved_with_live_writes(self):
        """watch(since_rv=...) registered while a writer hammers the store:
        every event with rv > since arrives exactly once, in order."""
        s = ObjectStore()
        for i in range(10):
            s.create("pods", mk_pod(f"pre{i}"))
        _, since = s.list_with_rv("pods")

        stop = threading.Event()
        written = []

        def writer():
            i = 0
            while not stop.is_set():
                written.append(s.create(
                    "pods", mk_pod(f"live{i}")).metadata.resource_version)
                i += 1
                time.sleep(0.001)

        t = threading.Thread(target=writer, daemon=True)
        t.start()
        time.sleep(0.02)  # some writes land before the watch registers
        w = s.watch("pods", since_rv=since)
        time.sleep(0.05)
        stop.set()
        t.join(timeout=5.0)
        try:
            evs = drain(w)
            got = [int(e.object.metadata.resource_version) for e in evs]
            assert got == sorted(got), "events out of write order"
            assert len(got) == len(set(got)), "duplicate events"
            # Exactly the writes after `since`, none lost.
            assert got == sorted(int(rv) for rv in written)
        finally:
            w.stop()

    def test_replay_respects_namespace_filter(self):
        s = ObjectStore()
        first = s.create("pods", mk_pod("a", ns="keep"))
        s.create("pods", mk_pod("b", ns="other"))
        s.create("pods", mk_pod("c", ns="keep"))
        w = s.watch("pods", namespace="keep",
                    since_rv=first.metadata.resource_version)
        try:
            assert [e.object.metadata.name for e in drain(w)] == ["c"]
        finally:
            w.stop()

    def test_ring_buffer_eviction_bounds_and_410(self):
        s = ObjectStore(watch_cache_size=4)
        created = [s.create("pods", mk_pod(f"p{i}")) for i in range(10)]
        assert len(s._watch_cache["pods"]) == 4
        # Depth gauge tracks the bounded buffer.
        assert REGISTRY.gauge("kctpu_watch_cache_depth", "",
                              ("kind",)).labels("pods").value == 4
        # A resume point inside the retained window works...
        w = s.watch("pods", since_rv=created[6].metadata.resource_version)
        try:
            assert [e.object.metadata.name for e in drain(w)] == [
                "p7", "p8", "p9"]
        finally:
            w.stop()
        # ...one that predates it is 410-too-old.
        with pytest.raises(TooOldResourceVersion):
            s.watch("pods", since_rv=created[0].metadata.resource_version)

    def test_list_with_rv_is_a_resume_point(self):
        s = ObjectStore()
        s.create("pods", mk_pod("before"))
        items, rv = s.list_with_rv("pods")
        assert [p.metadata.name for p in items] == ["before"]
        s.create("pods", mk_pod("after"))
        w = s.watch("pods", since_rv=rv)
        try:
            evs = drain(w)
            assert [e.object.metadata.name for e in evs] == ["after"]
        finally:
            w.stop()

    def test_initial_bookmark_carries_collection_rv(self):
        s = ObjectStore()
        s.create("pods", mk_pod("p"))
        _, rv = s.list_with_rv("pods")
        w = s.watch("pods", bookmark=True)
        try:
            ev = w.next(timeout=1.0)
            assert ev is not None and ev.type == BOOKMARK
            assert ev.object.metadata.resource_version == rv
        finally:
            w.stop()


# ---------------------------------------------------------------------------
# REST transport: resume, bookmarks, 410 fallback
# ---------------------------------------------------------------------------


@pytest.fixture
def server():
    srv = FakeAPIServer(bookmark_interval_s=0.2)
    url = srv.start()
    yield srv, url
    srv.stop()


@pytest.fixture
def rest(server):
    _, url = server
    cl = RestCluster(Kubeconfig(server=url))
    yield cl
    cl.close()


class TestRestWatchResume:
    def test_drop_resumes_without_gap(self, server, rest):
        """A forced stream drop with events written into the gap: the
        reconnect resumes from the last-seen RV, the gap events replay,
        and `gaps` never bumps (so an informer would not re-list)."""
        srv, _ = server
        resumes0 = counter_value("kctpu_watch_resumes_total")
        w = rest.tfjobs.watch("default")
        try:
            rest.tfjobs.create(mk_job("j1", (ReplicaType.LOCAL, 1)))
            ev = w.next(timeout=5.0)
            assert ev is not None and ev.object.metadata.name == "j1"
            srv.drop_watches()
            # Written while the stream is (about to be) torn down — only
            # the server watch cache can deliver it to this client.
            srv.store.create("tfjobs", mk_job("j2", (ReplicaType.LOCAL, 1)))
            ev = w.next(timeout=10.0)
            assert ev is not None and ev.object.metadata.name == "j2"
            assert w.gaps == 0
            wait_for(lambda: counter_value("kctpu_watch_resumes_total")
                     > resumes0)
        finally:
            w.stop()

    def test_bookmarks_advance_idle_stream_rv(self, server, rest):
        """A namespace-filtered stream sees no events while other
        namespaces churn; periodic bookmarks must keep its resume point
        fresh anyway — then a drop resumes instead of gapping."""
        srv, _ = server
        w = rest.pods.watch("quiet")
        try:
            wait_for(lambda: w.resource_version is not None)
            for i in range(5):
                srv.store.create("pods", mk_pod(f"noise{i}", ns="busy"))
            _, rv_now = srv.store.list_with_rv("pods")
            # The stream received none of those events, but its bookmark RV
            # catches up past them.
            wait_for(lambda: w.resource_version is not None
                     and int(w.resource_version) >= int(rv_now), timeout=5.0)
            srv.drop_watches()
            srv.store.create("pods", mk_pod("mine", ns="quiet"))
            ev = w.next(timeout=10.0)
            assert ev is not None and ev.object.metadata.name == "mine"
            assert w.gaps == 0
        finally:
            w.stop()

    def test_too_old_rv_falls_back_to_gap_and_informer_relists(self):
        """Server restart with a tiny watch cache overflowed during the
        outage: the resume 410s, the watcher reconnects live with a gap,
        and the informer recovers by full re-list — the strictly-fallback
        path, observable on kctpu_watch_relists_total."""
        import socket

        with socket.socket() as sck:
            sck.bind(("127.0.0.1", 0))
            port = sck.getsockname()[1]

        store = ObjectStore(watch_cache_size=2)
        srv = FakeAPIServer(store, port=port)
        url = srv.start()
        cl = RestCluster(Kubeconfig(server=url))
        informer = SharedInformer(cl.tfjobs, resync_period_s=0, name="tfjobs")
        relists0 = counter_value("kctpu_watch_relists_total")
        informer.start()
        try:
            cl.tfjobs.create(mk_job("before", (ReplicaType.LOCAL, 1)))
            wait_for(lambda: informer.get("default", "before") is not None)
            srv.stop()
            # stop() closes the listener but in-flight stream handlers
            # survive on their open sockets: sever them too, and wait for
            # the client to actually disconnect — otherwise the zombie
            # stream keeps the client's RV warm and it resumes legitimately.
            srv.drop_watches()
            wait_for(lambda: not informer._watcher._connected.is_set(),
                     timeout=10.0)
            # Enough writes to evict the client's resume point.
            for i in range(6):
                store.create("tfjobs", mk_job(f"during{i}",
                                              (ReplicaType.LOCAL, 1)))
            store.delete("tfjobs", "default", "before")
            srv2 = FakeAPIServer(store, port=port)
            srv2.start()
            try:
                wait_for(lambda: informer.get("default", "during5") is not None,
                         timeout=20.0)
                wait_for(lambda: informer.get("default", "before") is None)
                assert counter_value("kctpu_watch_relists_total") > relists0
            finally:
                srv2.stop()
        finally:
            informer.stop()
            cl.close()

    def test_rest_list_with_rv_seeds_watch(self, server, rest):
        srv, _ = server
        rest.tfjobs.create(mk_job("early", (ReplicaType.LOCAL, 1)))
        items, rv = rest.tfjobs.list_with_rv("default")
        assert [j.metadata.name for j in items] == ["early"]
        assert rv and int(rv) > 0
        srv.store.create("tfjobs", mk_job("later", (ReplicaType.LOCAL, 1)))
        w = rest.tfjobs.watch("default", resource_version=rv)
        try:
            ev = w.next(timeout=5.0)
            assert ev is not None and ev.object.metadata.name == "later"
        finally:
            w.stop()

    def test_no_resume_transport_gaps_on_drop(self, server):
        """watch_resume=False restores the baseline: every reconnect is a
        gap (what bench.py --churn --no-resume measures against)."""
        srv, url = server
        cl = RestCluster(Kubeconfig(server=url), watch_resume=False)
        w = cl.tfjobs.watch("default")
        try:
            cl.tfjobs.create(mk_job("j", (ReplicaType.LOCAL, 1)))
            assert w.next(timeout=5.0) is not None
            srv.drop_watches()
            wait_for(lambda: w.gaps >= 1, timeout=10.0)
        finally:
            w.stop()
            cl.close()


# ---------------------------------------------------------------------------
# Workqueue satellites: deque hot path + condition-driven delay loop
# ---------------------------------------------------------------------------


class TestWorkqueueDeque:
    def test_fifo_and_dedup_preserved(self):
        q = RateLimitingQueue(name="t-deque-fifo")
        for item in ("a", "b", "c", "a", "b"):
            q.add(item)
        assert [q.get(timeout=1.0) for _ in range(3)] == ["a", "b", "c"]
        assert len(q) == 0
        q.shut_down()

    def test_readd_while_processing_requeues_once(self):
        q = RateLimitingQueue(name="t-deque-requeue")
        q.add("k")
        assert q.get(timeout=1.0) == "k"
        q.add("k")  # dirty while processing
        q.add("k")  # collapsed
        assert len(q) == 0
        q.done("k")
        assert q.get(timeout=1.0) == "k"
        q.done("k")
        assert len(q) == 0
        q.shut_down()

    def test_concurrent_adds_no_loss_no_dup(self):
        q = RateLimitingQueue(name="t-deque-conc")
        items = [f"item-{i}" for i in range(50)]
        barrier = threading.Barrier(4)

        def hammer():
            barrier.wait()
            for it in items:
                q.add(it)

        threads = [threading.Thread(target=hammer) for _ in range(4)]
        for t in threads:
            t.start()
        got = []
        deadline = time.time() + 10.0
        while len(got) < len(items) and time.time() < deadline:
            it = q.get(timeout=0.2)
            if it is not None:
                got.append(it)
                q.done(it)
        for t in threads:
            t.join(timeout=5.0)
        # Items re-added while processing may legally requeue: drain those.
        while True:
            it = q.get(timeout=0.2)
            if it is None:
                break
            got.append(it)
            q.done(it)
        assert set(got) == set(items)
        # Dedup: far fewer gets than the 200 raw adds.
        assert len(got) <= 2 * len(items)
        q.shut_down()

    def test_add_after_fires_at_deadline_not_poll_tick(self):
        q = RateLimitingQueue(name="t-deque-delay")
        t0 = time.monotonic()
        q.add_after("x", 0.15)
        assert q.get(timeout=2.0) == "x"
        elapsed = time.monotonic() - t0
        assert 0.14 <= elapsed < 0.5, elapsed
        q.shut_down()

    def test_earlier_add_after_preempts_pending_deadline(self):
        """The delay thread sleeping toward a far deadline must wake for a
        nearer one (the condition-notify the 50 ms poll used to paper
        over)."""
        q = RateLimitingQueue(name="t-deque-preempt")
        q.add_after("late", 5.0)
        q.add_after("early", 0.05)
        t0 = time.monotonic()
        assert q.get(timeout=2.0) == "early"
        assert time.monotonic() - t0 < 1.0
        q.shut_down()


# ---------------------------------------------------------------------------
# Informer resync spread satellite
# ---------------------------------------------------------------------------


def test_resync_dispatches_spread_across_window():
    """One resync cycle's update dispatches are spaced across the window,
    not fired in one synchronous burst."""
    c = Cluster()
    for i in range(4):
        c.pods.create(mk_pod(f"p{i}"))
    inf = SharedInformer(c.pods, resync_period_s=0.4, name="pods-spread")
    stamps = []

    def on_update(old, new):
        if old is new:  # resync signature: identical object
            stamps.append(time.monotonic())

    inf.add_event_handler(on_update=on_update)
    inf.start()
    try:
        wait_for(lambda: len(stamps) >= 4, timeout=10.0)
        first_cycle = stamps[:4]
        # gap = 0.4 * 0.5 / 4 = 50 ms between dispatches; the burst the
        # spread replaces would land all four within ~1 ms.
        assert first_cycle[-1] - first_cycle[0] >= 0.1
    finally:
        inf.stop()


def test_informer_skips_bookmark_events():
    """An in-memory watcher carrying BOOKMARK events must not crash or
    pollute the informer cache."""
    c = Cluster()

    class BookmarkingClient:
        kind = "pods"

        def list(self, *a, **kw):
            return c.pods.list(*a, **kw)

        def watch(self, *a, **kw):
            return c.store.watch("pods", bookmark=True)

    inf = SharedInformer(BookmarkingClient(), resync_period_s=0,
                         name="pods-bm")
    inf.start()
    try:
        c.pods.create(mk_pod("real"))
        wait_for(lambda: inf.get("default", "real") is not None)
        assert len(inf.list()) == 1
    finally:
        inf.stop()
