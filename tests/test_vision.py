"""Vision models: shapes, BN state threading, and learnability (tiny)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_controller_tpu.models import vision as v
from kubeflow_controller_tpu.workloads import data as d


class TestShapes:
    @pytest.mark.slow
    def test_cnn_forward(self):
        m = v.FlaxMNISTCNN()
        var = v.vision_init(m, jax.random.PRNGKey(0), (28, 28, 1))
        x = jnp.zeros((4, 28, 28, 1))
        assert m.apply(var, x).shape == (4, 10)
        assert "batch_stats" not in var

    @pytest.mark.slow
    def test_resnet18_forward_and_bn_state(self):
        m = v.resnet18(width=8)
        var = v.vision_init(m, jax.random.PRNGKey(0), (32, 32, 3))
        assert "batch_stats" in var
        x = jnp.zeros((2, 32, 32, 3))
        loss, mut = v.vision_loss(m, var, x, jnp.zeros((2,), jnp.int32))
        assert loss.shape == ()
        assert "batch_stats" in mut  # BN stats update in train mode

    @pytest.mark.slow
    def test_resnet50_forward(self):
        m = v.resnet50(width=8)
        var = v.vision_init(m, jax.random.PRNGKey(0), (32, 32, 3))
        x = jnp.zeros((2, 32, 32, 3))
        logits, _ = m.apply(var, x, mutable=["batch_stats"])
        assert logits.shape == (2, 10)


class TestSyntheticCIFAR:
    def test_shapes_and_determinism(self):
        x1, y1 = d.synthetic_cifar(3, 64)
        x2, y2 = d.synthetic_cifar(3, 64)
        assert x1.shape == (64, 32, 32, 3)
        np.testing.assert_array_equal(np.asarray(y1), np.asarray(y2))

    def test_cnn_learns_cifar_slice(self):
        """A few SGD steps on the separable synthetic set drop the loss."""
        import optax

        x, y = d.synthetic_cifar(0, 256)
        m = v.FlaxMNISTCNN(features=(8, 16), dense=32)
        var = v.vision_init(m, jax.random.PRNGKey(0), (32, 32, 3))
        opt = optax.sgd(0.05, momentum=0.9)
        state = opt.init(var["params"])

        @jax.jit
        def step(params, state):
            def lf(p):
                loss, _ = v.vision_loss(m, {"params": p}, x, y)
                return loss
            loss, g = jax.value_and_grad(lf)(params)
            upd, state2 = opt.update(g, state, params)
            return optax.apply_updates(params, upd), state2, loss

        params = var["params"]
        params, state, l0 = step(params, state)
        for _ in range(8):
            params, state, loss = step(params, state)
        assert float(loss) < float(l0)
