"""Scale-envelope hot-path refactors (ISSUE 14): incremental rollup
bit-identity, workqueue priority tiers, watch fan-out batching, the store's
owner-indexed cascade, metric series budgets, the event recorder's
per-object rings, and the bounded reservoir metrics.
"""

import time

from kubeflow_controller_tpu.api.core import (
    Container,
    PHASE_FAILED,
    PHASE_PENDING,
    PHASE_RUNNING,
    PHASE_SUCCEEDED,
    Pod,
    PodTemplateSpec,
)
from kubeflow_controller_tpu.api.labels import (
    LABEL_INDEX,
    LABEL_JOB_TYPE,
)
from kubeflow_controller_tpu.api.meta import ObjectMeta, OwnerReference
from kubeflow_controller_tpu.api.tfjob import (
    ReplicaType,
    TFJob,
    TFReplicaSpec,
)
from kubeflow_controller_tpu.cluster import Cluster
from kubeflow_controller_tpu.cluster.store import ObjectStore
from kubeflow_controller_tpu.controller.events import EventRecorder
from kubeflow_controller_tpu.controller.metrics import ReconcileMetrics, _Reservoir
from kubeflow_controller_tpu.controller.workqueue import RateLimitingQueue
from kubeflow_controller_tpu.obs import metrics as obs_metrics
from kubeflow_controller_tpu.updater import RollupCache, compute_status
from kubeflow_controller_tpu.utils import serde


def mk_job(name="j", workers=2, ps=1, rv="10"):
    job = TFJob(metadata=ObjectMeta(name=name, namespace="default",
                                    uid=f"uid-{name}",
                                    resource_version=rv))
    for typ, n in ((ReplicaType.PS, ps), (ReplicaType.WORKER, workers)):
        if n <= 0:
            continue
        t = PodTemplateSpec()
        t.spec.containers.append(Container(name="tensorflow", image="img"))
        t.spec.restart_policy = "OnFailure"
        job.spec.tf_replica_specs.append(
            TFReplicaSpec(replicas=n, tf_replica_type=typ, template=t))
    return job


def mk_pod(name, typ, index, phase, rv):
    pod = Pod(metadata=ObjectMeta(name=name, namespace="default",
                                  resource_version=rv))
    pod.metadata.labels = {LABEL_JOB_TYPE: typ.value,
                           LABEL_INDEX: str(index)}
    pod.status.phase = phase
    return pod


# ---------------------------------------------------------------------------
# Incremental rollup: bit-identical to full recompute over the corpus
# ---------------------------------------------------------------------------

class TestRollupCache:
    def corpus(self):
        """(job, pods_by_type) scenarios spanning the status shapes the
        existing updater tests exercise."""
        w, p = ReplicaType.WORKER, ReplicaType.PS
        out = []
        # All running.
        out.append((mk_job(rv="5"), {
            w: [mk_pod("w0", w, 0, PHASE_RUNNING, "1"),
                mk_pod("w1", w, 1, PHASE_RUNNING, "2")],
            p: [mk_pod("p0", p, 0, PHASE_RUNNING, "3")]}))
        # Mixed pending/running.
        out.append((mk_job(rv="6"), {
            w: [mk_pod("w0", w, 0, PHASE_PENDING, "4"),
                mk_pod("w1", w, 1, PHASE_RUNNING, "5")],
            p: [mk_pod("p0", p, 0, PHASE_PENDING, "6")]}))
        # Workers done, PS still up (job Succeeded + Recycling).
        out.append((mk_job(rv="7"), {
            w: [mk_pod("w0", w, 0, PHASE_SUCCEEDED, "7"),
                mk_pod("w1", w, 1, PHASE_SUCCEEDED, "8")],
            p: [mk_pod("p0", p, 0, PHASE_RUNNING, "9")]}))
        # A failure under replace-on-failure (Recovering).
        out.append((mk_job(rv="8"), {
            w: [mk_pod("w0", w, 0, PHASE_FAILED, "10"),
                mk_pod("w1", w, 1, PHASE_RUNNING, "11")],
            p: [mk_pod("p0", p, 0, PHASE_RUNNING, "12")]}))
        # Missing replicas (scheduled=False).
        out.append((mk_job(rv="9"), {
            w: [mk_pod("w0", w, 0, PHASE_RUNNING, "13")],
            p: []}))
        return out

    def test_bit_identical_to_full_recompute(self):
        cache = RollupCache()
        for i, (job, pods) in enumerate(self.corpus()):
            key = f"default/{job.metadata.name}-{i}"
            now = time.time()
            fp = RollupCache.fingerprint(job, pods)
            assert fp is not None
            assert cache.lookup(key, fp) is None  # cold
            computed = compute_status(job, pods, now=now)
            cache.store(key, fp, computed)
            hit = cache.lookup(key, fp)
            assert hit is not None
            fresh = compute_status(job, pods, now=now)
            assert serde.to_dict(hit) == serde.to_dict(fresh), (
                f"scenario {i}: cached rollup diverged from full recompute")

    def test_any_input_rv_change_misses(self):
        w = ReplicaType.WORKER
        job = mk_job(rv="5", ps=0)
        pods = {w: [mk_pod("w0", w, 0, PHASE_RUNNING, "1")]}
        cache = RollupCache()
        fp = RollupCache.fingerprint(job, pods)
        cache.store("k", fp, compute_status(job, pods))
        # Pod RV bump -> miss.
        pods2 = {w: [mk_pod("w0", w, 0, PHASE_RUNNING, "2")]}
        assert cache.lookup("k", RollupCache.fingerprint(job, pods2)) is None
        # Job RV bump -> miss.
        job2 = mk_job(rv="6", ps=0)
        assert cache.lookup("k", RollupCache.fingerprint(job2, pods)) is None
        # Pod set change -> miss.
        pods3 = {w: []}
        assert cache.lookup("k", RollupCache.fingerprint(job, pods3)) is None
        # Unchanged -> hit.
        assert cache.lookup("k", RollupCache.fingerprint(job, pods)) is not None

    def test_progress_bearing_pods_never_cache(self):
        from kubeflow_controller_tpu.api.core import PodProgress

        w = ReplicaType.WORKER
        job = mk_job(rv="5", ps=0)
        pod = mk_pod("w0", w, 0, PHASE_RUNNING, "1")
        pod.status.progress = PodProgress(step=5, timestamp=time.time())
        assert RollupCache.fingerprint(job, {w: [pod]}) is None

    def test_forget_and_bound(self):
        cache = RollupCache(max_jobs=4)
        w = ReplicaType.WORKER
        job = mk_job(rv="1", ps=0)
        pods = {w: []}
        fp = RollupCache.fingerprint(job, pods)
        for i in range(8):
            cache.store(f"k{i}", fp, compute_status(job, pods))
        assert len(cache) <= 4
        cache.forget("k7")
        assert cache.lookup("k7", fp) is None


# ---------------------------------------------------------------------------
# Workqueue priority tiers
# ---------------------------------------------------------------------------

class TestWorkqueueTiers:
    def test_fresh_beats_low(self):
        q = RateLimitingQueue(name="tiers-a")
        q.add("resync-1", low=True)
        q.add("resync-2", low=True)
        q.add("fresh-1")
        assert q.get(timeout=0.5) == "fresh-1"
        got = {q.get(timeout=0.5), q.get(timeout=0.5)}
        assert got == {"resync-1", "resync-2"}
        q.shut_down()

    def test_fresh_add_promotes_parked_low_item(self):
        q = RateLimitingQueue(name="tiers-b")
        q.add("job", low=True)
        q.add("decoy", low=True)
        q.add("job")  # fresh edge arrives for the parked item
        assert q.get(timeout=0.5) == "job"
        assert q.get(timeout=0.5) == "decoy"
        # The stale low entry must not resurface.
        assert q.get(timeout=0.1) is None
        q.shut_down()

    def test_low_tier_not_starved_forever(self):
        q = RateLimitingQueue(name="tiers-c")
        q.add("low-item", low=True)
        for i in range(16):
            q.add(f"fresh-{i}")
        seen = [q.get(timeout=0.5) for _ in range(10)]
        assert "low-item" in seen, (
            "anti-starvation pop never serviced the low tier under a "
            f"sustained fresh stream: {seen}")
        q.shut_down()

    def test_done_requeues_into_the_dirtying_tier(self):
        q = RateLimitingQueue(name="tiers-d")
        q.add("job")
        assert q.get(timeout=0.5) == "job"
        q.add("job", low=True)   # went dirty mid-processing via a resync
        q.add("fresh")
        q.done("job")            # requeue lands in the LOW tier
        assert q.get(timeout=0.5) == "fresh"
        assert q.get(timeout=0.5) == "job"
        q.shut_down()

    def test_drain_pending_includes_low_tier(self):
        q = RateLimitingQueue(name="tiers-e")
        q.add("a")
        q.add("b", low=True)
        drained = dict(q.drain_pending())
        assert set(drained) == {"a", "b"}
        assert len(q) == 0
        q.shut_down()


# ---------------------------------------------------------------------------
# Watch fan-out batching
# ---------------------------------------------------------------------------

class TestWatchBatch:
    def test_next_batch_drains_in_order(self):
        store = ObjectStore()
        w = store.watch("pods")
        for i in range(10):
            store.create("pods", Pod(metadata=ObjectMeta(  # kctpu: vet-ok(fencing-token)
                name=f"p{i}", namespace="default")))
        batch = w.next_batch(max_n=64, timeout=1.0)
        assert [ev.object.metadata.name for ev in batch] == [
            f"p{i}" for i in range(10)]
        assert w.next_batch(max_n=4, timeout=0.05) == []
        w.stop()

    def test_next_batch_resumes_through_overflow_drop(self):
        store = ObjectStore(watch_queue_size=4)
        w = store.watch("pods")
        for i in range(12):
            store.create("pods", Pod(metadata=ObjectMeta(  # kctpu: vet-ok(fencing-token)
                name=f"p{i:02d}", namespace="default")))
        got = []
        deadline = time.time() + 5.0
        while len(got) < 12 and time.time() < deadline:
            got.extend(ev.object.metadata.name
                       for ev in w.next_batch(max_n=64, timeout=0.2))
        assert got == [f"p{i:02d}" for i in range(12)]
        assert w.gaps == 0
        w.stop()

    def test_next_batch_ends_on_stop(self):
        store = ObjectStore()
        w = store.watch("pods")
        store.create("pods", Pod(metadata=ObjectMeta(  # kctpu: vet-ok(fencing-token)
            name="p", namespace="default")))
        w.stop()
        batch = w.next_batch(max_n=8, timeout=0.5)
        assert [ev.object.metadata.name for ev in batch] == ["p"]
        assert w.next_batch(max_n=8, timeout=0.05) == []


# ---------------------------------------------------------------------------
# Owner-indexed cascade delete
# ---------------------------------------------------------------------------

class TestOwnerIndexedCascade:
    def owned_pod(self, name, owner):
        pod = Pod(metadata=ObjectMeta(name=name, namespace="default"))
        pod.metadata.owner_references.append(OwnerReference(
            api_version="v1", kind="TFJob", name=owner.metadata.name,
            uid=owner.metadata.uid, controller=True))
        return pod

    def test_cascade_deletes_owned_children_via_index(self):
        c = Cluster()
        job = c.tfjobs.create(TFJob(metadata=ObjectMeta(
            name="own", namespace="default")))
        for i in range(3):
            c.pods.create(self.owned_pod(f"c{i}", job))
        c.pods.create(Pod(metadata=ObjectMeta(name="stray",
                                              namespace="default")))
        c.tfjobs.delete("default", "own")
        assert [p.metadata.name for p in c.pods.list("default")] == ["stray"]

    def test_reowned_child_survives_old_owners_cascade(self):
        """A posting gone stale through adoption-release must be filtered
        at cascade time, not acted on."""
        c = Cluster()
        a = c.tfjobs.create(TFJob(metadata=ObjectMeta(name="a",
                                                      namespace="default")))
        b = c.tfjobs.create(TFJob(metadata=ObjectMeta(name="b",
                                                      namespace="default")))
        c.pods.create(self.owned_pod("child", a))

        def reown(meta):
            meta.owner_references[0].name = "b"
            meta.owner_references[0].uid = b.metadata.uid

        c.pods.patch_meta("default", "child", reown)
        c.tfjobs.delete("default", "a")
        assert c.pods.get("default", "child") is not None
        c.tfjobs.delete("default", "b")
        assert [p.metadata.name for p in c.pods.list("default")] == []


# ---------------------------------------------------------------------------
# Metric series budget
# ---------------------------------------------------------------------------

class TestSeriesBudget:
    def test_gauge_budget_drops_and_counts(self):
        g = obs_metrics.Gauge("kctpu_hotpath_test_gauge", "h", ("job",),
                              max_series=8)
        for i in range(20):
            g.labels(f"job-{i}").set(float(i))
        assert len(g.collect().samples) == 8
        dropped = obs_metrics.REGISTRY.counter(
            "kctpu_metric_series_dropped_total", "", ("metric",))
        assert dropped.labels("kctpu_hotpath_test_gauge").value >= 12

    def test_remove_frees_budget(self):
        g = obs_metrics.Gauge("kctpu_hotpath_test_gauge2", "h", ("job",),
                              max_series=2)
        g.labels("a").set(1)
        g.labels("b").set(1)
        g.labels("c").set(1)  # dropped
        g.remove("a")
        g.labels("c").set(3)  # admitted now
        names = {s.labels["job"] for s in g.collect().samples}
        assert names == {"b", "c"}

    def test_job_gauge_series_removed_on_job_delete_at_scale(self):
        """The /metrics page stays bounded: per-job series die with their
        jobs (Gauge.remove fires from the controller delete handler)."""
        from kubeflow_controller_tpu.cluster import PhasePolicy, SimKubelet
        from kubeflow_controller_tpu.controller import Controller

        cluster = Cluster()
        kubelet = SimKubelet(cluster, policy=PhasePolicy(run_s=20.0,
                                                         heartbeat_s=0.02))
        ctrl = Controller(cluster, resync_period_s=2.0)
        kubelet.start()
        ctrl.run(threadiness=2)
        n = 30
        try:
            for i in range(n):
                cluster.tfjobs.create(mk_job(f"gjob-{i:02d}", rv=""))
            deadline = time.time() + 20.0
            g = obs_metrics.REGISTRY.gauge(
                "kctpu_job_step", "", ("namespace", "tfjob"))

            def series():
                return {s.labels["tfjob"] for s in g.collect().samples
                        if s.labels["tfjob"].startswith("gjob-")}
            while len(series()) < n and time.time() < deadline:
                time.sleep(0.05)
            assert len(series()) == n
            for i in range(n):
                cluster.tfjobs.delete("default", f"gjob-{i:02d}")
            deadline = time.time() + 20.0
            while series() and time.time() < deadline:
                time.sleep(0.05)
            assert series() == set(), "per-job gauge series leaked past delete"
        finally:
            ctrl.stop()
            kubelet.stop()


# ---------------------------------------------------------------------------
# EventRecorder per-object rings
# ---------------------------------------------------------------------------

class _Obj:
    kind = "TFJob"

    def __init__(self, name):
        self.metadata = ObjectMeta(name=name, namespace="default")


class TestEventRings:
    def test_per_object_ring_keeps_newest(self):
        r = EventRecorder(max_events=1000, per_object_max=4)
        for i in range(10):
            r.event(_Obj("noisy"), "Normal", "ReasonX", f"m{i}")
        msgs = [e.message for e in r.events_for("default", "noisy")]
        assert msgs == ["m6", "m7", "m8", "m9"]

    def test_storm_cannot_flush_other_jobs(self):
        r = EventRecorder(max_events=64, per_object_max=8)
        r.event(_Obj("quiet"), "Normal", "ReasonQ", "important")
        for j in range(40):
            for i in range(4):
                r.event(_Obj(f"storm-{j}"), "Normal", "ReasonS", f"m{i}")
            # The quiet job stays live through the whole storm.
            r.event(_Obj("quiet"), "Normal", "ReasonQ", "important")
        ev = r.events_for("default", "quiet")
        assert len(ev) == 1 and ev[0].count >= 40

    def test_dedup_survives_ring_storage(self):
        r = EventRecorder(per_object_max=4)
        for _ in range(5):
            r.event(_Obj("a"), "Normal", "ReasonY", "same message")
        ev = r.events_for("default", "a")
        assert len(ev) == 1 and ev[0].count == 5


# ---------------------------------------------------------------------------
# Bounded reservoir metrics
# ---------------------------------------------------------------------------

class TestReservoirMetrics:
    def test_memory_is_bounded(self):
        res = _Reservoir(size=64, window=128)
        for i in range(100_000):
            res.add(float(i % 100))
        assert len(res._buf) == 64
        assert len(res._recent) == 128
        assert res.count == 100_000

    def test_percentiles_plausible(self):
        m = ReconcileMetrics(max_samples=512)
        for i in range(10_000):
            m.record_sync(i / 10_000.0)
        assert 0.3 < m.p50 < 0.7
        assert m.p99 > 0.9
        snap = m.snapshot()
        assert snap["samples"] == 10_000
        assert snap["syncs"] == 10_000

    def test_percentile_since_windows_newest(self):
        m = ReconcileMetrics(max_samples=512)
        for _ in range(1000):
            m.record_sync(0.001)
        start = m.sample_count()
        for _ in range(500):
            m.record_sync(1.0)  # the "storm"
        assert m.percentile_since(50, start) == 1.0
        assert m.percentile(50) < 1.0 or True  # all-time blends both
