"""Workload layer units: env contract, synthetic data, checkpoint roundtrip."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_controller_tpu.planner.materialize import (
    ENV_COORDINATOR,
    ENV_NUM_PROCESSES,
    ENV_PROCESS_ID,
    ENV_TPU_ACCELERATOR,
    ENV_TPU_WORKER_HOSTNAMES,
)
from kubeflow_controller_tpu.workloads import data as d
from kubeflow_controller_tpu.workloads.checkpoint import CheckpointManager
from kubeflow_controller_tpu.workloads.runtime import JobRuntime
from kubeflow_controller_tpu.workloads.trainer import default_optimizer, make_train_step


class TestJobRuntime:
    def test_from_env_reads_controller_contract(self):
        env = {
            ENV_COORDINATOR: "host-0.job-abc-tpu:8476",
            ENV_NUM_PROCESSES: "4",
            ENV_PROCESS_ID: "2",
            ENV_TPU_ACCELERATOR: "v5e-16",
            ENV_TPU_WORKER_HOSTNAMES: "h0,h1,h2,h3",
            "MODEL_DIR": "/ckpt",
        }
        rt = JobRuntime.from_env(env)
        assert rt.coordinator == "host-0.job-abc-tpu:8476"
        assert rt.num_processes == 4
        assert rt.process_id == 2
        assert not rt.is_chief
        assert rt.worker_hostnames == ["h0", "h1", "h2", "h3"]
        assert rt.model_dir == "/ckpt"

    def test_empty_env_is_single_process(self):
        rt = JobRuntime.from_env({})
        assert rt.num_processes == 1 and rt.is_chief
        rt.initialize()  # no-op, must not try to reach a coordinator
        assert rt._initialized

    def test_wait_coordinator_returns_once_port_bound(self):
        # The pre-connect TCP poll (avoids the ~1s gRPC reconnect backoff
        # when a worker dials before the coordinator binds) must return
        # promptly once something is listening, and must not hang forever
        # on a malformed address.
        import socket
        import time

        srv = socket.socket()
        srv.bind(("127.0.0.1", 0))
        srv.listen(1)
        port = srv.getsockname()[1]
        rt = JobRuntime(coordinator=f"127.0.0.1:{port}", num_processes=2,
                        process_id=1)
        t0 = time.monotonic()
        rt._wait_coordinator(timeout_s=5.0)
        assert time.monotonic() - t0 < 2.0
        srv.close()
        # Malformed coordinator -> immediate no-op (initialize() will fail
        # with jax's own clearer error).
        JobRuntime(coordinator="nonsense", num_processes=2,
                   process_id=1)._wait_coordinator(timeout_s=5.0)


class TestSyntheticData:
    def test_mnist_deterministic_and_balanced(self):
        x1, y1 = d.synthetic_mnist(jax.random.PRNGKey(5), 1000)
        x2, y2 = d.synthetic_mnist(jax.random.PRNGKey(5), 1000)
        np.testing.assert_array_equal(np.asarray(y1), np.asarray(y2))
        np.testing.assert_allclose(np.asarray(x1), np.asarray(x2))
        assert x1.shape == (1000, 784) and y1.dtype == jnp.int32
        counts = np.bincount(np.asarray(y1), minlength=10)
        assert counts.min() > 50  # roughly balanced classes

    def test_mnist_linearly_learnable(self):
        """The frozen mixture must support ~0.9 accuracy — the parity bar
        from the reference's local run (docs/get_started.md:29-38)."""
        x, y = d.synthetic_mnist(jax.random.PRNGKey(0), 4000)
        ex, ey = d.synthetic_mnist(jax.random.PRNGKey(1), 2000)
        # Closed-form-ish: class-mean classifier.
        means = jnp.stack([x[y == c].mean(0) for c in range(10)])
        pred = jnp.argmax(ex @ means.T - 0.5 * jnp.sum(means * means, -1), axis=-1)
        acc = float(jnp.mean(pred == ey))
        assert acc > 0.85, acc

    def test_tokens_have_bigram_structure(self):
        toks = d.synthetic_tokens(jax.random.PRNGKey(0), 32, 128, vocab=64)
        assert toks.shape == (32, 128) and toks.dtype == jnp.int32
        # With 90% chain-following, successor entropy is far below uniform:
        # the most common successor of each token dominates.
        t = np.asarray(toks)
        pairs = {}
        for row in t:
            for a, b in zip(row[:-1], row[1:]):
                pairs.setdefault(int(a), []).append(int(b))
        frac = np.mean([
            np.max(np.bincount(v)) / len(v) for v in pairs.values() if len(v) >= 10
        ])
        assert frac > 0.6, frac

    def test_shard_for_process(self):
        x = jnp.arange(12)
        np.testing.assert_array_equal(
            np.asarray(d.shard_for_process(x, 1, 3)), np.arange(4, 8)
        )


class TestCheckpoint:
    def test_save_restore_roundtrip(self, tmp_path):
        params = {"w": jnp.arange(6.0).reshape(2, 3), "b": jnp.zeros((3,))}
        opt = default_optimizer(1e-3)
        opt_state = opt.init(params)
        mgr = CheckpointManager(str(tmp_path / "ck"))
        assert mgr.latest_step() is None
        mgr.save(7, params, opt_state)
        p2, o2, step = CheckpointManager(str(tmp_path / "ck")).restore(params, opt_state)
        assert step == 7
        np.testing.assert_allclose(np.asarray(p2["w"]), np.asarray(params["w"]))

    def test_restore_without_checkpoint_raises(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path / "empty"))
        with pytest.raises(FileNotFoundError):
            mgr.restore({}, {})


class TestTrainStep:
    def test_donated_step_trains(self):
        x, y = d.synthetic_mnist(jax.random.PRNGKey(0), 512)
        from kubeflow_controller_tpu.models import mnist as m

        params = m.mlp_init(jax.random.PRNGKey(0))
        opt = default_optimizer(5e-3)
        state = opt.init(params)
        step = make_train_step(lambda p, b: m.mlp_loss(p, b[0], b[1]), opt)
        params, state, l0 = step(params, state, (x, y))
        for _ in range(20):
            params, state, loss = step(params, state, (x, y))
        assert float(loss) < float(l0)


def test_numpy_opt_state_matches_optax_init():
    """numpy_opt_state is valid only while default_optimizer's init is
    all-zeros — lock the two together so a future transform with non-zero
    init state cannot silently train from a wrong state."""
    import numpy as np

    from kubeflow_controller_tpu.models import mnist as m
    from kubeflow_controller_tpu.workloads.trainer import (
        default_optimizer,
        numpy_opt_state,
    )

    params = m.mlp_init(0)
    for kwargs in ({}, {"weight_decay": 0.1}, {"clip": None}):
        opt = default_optimizer(1e-3, **kwargs)
        fast = numpy_opt_state(opt, params)
        real = opt.init(params)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)), fast, real)
        assert (jax.tree_util.tree_structure(fast)
                == jax.tree_util.tree_structure(real))
