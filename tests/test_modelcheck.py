"""Model-checking layer (PR 11): the linearizability checker (sequential
spec + WGL search + cross-kind RV tokens), the store's opt-in recording
hook, watch-delivery exactness, the deterministic-simulation driver, and
the interleave exception-path fixes."""

import os
import sys
import threading
import time

import pytest

from kubeflow_controller_tpu.analysis import (
    interleave,
    linearize,
    lockcheck,
    simcheck,
    watchcheck,
)
from kubeflow_controller_tpu.analysis.linearize import (
    HistoryRecorder,
    SearchBudgetExceeded,
    _rec,
    build_key_histories,
    check_records,
    check_rv_tokens,
    linearize_key,
)
from kubeflow_controller_tpu.api.core import Pod
from kubeflow_controller_tpu.cluster.store import Conflict, ObjectStore


def _pod(name: str, ns: str = "default") -> Pod:
    p = Pod()
    p.metadata.namespace = ns
    p.metadata.name = name
    return p


# ---------------------------------------------------------------------------
# Sequential spec + WGL search on synthetic histories
# ---------------------------------------------------------------------------

class TestKnownHistories:
    @pytest.mark.parametrize("name", sorted(linearize.KNOWN_BAD))
    def test_known_bad_rejected(self, name):
        """Every known-bad synthetic history MUST be rejected — the
        check-smoke precondition for trusting a green simulation."""
        violations = check_records(linearize.KNOWN_BAD[name])
        assert violations, f"known-bad history {name!r} was accepted"

    @pytest.mark.parametrize("name", ["stale-read", "lost-update",
                                      "non-monotonic-list-rv"])
    def test_satellite_required_rejections(self, name):
        """The three bug classes the issue names explicitly."""
        assert check_records(linearize.KNOWN_BAD[name])

    @pytest.mark.parametrize("name", sorted(linearize.KNOWN_GOOD))
    def test_known_good_accepted(self, name):
        got = check_records(linearize.KNOWN_GOOD[name])
        assert got == [], [v.render() for v in got]

    def test_self_test_is_green(self):
        assert linearize.self_test() == []
        assert watchcheck.self_test() == []
        assert simcheck.run_self_test() == []


class TestWGLSearch:
    def test_overlapping_ops_explore_both_orders(self):
        """A read overlapping a CAS may legally see either the old or the
        new RV; a read AFTER the CAS returned may only see the new one."""
        base = [_rec("create", rv=1, t=(0, 1)),
                _rec("update", expected=1, rv=2, t=(2, 6))]
        ok_old = base + [_rec("get", rv=1, t=(3, 5))]   # overlaps the CAS
        ok_new = base + [_rec("get", rv=2, t=(3, 5))]
        bad = base + [_rec("get", rv=1, t=(7, 8))]      # strictly after
        assert check_records(ok_old) == []
        assert check_records(ok_new) == []
        assert check_records(bad)

    def test_memoized_search_handles_long_sequential_history(self):
        recs = [_rec("create", rv=1, t=(0, 1))]
        t, rv = 2, 1
        for i in range(400):
            recs.append(_rec("update", expected=rv, rv=rv + 1, t=(t, t + 1)))
            rv += 1
            t += 2
        assert check_records(recs) == []

    def test_search_budget_is_enforced(self):
        # 8 fully-overlapping RMWs with distinct RVs followed by a read
        # no order can satisfy: the search must refute every (mask, last-
        # writer) configuration — ~8·2^8 states — before giving up, so a
        # budget of 200 trips first.
        recs = [_rec("create", rv=100, t=(-2, -1))]
        recs += [_rec("patch", rv=i, t=(0, 10)) for i in range(1, 9)]
        recs.append(_rec("get", rv=999, t=(11, 12)))
        ops = build_key_histories(recs)
        (key, key_ops), = ops.items()
        with pytest.raises(SearchBudgetExceeded):
            linearize_key(key_ops, key=key, max_configs=200)

    def test_failure_report_names_pending_ops(self):
        res = linearize_key(
            build_key_histories(linearize.KNOWN_BAD["stale-read"])[
                ("pods", "default", "a")],
            key=("pods", "default", "a"))
        assert not res.ok
        assert "pending" in res.message()


class TestRVTokens:
    def test_concurrent_writes_may_interleave(self):
        # Overlapping writes: no real-time order, any RVs are fine.
        recs = [_rec("create", "a", rv=2, t=(0, 5)),
                _rec("create", "b", kind="services", rv=1, t=(1, 6))]
        assert check_rv_tokens(recs) == []

    def test_sequential_writes_must_increase(self):
        recs = [_rec("create", "a", rv=5, t=(0, 1)),
                _rec("create", "b", kind="services", rv=4, t=(2, 3))]
        out = check_rv_tokens(recs)
        assert out and out[0].checker == "rv-monotonicity"

    def test_list_rv_may_repeat_but_not_regress(self):
        ok = [_rec("list_with_rv", None, items=(), rv=7, t=(0, 1)),
              _rec("list_with_rv", None, items=(), rv=7, t=(2, 3))]
        assert check_rv_tokens(ok) == []


# ---------------------------------------------------------------------------
# The store recording hook
# ---------------------------------------------------------------------------

class TestRecorderHook:
    def test_detached_store_has_zero_footprint(self):
        store = ObjectStore()
        baseline_dict = set(store.__dict__)
        rec = HistoryRecorder()
        store.attach_recorder(rec)
        assert set(store.__dict__) - baseline_dict >= set(
            ObjectStore.RECORDED_OPS)
        store.detach_recorder()
        # Back to plain class-method dispatch: no wrapper attrs remain.
        assert not (set(store.__dict__) & set(ObjectStore.RECORDED_OPS))
        assert store.create.__func__ is ObjectStore.create

    def test_double_attach_refused(self):
        store = ObjectStore()
        store.attach_recorder(HistoryRecorder())
        with pytest.raises(RuntimeError):
            store.attach_recorder(HistoryRecorder())
        store.detach_recorder()

    def test_errors_recorded_with_class_name(self):
        store = ObjectStore()
        rec = HistoryRecorder()
        store.attach_recorder(rec)
        created = store.create("pods", _pod("x"))
        stale = _pod("x")
        stale.metadata.resource_version = "999"
        with pytest.raises(Conflict):
            store.update("pods", stale)
        store.detach_recorder()
        recs = rec.records()
        assert [r.op for r in recs] == ["create", "update"]
        assert recs[1].err == "Conflict"
        assert recs[1].expected_rv == 999
        assert int(created.metadata.resource_version) == recs[0].rv

    def test_plain_list_routes_through_recorded_list_with_rv(self):
        store = ObjectStore()
        rec = HistoryRecorder()
        store.attach_recorder(rec)
        store.create("pods", _pod("x"))
        store.list("pods", "default")
        store.detach_recorder()
        assert [r.op for r in rec.records()] == ["create", "list_with_rv"]
        lst = rec.records()[-1]
        assert lst.items and lst.items[0][1] == "x"

    def test_real_history_checks_clean(self):
        store = ObjectStore()
        rec = HistoryRecorder()
        store.attach_recorder(rec)
        store.create("pods", _pod("x"))
        got = store.get("pods", "default", "x")
        got.metadata.labels["a"] = "b"
        store.update("pods", got)
        store.delete("pods", "default", "x", cascade=False)
        store.detach_recorder()
        assert check_records(rec.records()) == []


class TestRVMonotonicityProperty:
    """The satellite property test: strict cross-kind RV monotonicity
    under concurrent writers, on the sharded store AND the global-lock
    baseline (whose one lock must not change the contract)."""

    @pytest.mark.parametrize("sharded", [True, False])
    def test_concurrent_writers_all_kinds(self, sharded):
        store = ObjectStore(sharded=sharded)
        rec = HistoryRecorder()
        store.attach_recorder(rec)
        kinds = ("pods", "services", "tfjobs")
        stop = threading.Event()
        errors = []

        def writer(kind, idx):
            i = 0
            try:
                while not stop.is_set():
                    name = f"{kind}-{(i + idx) % 6}"
                    try:
                        store.create(kind, _pod(name))
                    except Exception:
                        try:
                            obj = store.get(kind, "default", name)
                            store.update(kind, obj)
                        except Exception:
                            pass
                    if i % 5 == 0:
                        try:
                            store.delete(kind, "default", name,
                                         cascade=False)
                        except Exception:
                            pass
                    store.list_with_rv(kind, "default")
                    i += 1
            except BaseException as e:  # pragma: no cover - diagnostic
                errors.append(e)

        threads = [threading.Thread(target=writer, args=(k, j),
                                    name=f"rvprop-{k}-{j}", daemon=True)
                   for k in kinds for j in range(2)]
        for t in threads:
            t.start()
        time.sleep(0.25)
        stop.set()
        for t in threads:
            t.join(timeout=5.0)
        store.detach_recorder()
        assert not errors
        records = rec.records()
        assert len(records) > 100
        assert check_rv_tokens(records) == []
        # And the per-key WGL pass holds on the same history.
        assert check_records(records) == []


# ---------------------------------------------------------------------------
# Watch-delivery exactness
# ---------------------------------------------------------------------------

class TestWatchcheck:
    @pytest.mark.parametrize("name", sorted(watchcheck.KNOWN_BAD_STREAMS))
    def test_known_bad_streams_rejected(self, name):
        events, oracle = watchcheck.KNOWN_BAD_STREAMS[name]
        assert watchcheck.verify_stream(events, oracle=oracle, label=name)

    def test_good_stream_accepted(self):
        events, oracle = watchcheck.KNOWN_GOOD_STREAM
        assert watchcheck.verify_stream(events, oracle=oracle) == []

    def test_overflow_drop_resume_is_exact(self):
        """A slow consumer on a tiny bounded queue is dropped and
        transparently RV-resumed by the store; its merged stream must
        still be exactly-once, ordered, and gap-free vs the oracle."""
        store = ObjectStore(watch_cache_size=65536, watch_queue_size=8)
        oracle = watchcheck.ShadowConsumer(store, "pods", max_queue=0,
                                           name="oracle").start()
        slow = watchcheck.ShadowConsumer(store, "pods", name="slow",
                                         slow_every=2, slow_us=500).start()
        for i in range(300):
            store.create("pods", _pod(f"p-{i:03d}"))
        time.sleep(0.3)
        for c in (slow, oracle):
            c.stop()
            c.drain()
        overflows = sum(sh.overflows for sh in store._shards.values())
        assert overflows > 0, "queue never overflowed: test mis-sized"
        out = watchcheck.verify_consumers({"pods": oracle}, [slow])
        assert out == [], [v.render() for v in out]
        assert slow.events, "slow consumer saw nothing"

    def test_crash_point_resume_is_exact(self):
        store = ObjectStore(watch_cache_size=65536)
        oracle = watchcheck.ShadowConsumer(store, "pods", max_queue=0,
                                           name="oracle").start()
        victim = watchcheck.ShadowConsumer(store, "pods",
                                           name="victim").start()
        for i in range(100):
            store.create("pods", _pod(f"p-{i:03d}"))
            if i % 25 == 10:
                victim.crash()
        time.sleep(0.3)
        for c in (victim, oracle):
            c.stop()
            c.drain()
        assert victim.crashes >= 1
        out = watchcheck.verify_consumers({"pods": oracle}, [victim])
        assert out == [], [v.render() for v in out]

    def test_forced_drop_mid_batch_is_exact(self):
        store = ObjectStore(watch_cache_size=65536)
        oracle = watchcheck.ShadowConsumer(store, "pods", max_queue=0,
                                           name="oracle").start()
        c = watchcheck.ShadowConsumer(store, "pods", name="dropped").start()
        total_dropped = 0
        for i in range(120):
            store.create("pods", _pod(f"p-{i:03d}"))
            if i % 40 == 20:
                # A later drop can land before the consumer re-subscribed
                # from the previous one (it is then not in the watcher
                # list) — only the total matters.
                total_dropped += store.drop_watchers(
                    "pods", exclude=(oracle.watcher,))
        assert total_dropped >= 1
        time.sleep(0.3)
        for x in (c, oracle):
            x.stop()
            x.drain()
        out = watchcheck.verify_consumers({"pods": oracle}, [c])
        assert out == [], [v.render() for v in out]

    def test_negative_control_lost_event_is_flagged(self):
        """End-to-end negative: silently drop one delivered event from a
        consumer's log and the verifier must report the gap."""
        store = ObjectStore(watch_cache_size=65536)
        oracle = watchcheck.ShadowConsumer(store, "pods", max_queue=0,
                                           name="oracle").start()
        c = watchcheck.ShadowConsumer(store, "pods", name="lossy").start()
        for i in range(30):
            store.create("pods", _pod(f"p-{i:03d}"))
        time.sleep(0.2)
        for x in (c, oracle):
            x.stop()
            x.drain()
        assert len(c.events) >= 10
        del c.events[4]  # the injected delivery bug
        out = watchcheck.verify_consumers({"pods": oracle}, [c])
        assert any("gap" in v.message for v in out)


# ---------------------------------------------------------------------------
# The simulation driver
# ---------------------------------------------------------------------------

class TestSimcheck:
    def test_one_seed_clean_with_injection(self):
        out = simcheck.run_seed(7, duration_s=0.25)
        assert out["violations"] == [], \
            [v.render() for v in out["violations"]]
        assert out["ops"] > 200
        assert out["drops"] >= 1
        assert all(n > 0 for n in out["events"].values())

    def test_repro_command_round_trips_the_seed(self):
        cmd = simcheck.repro_command(42, 0.5)
        assert "KCTPU_FUZZ_SEED=42" in cmd
        assert "--seeds 42" in cmd
        assert "simcheck" in cmd

    def test_main_json_envelope(self, capsys, monkeypatch):
        monkeypatch.delenv("KCTPU_FUZZ_SEED", raising=False)
        rc = simcheck.main(["--self-test", "--seeds", "9",
                            "--duration", "0.15", "--json"])
        captured = capsys.readouterr()
        import json

        doc = json.loads(captured.out)
        assert rc == 0
        assert doc["tool"] == "kctpu-check"
        assert doc["schema_version"] == 1
        assert doc["clean"] is True
        assert doc["self_test"] is True
        assert doc["findings"] == []

    def test_failing_seed_exports_env_and_prints_repro(self, capsys,
                                                      monkeypatch):
        monkeypatch.delenv("KCTPU_FUZZ_SEED", raising=False)

        def broken_run_seed(seed, duration_s=0.5):
            return {"seed": seed, "ops": 0, "keys": 0, "events": {},
                    "drops": 0, "crashes": 0, "overflow_drops": 0,
                    "violations": [linearize.Violation(
                        "linearizability", "pods/default/a", "boom")]}

        monkeypatch.setattr(simcheck, "run_seed", broken_run_seed)
        rc = simcheck.main(["--seeds", "13", "--duration", "0.1"])
        captured = capsys.readouterr()
        assert rc == 1
        assert os.environ.get("KCTPU_FUZZ_SEED") == "13"
        assert "repro: KCTPU_FUZZ_SEED=13" in captured.out


# ---------------------------------------------------------------------------
# interleave.py exception-path fixes (satellite)
# ---------------------------------------------------------------------------

class TestInterleaveExceptionPaths:
    def test_run_seed_restores_on_scenario_exception(self):
        from kubeflow_controller_tpu.utils import locks

        before = sys.getswitchinterval()
        assert locks.get_fuzzer() is None

        def explode(duration_s):
            raise AssertionError("scenario blew up")

        with pytest.raises(AssertionError):
            interleave.run_seed(5, 0.05, scenarios={"explode": explode})
        assert sys.getswitchinterval() == pytest.approx(before)
        assert locks.get_fuzzer() is None
        # A fresh checker installed by run_seed is also torn down.
        if os.environ.get("KCTPU_LOCKCHECK", "") in ("", "0"):
            assert lockcheck.installed() is None

    def test_failed_scenario_prints_repro_and_exports_seed(self, capsys,
                                                           monkeypatch):
        monkeypatch.delenv("KCTPU_FUZZ_SEED", raising=False)

        def explode(duration_s):
            raise AssertionError("injected failure")

        monkeypatch.setitem(interleave.SCENARIOS, "store", explode)
        rc = interleave.main(["--seeds", "17", "--duration", "0.05",
                              "--scenario", "store"])
        captured = capsys.readouterr()
        assert rc == 1
        assert os.environ.get("KCTPU_FUZZ_SEED") == "17"
        assert "repro: KCTPU_FUZZ_SEED=17" in captured.out
        assert "--scenario store" in captured.out

    def test_repro_command_format(self):
        cmd = interleave.repro_command(101, 0.5, "workqueue")
        assert cmd.startswith("KCTPU_FUZZ_SEED=101 ")
        assert "--seeds 101" in cmd and "--scenario workqueue" in cmd
