"""Parallel layer: mesh resolution, logical sharding rules, ring attention
numerics vs the naive oracle — all on the virtual 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from kubeflow_controller_tpu.parallel import (
    DEFAULT_RULES,
    MeshSpec,
    build_mesh,
    logical_to_pspec,
    ring_attention,
)
from kubeflow_controller_tpu.parallel.mesh import data_parallel_size, mesh_shape_for
from kubeflow_controller_tpu.parallel.ring import attention_reference
from kubeflow_controller_tpu.parallel.compat import set_mesh as compat_set_mesh


class TestMeshSpec:
    def test_wildcard_absorbs_remaining(self):
        sizes = MeshSpec(dp=2, fsdp=-1, tp=2).resolve(8)
        assert sizes["fsdp"] == 2 and sizes["dp"] == 2 and sizes["tp"] == 2

    def test_fixed_mismatch_raises(self):
        with pytest.raises(ValueError):
            MeshSpec(dp=3, fsdp=1).resolve(8)

    def test_two_wildcards_raise(self):
        with pytest.raises(ValueError):
            MeshSpec(dp=-1, fsdp=-1).resolve(8)

    def test_indivisible_raises(self):
        with pytest.raises(ValueError):
            MeshSpec(dp=3, fsdp=-1).resolve(8)

    def test_canonical_order(self):
        shape = mesh_shape_for(8, MeshSpec(tp=2, fsdp=-1))
        assert [a for a, _ in shape] == ["pp", "dp", "fsdp", "ep", "sp", "tp"]

    def test_build_mesh_all_devices(self):
        mesh = build_mesh(MeshSpec(fsdp=-1))
        assert mesh.devices.size == 8
        assert mesh.shape["fsdp"] == 8
        assert data_parallel_size(mesh) == 8


class TestShardingRules:
    def test_batch_maps_to_dp_fsdp(self):
        # 'embed' would claim fsdp a second time -> dropped to replicated.
        assert logical_to_pspec(("batch", "seq", "embed")) == P(
            ("dp", "fsdp"), "sp", None
        )

    def test_param_embed_shards_over_fsdp(self):
        assert logical_to_pspec(("embed", "mlp")) == P("fsdp", "tp")

    def test_partial_conflict_keeps_free_axes(self):
        # 'embed' takes fsdp; 'batch' -> ('dp','fsdp') keeps the free dp.
        assert logical_to_pspec(("embed", "batch")) == P("fsdp", "dp")

    def test_bare_string_leaf_rejected(self):
        from kubeflow_controller_tpu.parallel import shard_pytree_specs
        with pytest.raises(TypeError):
            shard_pytree_specs({"w": "batch"})

    def test_constraint_applies_under_mesh(self):
        from kubeflow_controller_tpu.parallel import with_logical_constraint
        mesh = build_mesh(MeshSpec(dp=2, fsdp=2, sp=1, tp=2))
        x = jnp.zeros((4, 8, 6))
        # No mesh context: identity.
        assert with_logical_constraint(x, ("batch", "seq", "heads")) is x
        with compat_set_mesh(mesh):
            y = jax.jit(lambda a: with_logical_constraint(a, ("batch", "seq", "heads")))(x)
        assert y.shape == x.shape

    def test_unknown_logical_replicated(self):
        assert logical_to_pspec(("nonesuch",)) == P(None)

    def test_none_axis_replicated(self):
        assert logical_to_pspec((None, "mlp")) == P(None, "tp")


@pytest.mark.slow
class TestRingAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_reference_sp4(self, causal):
        mesh = build_mesh(MeshSpec(fsdp=2, sp=4, tp=1))
        key = jax.random.PRNGKey(0)
        b, t, h, d = 4, 32, 2, 16
        q, k, v = (
            jax.random.normal(kk, (b, t, h, d), dtype=jnp.float32)
            for kk in jax.random.split(key, 3)
        )
        with compat_set_mesh(mesh):
            out = ring_attention(q, k, v, mesh, causal=causal)
        ref = attention_reference(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)

    def test_sp1_degenerates_to_plain_attention(self):
        mesh = build_mesh(MeshSpec(dp=2, fsdp=2, sp=1, tp=2))
        key = jax.random.PRNGKey(1)
        b, t, h, d = 4, 16, 2, 8
        q, k, v = (
            jax.random.normal(kk, (b, t, h, d), dtype=jnp.float32)
            for kk in jax.random.split(key, 3)
        )
        with compat_set_mesh(mesh):
            out = ring_attention(q, k, v, mesh, causal=True)
        ref = attention_reference(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)

    def test_jit_compiles_under_mesh(self):
        mesh = build_mesh(MeshSpec(fsdp=2, sp=4))
        key = jax.random.PRNGKey(2)
        b, t, h, d = 2, 32, 2, 8
        q, k, v = (
            jax.random.normal(kk, (b, t, h, d), dtype=jnp.float32)
            for kk in jax.random.split(key, 3)
        )
        with compat_set_mesh(mesh):
            f = jax.jit(lambda a, b_, c: ring_attention(a, b_, c, mesh, causal=True))
            out = f(q, k, v)
        assert out.shape == (b, t, h, d)

    @pytest.mark.parametrize("causal", [True, False])
    def test_flash_inner_grads_match_reference(self, causal):
        """The flash-inner custom VJP (blockwise flash backward with dk/dv
        accumulators rotating home around the ring) against the dense
        oracle's gradients."""
        mesh = build_mesh(MeshSpec(fsdp=2, sp=4, tp=1))
        key = jax.random.PRNGKey(3)
        b, t, h, d = 2, 32, 2, 16
        q, k, v = (
            jax.random.normal(kk, (b, t, h, d), dtype=jnp.float32)
            for kk in jax.random.split(key, 3)
        )

        def loss_ring(q, k, v):
            return jnp.sum(
                ring_attention(q, k, v, mesh, causal=causal,
                               inner="flash") ** 2)

        def loss_ref(q, k, v):
            return jnp.sum(attention_reference(q, k, v, causal=causal) ** 2)

        with compat_set_mesh(mesh):
            gq, gk, gv = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
        rq, rk, rv = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for got, want in ((gq, rq), (gk, rk), (gv, rv)):
            np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                       atol=5e-5, rtol=5e-5)


class TestFlashBlock:
    def test_alignment_gating(self):
        from kubeflow_controller_tpu.parallel.ring import flash_block

        # f32 sublane tile is 8; bf16 is 16.
        assert flash_block(1024, jnp.float32) == 1024
        assert flash_block(8, jnp.float32) == 8
        assert flash_block(8, jnp.bfloat16) == 0     # below bf16 tile
        assert flash_block(24, jnp.bfloat16) == 0    # 24 % 16 != 0
        assert flash_block(24, jnp.float32) == 24    # 24 % 8 == 0
        assert flash_block(7, jnp.float32) == 0      # odd length
        assert flash_block(2048, jnp.bfloat16) == 1024

    @pytest.mark.parametrize("causal", [True, False])
    @pytest.mark.slow
    def test_unaligned_shard_falls_back_to_dense(self, causal):
        """bf16 with t_local=8 (< the 16-row bf16 tile) must take the dense
        inner and still match the oracle — the flash path would fail Mosaic
        compilation on real TPUs at this shape."""
        mesh = build_mesh(MeshSpec(fsdp=2, sp=4, tp=1))
        key = jax.random.PRNGKey(7)
        b, t, h, d = 2, 32, 2, 16
        q, k, v = (
            jax.random.normal(kk, (b, t, h, d)).astype(jnp.bfloat16)
            for kk in jax.random.split(key, 3)
        )
        with compat_set_mesh(mesh):
            out = ring_attention(q, k, v, mesh, causal=causal, inner="flash")
        ref = attention_reference(q, k, v, causal=causal)
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(ref, np.float32),
            atol=3e-2, rtol=3e-2)


class TestUlyssesAttention:
    """All-to-all sequence parallelism vs the same oracle as ring."""

    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_reference_sp4(self, causal):
        from kubeflow_controller_tpu.parallel import ulysses_attention

        mesh = build_mesh(MeshSpec(fsdp=2, sp=4, tp=1))
        key = jax.random.PRNGKey(0)
        b, t, h, d = 4, 32, 4, 16  # heads divisible by sp
        q, k, v = (
            jax.random.normal(kk, (b, t, h, d), dtype=jnp.float32)
            for kk in jax.random.split(key, 3)
        )
        with compat_set_mesh(mesh):
            out = ulysses_attention(q, k, v, mesh, causal=causal)
        ref = attention_reference(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_with_tp_sharded_heads(self):
        """sp=2 and tp=2 together: local heads = H/tp must still divide sp."""
        from kubeflow_controller_tpu.parallel import ulysses_attention

        mesh = build_mesh(MeshSpec(dp=2, sp=2, tp=2))
        key = jax.random.PRNGKey(1)
        b, t, h, d = 2, 16, 8, 8
        q, k, v = (
            jax.random.normal(kk, (b, t, h, d), dtype=jnp.float32)
            for kk in jax.random.split(key, 3)
        )
        with compat_set_mesh(mesh):
            out = jax.jit(
                lambda a, b_, c: ulysses_attention(a, b_, c, mesh, causal=True)
            )(q, k, v)
        ref = attention_reference(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    @pytest.mark.slow
    def test_grads_flow(self):
        from kubeflow_controller_tpu.parallel import ulysses_attention

        mesh = build_mesh(MeshSpec(fsdp=2, sp=4))
        key = jax.random.PRNGKey(2)
        b, t, h, d = 2, 32, 4, 8
        q, k, v = (
            jax.random.normal(kk, (b, t, h, d), dtype=jnp.float32)
            for kk in jax.random.split(key, 3)
        )
        with compat_set_mesh(mesh):
            g = jax.grad(
                lambda q: jnp.mean(ulysses_attention(q, k, v, mesh) ** 2))(q)
            gr = jax.grad(
                lambda q: jnp.mean(attention_reference(q, k, v) ** 2))(q)
        np.testing.assert_allclose(np.asarray(g), np.asarray(gr),
                                   atol=2e-5, rtol=2e-5)

    def test_llama_ulysses_matches_dense(self):
        """Model-level: the sp_attention='ulysses' path reproduces the
        unsharded forward."""
        import dataclasses

        from kubeflow_controller_tpu.models import (
            LlamaConfig, llama_forward, llama_init)
        from kubeflow_controller_tpu.models.llama import llama_param_pspecs
        from jax.sharding import NamedSharding

        cfg = LlamaConfig.tiny(remat=False)
        params = llama_init(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(3), (2, 16), 0,
                                    cfg.vocab_size)
        ref = llama_forward(params, tokens, cfg)
        cfg_u = dataclasses.replace(cfg, sp_attention="ulysses")
        mesh = build_mesh(MeshSpec(dp=2, sp=2, tp=2))
        sharded = jax.tree.map(
            lambda a, s: jax.device_put(a, NamedSharding(mesh, s)),
            params, llama_param_pspecs(cfg))
        with compat_set_mesh(mesh):
            out = jax.jit(
                lambda p, t: llama_forward(p, t, cfg_u, mesh=mesh))(sharded, tokens)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-4, rtol=2e-4)
