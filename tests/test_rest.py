"""REST transport tests: the typed clients (cluster/rest.py) against the
in-process HTTP API server (cluster/apiserver.py), and the controller
running end-to-end over HTTP — the exact code path ``-kubeconfig`` selects
(ref: cmd/controller/main.go:47-60; typed client surface at
vendor/.../typed/kubeflow/v1alpha1/tfjob.go:34-154)."""

import time

import pytest

from kubeflow_controller_tpu.api.core import Container, PodTemplateSpec, Pod
from kubeflow_controller_tpu.api.meta import ObjectMeta, OwnerReference
from kubeflow_controller_tpu.api.tfjob import (
    ReplicaType,
    TFJob,
    TFJobPhase,
    TFReplicaSpec,
)
from kubeflow_controller_tpu.cluster import Cluster, FakeKubelet, PhasePolicy
from kubeflow_controller_tpu.cluster.apiserver import FakeAPIServer
from kubeflow_controller_tpu.cluster.rest import (
    Kubeconfig,
    KubeconfigError,
    RestCluster,
)
from kubeflow_controller_tpu.cluster.store import (
    ADDED,
    AlreadyExists,
    APIError,
    Conflict,
    DELETED,
    MODIFIED,
    NotFound,
)
from kubeflow_controller_tpu.controller import Controller


def mk_job(name, *types_and_replicas):
    job = TFJob(metadata=ObjectMeta(name=name, namespace="default"))
    for typ, n in types_and_replicas:
        t = PodTemplateSpec()
        t.spec.containers.append(Container(name="tensorflow", image="img"))
        t.spec.restart_policy = "OnFailure"
        job.spec.tf_replica_specs.append(
            TFReplicaSpec(replicas=n, tf_replica_type=typ, template=t))
    return job


def wait_for(fn, timeout=15.0, interval=0.02):
    deadline = time.time() + timeout
    while time.time() < deadline:
        v = fn()
        if v:
            return v
        time.sleep(interval)
    raise AssertionError("condition not met within timeout")


@pytest.fixture
def server():
    srv = FakeAPIServer()
    url = srv.start()
    yield srv, url
    srv.stop()


@pytest.fixture
def rest(server):
    srv, url = server
    yield RestCluster(Kubeconfig(server=url))


class TestRestCRUD:
    def test_tfjob_roundtrip(self, rest):
        created = rest.tfjobs.create(mk_job("j1", (ReplicaType.LOCAL, 1)))
        assert created.metadata.resource_version
        got = rest.tfjobs.get("default", "j1")
        assert got.metadata.uid == created.metadata.uid
        assert got.spec.tf_replica_specs[0].tf_replica_type == ReplicaType.LOCAL
        assert [j.metadata.name for j in rest.tfjobs.list("default")] == ["j1"]
        rest.tfjobs.delete("default", "j1")
        with pytest.raises(NotFound):
            rest.tfjobs.get("default", "j1")

    def test_create_duplicate_is_already_exists(self, rest):
        rest.tfjobs.create(mk_job("dup", (ReplicaType.LOCAL, 1)))
        with pytest.raises(AlreadyExists):
            rest.tfjobs.create(mk_job("dup", (ReplicaType.LOCAL, 1)))

    def test_stale_update_conflicts(self, rest):
        created = rest.tfjobs.create(mk_job("c1", (ReplicaType.LOCAL, 1)))
        fresh = rest.tfjobs.get("default", "c1")
        fresh.spec.runtime_id = "aaaaa"
        rest.tfjobs.update(fresh)
        created.spec.runtime_id = "bbbbb"  # stale resourceVersion
        with pytest.raises(Conflict):
            rest.tfjobs.update(created)

    def test_generate_name(self, rest):
        pod = Pod()
        pod.metadata.namespace = "default"
        pod.metadata.generate_name = "job-worker-"
        out = rest.pods.create(pod)
        assert out.metadata.name.startswith("job-worker-")
        assert len(out.metadata.name) > len("job-worker-")

    def test_label_selector_list(self, rest):
        for i, color in enumerate(["red", "blue", "red"]):
            p = Pod()
            p.metadata.namespace = "default"
            p.metadata.name = f"p{i}"
            p.metadata.labels = {"color": color}
            rest.pods.create(p)
        reds = rest.pods.list("default", selector={"color": "red"})
        assert sorted(p.metadata.name for p in reds) == ["p0", "p2"]

    def test_status_subresource_ignores_spec(self, rest):
        rest.tfjobs.create(mk_job("s1", (ReplicaType.LOCAL, 1)))
        j = rest.tfjobs.get("default", "s1")
        j.status.phase = TFJobPhase.RUNNING
        j.spec.runtime_id = "hacked"  # must not land through /status
        out = rest.tfjobs.update_status(j)
        assert out.status.phase == TFJobPhase.RUNNING
        assert rest.tfjobs.get("default", "s1").spec.runtime_id != "hacked"

    def test_patch_meta_adoption(self, server, rest):
        srv, _ = server
        rest.tfjobs.create(mk_job("owner", (ReplicaType.LOCAL, 1)))
        owner = rest.tfjobs.get("default", "owner")
        p = Pod()
        p.metadata.namespace = "default"
        p.metadata.name = "orphan"
        rest.pods.create(p)

        def adopt(meta):
            meta.owner_references = [OwnerReference(
                api_version="kubeflow.caicloud.io/v1alpha1", kind="TFJob",
                name="owner", uid=owner.metadata.uid,
                controller=True, block_owner_deletion=True)]
            meta.labels["adopted"] = "true"

        out = rest.pods.patch_meta("default", "orphan", adopt)
        assert out.metadata.owner_references[0].uid == owner.metadata.uid
        # Authoritative state lives in the server's store.
        stored = srv.store.get("pods", "default", "orphan")
        assert stored.metadata.labels["adopted"] == "true"
        assert stored.metadata.owner_references[0].controller is True

    def test_object_patch_over_rest(self, server, rest):
        """The PatchService analog over the wire: a spec-touching merge
        patch mutates exactly the named fields server-side (ref:
        pkg/controller/control/service.go:50-53)."""
        from kubeflow_controller_tpu.api.core import Service, ServiceSpec

        srv, _ = server
        svc = Service(metadata=ObjectMeta(name="svc", namespace="default",
                                          labels={"keep": "yes"}),
                      spec=ServiceSpec(selector={"job": "x", "idx": "0"}))
        rest.services.create(svc)
        out = rest.services.patch("default", "svc", {
            "spec": {"selector": {"idx": "7"}},
            "metadata": {"labels": {"extra": "1"}},
        })
        assert out.spec.selector == {"job": "x", "idx": "7"}
        assert out.metadata.labels == {"keep": "yes", "extra": "1"}
        stored = srv.store.get("services", "default", "svc")
        assert stored.spec.selector["idx"] == "7"
        assert stored.metadata.labels == {"keep": "yes", "extra": "1"}


class TestRestWatch:
    def test_watch_stream_add_modify_delete(self, rest):
        w = rest.tfjobs.watch("default")
        try:
            rest.tfjobs.create(mk_job("w1", (ReplicaType.LOCAL, 1)))
            ev = w.next(timeout=5.0)
            assert ev is not None and ev.type == ADDED
            assert ev.object.metadata.name == "w1"

            j = rest.tfjobs.get("default", "w1")
            j.spec.runtime_id = "zzzzz"
            rest.tfjobs.update(j)
            ev = w.next(timeout=5.0)
            assert ev is not None and ev.type == MODIFIED
            assert ev.object.spec.runtime_id == "zzzzz"

            rest.tfjobs.delete("default", "w1")
            ev = w.next(timeout=5.0)
            assert ev is not None and ev.type == DELETED
        finally:
            w.stop()


class TestWatchGapRelist:
    def test_informer_relists_after_server_restart(self):
        """Events lost while the watch stream is down must be recovered by a
        re-list on reconnect (client-go reflector semantics)."""
        import socket

        from kubeflow_controller_tpu.cluster.store import ObjectStore
        from kubeflow_controller_tpu.controller.informer import SharedInformer

        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]

        store = ObjectStore()
        srv = FakeAPIServer(store, port=port)
        url = srv.start()
        rest = RestCluster(Kubeconfig(server=url))
        informer = SharedInformer(rest.tfjobs, resync_period_s=0, name="tfjobs")
        informer.start()
        try:
            rest.tfjobs.create(mk_job("before", (ReplicaType.LOCAL, 1)))
            wait_for(lambda: informer.get("default", "before") is not None)

            srv.stop()  # the watch stream drops
            # Mutations the informer cannot see while disconnected:
            store.create("tfjobs", mk_job("during", (ReplicaType.LOCAL, 1)))
            store.delete("tfjobs", "default", "before")
            srv2 = FakeAPIServer(store, port=port)
            srv2.start()
            try:
                wait_for(lambda: informer.get("default", "during") is not None)
                wait_for(lambda: informer.get("default", "before") is None)
            finally:
                srv2.stop()
        finally:
            informer.stop()

    def test_informer_relists_after_drop_watches(self, server):
        """drop_watches() (server closes every stream, no restart) must put
        the informer through the same gap re-list: a mutation racing the
        reconnect window is recovered."""
        from kubeflow_controller_tpu.controller.informer import SharedInformer

        srv, url = server
        rest = RestCluster(Kubeconfig(server=url))
        informer = SharedInformer(rest.tfjobs, resync_period_s=0,
                                  name="tfjobs")
        informer.start()
        try:
            rest.tfjobs.create(mk_job("pre", (ReplicaType.LOCAL, 1)))
            wait_for(lambda: informer.get("default", "pre") is not None)
            srv.drop_watches()
            # A write straight to the store right after the drop: it may
            # land in the gap (stream closed, not yet re-listed) — the
            # re-list must surface it either way.
            srv.store.create("tfjobs", mk_job("mid", (ReplicaType.LOCAL, 1)))
            wait_for(lambda: informer.get("default", "mid") is not None)
        finally:
            informer.stop()


class TestAuth:
    def test_bearer_token_required(self):
        srv = FakeAPIServer(token="sekrit")
        url = srv.start()
        try:
            bad = RestCluster(Kubeconfig(server=url))
            with pytest.raises(APIError):
                bad.tfjobs.list("default")
            good = RestCluster(Kubeconfig(server=url, token="sekrit"))
            assert good.tfjobs.list("default") == []
        finally:
            srv.stop()


class TestKubeconfig:
    def test_load_and_master_override(self, tmp_path):
        cfg = tmp_path / "kubeconfig"
        cfg.write_text("""
apiVersion: v1
kind: Config
current-context: ctx
contexts:
- name: ctx
  context: {cluster: c, user: u}
clusters:
- name: c
  cluster: {server: "http://10.0.0.1:8080"}
users:
- name: u
  user: {token: tok123}
""")
        kc = Kubeconfig.load(str(cfg))
        assert kc.server == "http://10.0.0.1:8080"
        assert kc.token == "tok123"
        kc2 = Kubeconfig.load(str(cfg), master="http://127.0.0.1:9999")
        assert kc2.server == "http://127.0.0.1:9999"

    def test_no_server_raises(self, tmp_path):
        cfg = tmp_path / "empty"
        cfg.write_text("apiVersion: v1\nkind: Config\n")
        with pytest.raises(KubeconfigError):
            Kubeconfig.load(str(cfg))

    def test_from_flags_requires_one(self):
        with pytest.raises(KubeconfigError):
            RestCluster.from_flags("", "")


class TestControllerOverREST:
    """The same Controller object, fed a RestCluster: API -> HTTP -> store ->
    watch stream -> informers -> sync -> HTTP writes.  The kubelet drives pod
    phases in the server's store directly, as a node agent would."""

    @pytest.fixture
    def rig(self, server):
        srv, url = server
        substrate = Cluster(store=srv.store)
        kubelet = FakeKubelet(substrate, policy=PhasePolicy(run_s=0.05))
        rest = RestCluster(Kubeconfig(server=url))
        ctrl = Controller(rest, resync_period_s=0.5)
        kubelet.start()
        ctrl.run(threadiness=2)
        yield rest, ctrl
        ctrl.stop()
        kubelet.stop()

    def test_local_job_to_succeeded(self, rig):
        rest, _ = rig
        rest.tfjobs.create(mk_job("local-rest", (ReplicaType.LOCAL, 1)))
        wait_for(lambda: rest.tfjobs.get("default", "local-rest").status.phase
                 == TFJobPhase.SUCCEEDED)
        assert len(rest.pods.list("default")) == 1

    def test_distributed_job_to_succeeded(self, rig):
        rest, _ = rig
        rest.tfjobs.create(
            mk_job("dist-rest", (ReplicaType.PS, 1), (ReplicaType.WORKER, 2)))
        wait_for(lambda: rest.tfjobs.get("default", "dist-rest").status.phase
                 == TFJobPhase.SUCCEEDED)
        job = rest.tfjobs.get("default", "dist-rest")
        types = {rs.type for rs in job.status.tf_replica_statuses}
        assert {ReplicaType.PS, ReplicaType.WORKER} <= types
        # Ownership was stamped over the wire.
        for pod in rest.pods.list("default"):
            refs = pod.metadata.owner_references
            assert refs and refs[0].kind == "TFJob" and refs[0].controller


class TestGangReleaseOverREST:
    def test_sequential_tpu_jobs_reuse_the_slice(self, server):
        """In two-process mode the controller has no inventory handle; the
        kubelet-side reaper must free the slice when a gang's pods finish,
        or every TPU job after the first hangs Pending forever."""
        from kubeflow_controller_tpu.api.tfjob import TPUSpec
        from kubeflow_controller_tpu.cluster import TPUInventory, TPUSlice

        srv, url = server
        substrate = Cluster(store=srv.store)
        inventory = TPUInventory([TPUSlice("slice-0", "v5e-8", num_hosts=2)])
        kubelet = FakeKubelet(substrate, policy=PhasePolicy(run_s=0.05),
                              inventory=inventory)
        rest = RestCluster(Kubeconfig(server=url))
        ctrl = Controller(rest, resync_period_s=0.5)  # inventory=None: REST mode
        kubelet.start()
        ctrl.run(threadiness=2)
        try:
            for name in ("tpu-a", "tpu-b"):
                job = TFJob(metadata=ObjectMeta(name=name, namespace="default"))
                t = PodTemplateSpec()
                t.spec.containers.append(
                    Container(name="tensorflow", image="img"))
                t.spec.restart_policy = "OnFailure"
                spec = TFReplicaSpec(replicas=2, tf_replica_type=ReplicaType.TPU,
                                     template=t)
                spec.tpu = TPUSpec(accelerator_type="v5e-8", chips_per_host=4)
                job.spec.tf_replica_specs.append(spec)
                rest.tfjobs.create(job)
                wait_for(lambda: rest.tfjobs.get("default", name).status.phase
                         == TFJobPhase.SUCCEEDED, timeout=20.0)
        finally:
            ctrl.stop()
            kubelet.stop()


class TestCLITwoProcess:
    """`serve` + `run -master` as real subprocesses — the reference's
    deployment shape (controller binary pointed at an API server)."""

    def test_serve_and_run(self, tmp_path):
        import os
        import re
        import signal
        import subprocess
        import sys

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env = dict(os.environ)
        srv = subprocess.Popen(
            [sys.executable, "-m", "kubeflow_controller_tpu.cli", "serve"],
            cwd=repo, env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        try:
            line = srv.stdout.readline()
            m = re.search(r"listening on (http://\S+)", line)
            assert m, f"no listen line: {line!r}"
            url = m.group(1)
            out = subprocess.run(
                [sys.executable, "-m", "kubeflow_controller_tpu.cli",
                 "-master", url, "run",
                 "--manifests", "examples/jobs/local.yaml", "--until-done"],
                cwd=repo, env=env, capture_output=True, text=True, timeout=120)
            assert out.returncode == 0, out.stderr[-2000:]
            assert "phase=Succeeded" in out.stdout
        finally:
            srv.send_signal(signal.SIGINT)
            try:
                srv.wait(timeout=10)
            except subprocess.TimeoutExpired:
                srv.kill()


class TestCLIGetDescribe:
    def test_get_and_describe_over_rest(self, server):
        from kubeflow_controller_tpu.cli.main import main as cli_main

        srv, url = server
        substrate = Cluster(store=srv.store)
        kubelet = FakeKubelet(substrate, policy=PhasePolicy(run_s=0.05))
        rest = RestCluster(Kubeconfig(server=url))
        ctrl = Controller(rest, resync_period_s=0.5)
        kubelet.start()
        ctrl.run(threadiness=2)
        try:
            rest.tfjobs.create(
                mk_job("cli-job", (ReplicaType.WORKER, 2)))
            wait_for(lambda: rest.tfjobs.get("default", "cli-job").status.phase
                     == TFJobPhase.SUCCEEDED)
        finally:
            ctrl.stop()
            kubelet.stop()

        import contextlib
        import io

        out = io.StringIO()
        with contextlib.redirect_stdout(out):
            rc = cli_main(["-master", url, "get"])
        assert rc == 0
        assert "cli-job" in out.getvalue()
        assert "Succeeded" in out.getvalue()

        out = io.StringIO()
        with contextlib.redirect_stdout(out):
            rc = cli_main(["-master", url, "describe", "cli-job"])
        assert rc == 0
        text = out.getvalue()
        assert "Phase:     Succeeded" in text
        assert "SuccessfulCreate" in text  # events came from the API
        # The per-replica health report (checker/health.py) renders from
        # the job's live pods.
        assert "Health:    Complete" in text
        assert "Worker: Complete" in text

    def test_describe_missing_job(self, server):
        from kubeflow_controller_tpu.cli.main import main as cli_main

        _, url = server
        assert cli_main(["-master", url, "describe", "nope"]) == 1


class TestPodLogsOverREST:
    def test_logs_and_delete_cli(self, tmp_path):
        """Pod logs flow kubelet -> API server log subresource -> REST
        client -> CLI; delete flows CLI -> finalizer cleanup."""
        import contextlib
        import io
        import sys as _sys

        from kubeflow_controller_tpu.api.core import Pod
        from kubeflow_controller_tpu.cli.main import main as cli_main
        from kubeflow_controller_tpu.cluster.store import ObjectStore

        store = ObjectStore()
        substrate = Cluster(store=store)
        kubelet = FakeKubelet(substrate, policy=PhasePolicy(run_s=0.05),
                              execute=True, warm_start=False)
        srv = FakeAPIServer(store, kubelet=kubelet)
        url = srv.start()
        rest = RestCluster(Kubeconfig(server=url))
        ctrl = Controller(rest, resync_period_s=0.5)
        kubelet.start()
        ctrl.run(threadiness=2)
        try:
            pod = Pod()
            pod.metadata.namespace = "default"
            pod.metadata.name = "sayer"
            pod.spec.containers.append(Container(
                name="c", image="img",
                command=[_sys.executable, "-c",
                         "print('hello from the pod'); "
                         "import sys; print('and stderr', file=sys.stderr)"]))
            rest.pods.create(pod)
            wait_for(lambda: rest.pods.get("default", "sayer").status.phase
                     == "Succeeded")
            text = rest.pods.read_log("default", "sayer")
            assert "hello from the pod" in text
            assert "and stderr" in text

            out = io.StringIO()
            with contextlib.redirect_stdout(out):
                rc = cli_main(["-master", url, "logs", "sayer"])
            assert rc == 0 and "hello from the pod" in out.getvalue()

            # CLI delete of a TFJob goes through finalizer cleanup.
            rest.tfjobs.create(mk_job("deljob", (ReplicaType.LOCAL, 1)))
            wait_for(lambda: rest.tfjobs.get("default", "deljob").status.phase
                     == TFJobPhase.SUCCEEDED)
            out = io.StringIO()
            with contextlib.redirect_stdout(out):
                rc = cli_main(["-master", url, "delete", "deljob"])
            assert rc == 0
            def job_gone():
                try:
                    rest.tfjobs.get("default", "deljob")
                    return False
                except NotFound:
                    return True
            wait_for(job_gone)
        finally:
            ctrl.stop()
            kubelet.stop()
            srv.stop()

    def test_logs_without_kubelet_404(self, server, rest):
        from kubeflow_controller_tpu.api.core import Pod

        pod = Pod()
        pod.metadata.namespace = "default"
        pod.metadata.name = "p"
        rest.pods.create(pod)
        with pytest.raises(NotFound):
            rest.pods.read_log("default", "p")
