"""Capacity-plane tests: priority gang queue, preemption, backfill,
mid-admission failure recovery, and warm-pool readmission."""

import sys
import threading
import time

import pytest

from kubeflow_controller_tpu.api.core import (
    PHASE_FAILED,
    PHASE_PENDING,
    PHASE_SUCCEEDED,
    Container,
    Pod,
    PodTemplateSpec,
    ResourceRequirements,
)
from kubeflow_controller_tpu.api.labels import (
    ANNOTATION_ACCELERATOR,
    ANNOTATION_GANG_NAME,
    ANNOTATION_GANG_SIZE,
    ANNOTATION_NUM_SLICES,
    ANNOTATION_PRIORITY_CLASS,
    LABEL_INDEX,
)
from kubeflow_controller_tpu.api.meta import ObjectMeta
from kubeflow_controller_tpu.api.tfjob import (
    ReplicaType,
    TFJob,
    TFJobPhase,
    TFReplicaSpec,
    TPUSpec,
    ValidationError,
    validate_tfjob,
)
from kubeflow_controller_tpu.cluster import (
    Cluster,
    FakeKubelet,
    PhasePolicy,
    TPUInventory,
    TPUSlice,
)
from kubeflow_controller_tpu.cluster.tpu import TPUSliceInventory
from kubeflow_controller_tpu.controller import Controller
from kubeflow_controller_tpu.scheduler import (
    GangScheduler,
    SchedulerPolicy,
    priority_for,
)


def wait_for(fn, timeout=10.0, interval=0.01):
    deadline = time.time() + timeout
    while time.time() < deadline:
        v = fn()
        if v:
            return v
        time.sleep(interval)
    raise AssertionError("condition not met within timeout")


def gang_pod(name, gang, size, index=0, accel="v5e-8", cls="default",
             num_slices=1, ns="default"):
    pod = Pod(metadata=ObjectMeta(name=name, namespace=ns))
    pod.metadata.labels = {LABEL_INDEX: str(index)}
    pod.metadata.annotations = {
        ANNOTATION_GANG_NAME: gang,
        ANNOTATION_GANG_SIZE: str(size),
        ANNOTATION_ACCELERATOR: accel,
        ANNOTATION_NUM_SLICES: str(num_slices),
        ANNOTATION_PRIORITY_CLASS: cls,
    }
    c = Container(name="main")
    c.resources = ResourceRequirements(requests={"google.com/tpu": "4"})
    pod.spec.containers.append(c)
    return pod


def offer_gang(sched, gang, size, cls="default", num_slices=1, accel="v5e-8"):
    """Offer all pods of a gang; returns the list of offer() results with
    the coordinator (index 0) offered LAST so its result decides."""
    out = []
    for i in range(size - 1, -1, -1):
        out.append(sched.offer(gang_pod(f"{gang}-p{i}", gang, size, index=i,
                                        accel=accel, cls=cls,
                                        num_slices=num_slices)))
    return out


def slices(n, accel="v5e-8"):
    return [TPUSlice(f"slice-{i}", accel, num_hosts=2) for i in range(n)]


# ---------------------------------------------------------------------------
# Queue ordering
# ---------------------------------------------------------------------------

class TestPriorityQueue:
    def test_priority_class_values(self):
        assert priority_for("high") > priority_for("default") > priority_for("low")
        assert priority_for("") == priority_for("default")
        assert priority_for("weird") == priority_for("default")

    def test_higher_class_admitted_before_older_lower(self):
        sched = GangScheduler(TPUInventory(slices(1)))
        # Occupy the slice with a started high gang (not preemptible by
        # either waiter).
        assert offer_gang(sched, "run", 1, cls="high")[-1]
        # Low queues first, high second; on release the HIGH gang wins.
        assert not any(offer_gang(sched, "low", 1, cls="low"))
        assert not any(offer_gang(sched, "high", 1, cls="high"))
        sched.release_gang("run")
        assert sched.offer(gang_pod("high-p0", "high", 1, cls="high"))
        assert not sched.offer(gang_pod("low-p0", "low", 1, cls="low"))

    def test_fifo_within_class(self):
        sched = GangScheduler(TPUInventory(slices(1)))
        assert offer_gang(sched, "run", 1, cls="high")[-1]
        assert not any(offer_gang(sched, "a", 1, cls="low"))
        time.sleep(0.01)
        assert not any(offer_gang(sched, "b", 1, cls="low"))
        sched.release_gang("run")
        assert sched.offer(gang_pod("a-p0", "a", 1, cls="low"))
        assert not sched.offer(gang_pod("b-p0", "b", 1, cls="low"))

    def test_incomplete_gang_never_queued(self):
        sched = GangScheduler(TPUInventory(slices(1)))
        assert not sched.offer(gang_pod("g-p1", "g", 2, index=1))
        assert sched.queue_depth() == 0
        assert sched.offer(gang_pod("g-p0", "g", 2, index=0))

    def test_queue_info_reports_position_and_class(self):
        sched = GangScheduler(TPUInventory(slices(1)))
        assert offer_gang(sched, "run", 1, cls="high")[-1]
        offer_gang(sched, "w1", 1, cls="high")
        offer_gang(sched, "w2", 1, cls="low")
        info = sched.queue_info("w2")
        assert info.startswith("GangQueued")
        assert "position 2/2" in info and "class low" in info
        assert "GangQueued" in sched.queue_info("w1")
        sched.pod_started(gang_pod("run-p0", "run", 1, index=0))
        assert sched.queue_info("run") == ""  # admitted & started


# ---------------------------------------------------------------------------
# Preemption
# ---------------------------------------------------------------------------

class TestPreemption:
    def test_high_preempts_started_low(self):
        sched = GangScheduler(TPUInventory(slices(1)))
        evicted = []
        sched.set_evictor(lambda keys, reason: evicted.append((sorted(keys), reason)))
        assert offer_gang(sched, "low", 2, cls="low")[-1]
        assert sched.offer(gang_pod("high-p0", "high", 1, cls="high"))
        assert len(evicted) == 1
        keys, reason = evicted[0]
        assert keys == ["default/low-p0", "default/low-p1"]
        assert "high" in reason and reason.startswith("Preempted")
        assert sched.gang_slices("high") == ["slice-0"]

    def test_no_preemption_within_same_class(self):
        sched = GangScheduler(TPUInventory(slices(1)))
        evicted = []
        sched.set_evictor(lambda keys, reason: evicted.append(keys))
        assert offer_gang(sched, "a", 1, cls="default")[-1]
        assert not sched.offer(gang_pod("b-p0", "b", 1, cls="default"))
        assert not evicted

    def test_preemption_disabled_by_policy(self):
        sched = GangScheduler(TPUInventory(slices(1)),
                              SchedulerPolicy(preemption=False))
        evicted = []
        sched.set_evictor(lambda keys, reason: evicted.append(keys))
        assert offer_gang(sched, "low", 1, cls="low")[-1]
        assert not sched.offer(gang_pod("high-p0", "high", 1, cls="high"))
        assert not evicted

    def test_victims_lowest_class_youngest_first(self):
        sched = GangScheduler(TPUInventory(slices(2)))
        evicted = []
        sched.set_evictor(lambda keys, reason: evicted.append(sorted(keys)))
        assert offer_gang(sched, "old-low", 1, cls="low")[-1]
        time.sleep(0.01)
        assert offer_gang(sched, "young-low", 1, cls="low")[-1]
        # High gang needs ONE slice: the YOUNGEST low gang goes.
        assert sched.offer(gang_pod("h-p0", "h", 1, cls="high"))
        assert evicted == [["default/young-low-p0"]]
        assert sched.gang_slices("old-low")  # survivor untouched

    def test_unstarted_victim_requeued_silently(self):
        # A gang admitted but whose pods never left Pending is requeued at
        # the head of its class instead of being torn down.
        sched = GangScheduler(TPUInventory(slices(1)))
        evicted = []
        sched.set_evictor(lambda keys, reason: evicted.append(keys))
        # Complete the low gang via its WORKER pods only: admitted, but the
        # workers wait for the coordinator, so the gang never starts.
        assert not sched.offer(gang_pod("low-p1", "low", 2, index=1, cls="low"))
        assert not sched.offer(gang_pod("low-p2", "low", 2, index=2, cls="low"))
        assert sched.gang_slices("low") == ["slice-0"]  # admitted, unstarted
        assert not evicted
        assert sched.offer(gang_pod("high-p0", "high", 1, cls="high"))
        assert not evicted  # nothing was killed ...
        assert "position 1/1" in sched.queue_info("low")  # ... just requeued
        sched.release_gang("high")
        assert sched.offer(gang_pod("low-p0", "low", 2, index=0, cls="low"))


# ---------------------------------------------------------------------------
# Backfill + starvation guard
# ---------------------------------------------------------------------------

class TestBackfill:
    def test_small_gang_backfills_blocked_wide_head(self):
        sched = GangScheduler(TPUInventory(slices(2)))
        assert offer_gang(sched, "run", 1, cls="high")[-1]  # 1 of 2 busy
        # Wide default gang needs 2 slices: blocked with 1 free.
        assert not any(offer_gang(sched, "wide", 4, cls="default",
                                  num_slices=2))
        # A later small same-class gang takes the free slice the head
        # cannot use yet.
        assert offer_gang(sched, "small", 1, cls="default")[-1]
        assert "position 1/1" in sched.queue_info("wide")

    def test_starvation_guard_stops_backfill(self):
        sched = GangScheduler(TPUInventory(slices(2)),
                              SchedulerPolicy(starvation_s=0.05))
        assert offer_gang(sched, "run", 1, cls="high")[-1]
        assert not any(offer_gang(sched, "wide", 4, cls="default",
                                  num_slices=2))
        time.sleep(0.08)  # the head is now starving
        assert not offer_gang(sched, "small", 1, cls="default")[-1]
        # Head admitted as soon as capacity suffices.
        sched.release_gang("run")
        assert sched.offer(gang_pod("wide-p0", "wide", 4, index=0,
                                    cls="default", num_slices=2))
        assert sorted(sched.gang_slices("wide")) == ["slice-0", "slice-1"]

    def test_backfill_disabled_by_policy(self):
        sched = GangScheduler(TPUInventory(slices(2)),
                              SchedulerPolicy(backfill=False))
        assert offer_gang(sched, "run", 1, cls="high")[-1]
        assert not any(offer_gang(sched, "wide", 4, cls="default",
                                  num_slices=2))
        assert not offer_gang(sched, "small", 1, cls="default")[-1]


# ---------------------------------------------------------------------------
# Coordinator-first start
# ---------------------------------------------------------------------------

class TestCoordinatorFirst:
    def test_workers_wait_for_coordinator(self):
        sched = GangScheduler(TPUInventory(slices(1)))
        w = gang_pod("g-p1", "g", 2, index=1)
        coord = gang_pod("g-p0", "g", 2, index=0)
        assert not sched.offer(w)       # completes the gang -> admitted,
        assert sched.offer(coord)       # but only the coordinator passes
        assert not sched.offer(w)       # worker still held
        sched.pod_started(coord)
        assert sched.offer(w)           # released once the coordinator ran

    def test_grace_timeout_releases_workers(self):
        sched = GangScheduler(TPUInventory(slices(1)),
                              SchedulerPolicy(coordinator_grace_s=0.05))
        w = gang_pod("g-p1", "g", 2, index=1)
        assert not sched.offer(w)
        assert not sched.offer(gang_pod("g-p0x", "g", 2, index=1))
        time.sleep(0.08)
        assert sched.offer(w)  # missing coordinator must not deadlock


# ---------------------------------------------------------------------------
# Mid-admission slice failure (the satellite regression)
# ---------------------------------------------------------------------------

class TestSliceFailure:
    def test_mid_admission_failure_returns_gang_to_head(self):
        sched = GangScheduler(TPUInventory(slices(2)))
        # Admit (but never start) gang A via its worker pods.
        assert not sched.offer(gang_pod("a-p1", "a", 2, index=1, cls="default"))
        assert not sched.offer(gang_pod("a-p2", "a", 2, index=2, cls="default"))
        bound = sched.gang_slices("a")
        assert len(bound) == 1
        time.sleep(0.01)
        # A second gang queues BEHIND a (other slice still free: admitted).
        assert offer_gang(sched, "b", 1, cls="default")[-1]
        # The bound slice dies mid-admission: nothing to kill, binding not
        # leaked, gang back at the head of the queue.
        assert sched.fail_slice(bound[0]) == []
        assert sched.inventory.gang_on_slice(bound[0]) == ""
        assert "position 1/1" in sched.queue_info("a")
        # Capacity returns: A is first in line and re-binds the healthy
        # slice (the failed one never admits again).
        sched.release_gang("b")
        assert sched.offer(gang_pod("a-p0", "a", 2, index=0, cls="default"))
        assert sched.gang_slices("a") != bound

    def test_started_gang_slice_failure_evicts(self):
        sched = GangScheduler(TPUInventory(slices(1)))
        assert offer_gang(sched, "g", 2, cls="default")[-1]
        failed = sorted(sched.fail_slice("slice-0"))
        assert failed == ["default/g-p0", "default/g-p1"]
        assert sched.queue_info("g") == ""  # entry gone; replacement re-queues

    def test_inventory_admission_vs_fail_slice_race(self):
        """Regression: racing gang admission against fail_slice must never
        leave a slice bound to a gang the inventory no longer tracks, or a
        tracked gang bound to an unhealthy slice."""
        for _ in range(30):
            inv = TPUSliceInventory(slices(2))
            stop = threading.Event()

            def admitter():
                i = 0
                while not stop.is_set():
                    g = f"g{i}"
                    inv.bind_gang(g, "v5e-8", 1,
                                  pods={f"default/{g}-p0": None})
                    inv.release_gang(g)
                    i += 1

            def failer():
                inv.fail_slice("slice-0")

            t = threading.Thread(target=admitter, daemon=True)
            t.start()
            failer()
            stop.set()
            t.join(timeout=5)
            with inv._lock:
                for s in inv.slices.values():
                    if s.bound_gang:
                        assert s.bound_gang in inv._gangs
                        assert s.healthy
                for g in inv._gangs.values():
                    for sn in g.slice_names:
                        assert inv.slices[sn].bound_gang == g.name

    def test_busy_accounting_and_utilization(self):
        inv = TPUInventory(slices(2))
        assert inv.utilization_now() == 0.0
        b0 = inv.busy_seconds()
        assert inv.bind_gang("g", "v5e-8", 1)
        assert inv.utilization_now() == 0.5
        time.sleep(0.05)
        assert inv.busy_seconds() - b0 >= 0.04
        inv.release_gang("g")
        assert inv.utilization_now() == 0.0
        settled = inv.busy_seconds()
        time.sleep(0.03)
        assert inv.busy_seconds() == settled  # released slices stop accruing


# ---------------------------------------------------------------------------
# Stale-queue reaping
# ---------------------------------------------------------------------------

def test_release_idle_gangs_prunes_dead_queue_entries():
    sched = GangScheduler(TPUInventory(slices(1)))
    assert offer_gang(sched, "run", 1, cls="high")[-1]
    offer_gang(sched, "ghost", 1, cls="high")  # queued, then its job dies
    assert sched.queue_depth() == 1
    # Two-scan confirmation, like the inventory's reaper.
    sched.release_idle_gangs({"default/run-p0"})
    assert "ghost" in sched.release_idle_gangs({"default/run-p0"})
    assert sched.queue_depth() == 0
    # The running gang was never touched.
    assert sched.gang_slices("run") == ["slice-0"]


# ---------------------------------------------------------------------------
# API + updater surface
# ---------------------------------------------------------------------------

def mk_tpu_job(name, cls="", num_slices=1, restart="OnFailure"):
    job = TFJob(metadata=ObjectMeta(name=name, namespace="default"))
    job.spec.priority_class_name = cls
    t = PodTemplateSpec()
    t.spec.containers.append(Container(name="tensorflow", image="img"))
    t.spec.restart_policy = restart
    job.spec.tf_replica_specs = [TFReplicaSpec(
        replicas=2 * num_slices, tf_replica_type=ReplicaType.TPU, template=t,
        tpu=TPUSpec(accelerator_type="v5e-8", num_hosts=2,
                    num_slices=num_slices))]
    return job


class TestAPISurface:
    def test_priority_class_validation(self):
        job = mk_tpu_job("j", cls="high")
        validate_tfjob(job)
        job.spec.priority_class_name = "urgent"
        with pytest.raises(ValidationError):
            validate_tfjob(job)

    def test_materialize_stamps_priority_annotation(self):
        from kubeflow_controller_tpu.planner.materialize import make_pod

        job = mk_tpu_job("j", cls="high")
        job.spec.runtime_id = "abc12"
        pod = make_pod(job, job.spec.tf_replica_specs[0], 0)
        assert pod.metadata.annotations[ANNOTATION_PRIORITY_CLASS] == "high"
        job2 = mk_tpu_job("k")
        job2.spec.runtime_id = "abc12"
        pod2 = make_pod(job2, job2.spec.tf_replica_specs[0], 0)
        assert pod2.metadata.annotations[ANNOTATION_PRIORITY_CLASS] == "default"

    def test_updater_surfaces_queue_and_preemption(self):
        from kubeflow_controller_tpu.api.tfjob import TFJobConditionType
        from kubeflow_controller_tpu.updater import compute_status

        job = mk_tpu_job("j", cls="low")
        queued = []
        for i in range(2):
            p = gang_pod(f"j-tpu-{i}", "j-rid", 2, index=i)
            p.status.phase = PHASE_PENDING
            p.status.reason = "GangQueued: position 2/3 (class low); needs 1 x v5e-8 slice(s), 0 free"
            queued.append(p)
        st = compute_status(job, {ReplicaType.TPU: queued})
        assert st.reason.startswith("GangQueued")
        sched_cond = next(c for c in st.conditions
                          if c.type == TFJobConditionType.SCHEDULED)
        assert sched_cond.status == "False"
        assert sched_cond.reason == "GangQueued"
        assert "position 2/3" in sched_cond.message

        preempted = []
        for i in range(2):
            p = gang_pod(f"j-tpu-{i}", "j-rid", 2, index=i)
            p.status.phase = PHASE_FAILED
            p.status.reason = "Preempted: evicted by gang other-xyz (class high)"
            preempted.append(p)
        st2 = compute_status(job, {ReplicaType.TPU: preempted})
        rec = next(c for c in st2.conditions
                   if c.type == TFJobConditionType.RECOVERING)
        assert rec.status == "True"
        assert rec.reason == "GangPreempted"
        assert "other-xyz" in rec.message
        # Queue reason cleared once no pod is queued anymore.
        assert not st2.reason.startswith("GangQueued")


# ---------------------------------------------------------------------------
# End to end: preemption -> events/conditions -> warm readmission
# ---------------------------------------------------------------------------

class TestEndToEnd:
    def _start(self, n_slices=1, policy=None, **kubelet_kw):
        cluster = Cluster()
        inv = TPUInventory(slices(n_slices))
        sched = GangScheduler(inv, policy or SchedulerPolicy())
        kubelet = FakeKubelet(cluster, policy=PhasePolicy(
            run_s=0.5, heartbeat_s=0.04, cold_start_s=0.15,
            warm_start_s=0.01), inventory=sched, **kubelet_kw)
        ctrl = Controller(cluster, inventory=sched, resync_period_s=0.5)
        kubelet.start()
        ctrl.run(threadiness=2)
        return cluster, sched, kubelet, ctrl

    def test_preempt_readmit_warm_with_events(self):
        from kubeflow_controller_tpu.obs.metrics import REGISTRY

        starts = REGISTRY.counter("kctpu_pod_starts_total", "", ("mode",))
        warm0 = starts.labels("warm").value
        cluster, sched, kubelet, ctrl = self._start(n_slices=1)
        try:
            cluster.tfjobs.create(mk_tpu_job("victim", cls="low"))
            wait_for(lambda: cluster.tfjobs.get("default", "victim")
                     .status.phase == TFJobPhase.RUNNING)
            gang = next(iter(kubelet._warm_gangs & {
                g for g in kubelet._warm_gangs if g.startswith("victim")}),
                None)
            assert gang is not None  # cold start marked the gang warm
            cluster.tfjobs.create(mk_tpu_job("preemptor", cls="high"))
            # Victim preempted: Warning event names the preemptor, and the
            # job re-queues (GangQueued) while the high job runs.
            wait_for(lambda: any(
                e.reason == "GangPreempted" and "preemptor" in e.message
                for e in ctrl.recorder.events_for("default", "victim")))
            wait_for(lambda: any(
                e.reason == "GangQueued"
                for e in ctrl.recorder.events_for("default", "victim")))
            # Both jobs finish; the victim's readmission forked warm.
            wait_for(lambda: cluster.tfjobs.get("default", "preemptor")
                     .status.phase == TFJobPhase.SUCCEEDED, timeout=20)
            wait_for(lambda: cluster.tfjobs.get("default", "victim")
                     .status.phase == TFJobPhase.SUCCEEDED, timeout=20)
            assert starts.labels("warm").value - warm0 >= 2
            admitted = [e for e in ctrl.recorder.events_for("default", "preemptor")
                        if e.reason == "GangAdmitted"]
            assert admitted and "slice-0" in admitted[0].message
        finally:
            ctrl.stop()
            kubelet.stop()

    def test_queued_job_status_reason_and_describe_surface(self):
        cluster, sched, kubelet, ctrl = self._start(n_slices=1)
        try:
            cluster.tfjobs.create(mk_tpu_job("first", cls="default"))
            wait_for(lambda: cluster.tfjobs.get("default", "first")
                     .status.phase == TFJobPhase.RUNNING)
            cluster.tfjobs.create(mk_tpu_job("second", cls="default"))
            j = wait_for(lambda: (
                lambda x: x if x.status.reason.startswith("GangQueued") else None
            )(cluster.tfjobs.get("default", "second")))
            assert "position 1/1" in j.status.reason
            wait_for(lambda: cluster.tfjobs.get("default", "second")
                     .status.phase == TFJobPhase.SUCCEEDED, timeout=20)
            # Reason cleared once admitted and run.
            assert not (cluster.tfjobs.get("default", "second")
                        .status.reason.startswith("GangQueued"))
        finally:
            ctrl.stop()
            kubelet.stop()

    def test_warm_start_delay_shrinks_on_readmission(self):
        """The simulated rendezvous/import analog: a gang's first start
        pays cold_start_s, its readmission only warm_start_s."""
        cluster = Cluster()
        inv = TPUInventory(slices(1))
        sched = GangScheduler(inv)
        kubelet = FakeKubelet(cluster, policy=PhasePolicy(
            cold_start_s=0.2, warm_start_s=0.0), inventory=sched)
        pod = gang_pod("g-p0", "g", 1, index=0)
        cluster.pods.create(pod)
        t0 = time.monotonic()
        assert kubelet._start_delay(pod)
        cold = time.monotonic() - t0
        t0 = time.monotonic()
        assert kubelet._start_delay(pod)
        warm = time.monotonic() - t0
        assert cold >= 0.19
        assert warm < cold / 4


@pytest.mark.slow
def test_executed_readmission_reuses_warm_pool(monkeypatch):
    """Kill/readmit an executed gang: both runs fork from the SAME zygote
    (no cold Popen for pod processes — the warm pool survives preemption)."""
    import kubeflow_controller_tpu.cluster.kubelet as kubelet_mod

    cold_popens = []
    real_popen = kubelet_mod.subprocess.Popen

    def counting_popen(*a, **kw):
        cold_popens.append(a)
        return real_popen(*a, **kw)

    monkeypatch.setattr(kubelet_mod.subprocess, "Popen", counting_popen)

    cluster = Cluster()
    inv = TPUInventory(slices(1))
    sched = GangScheduler(inv)
    kubelet = FakeKubelet(cluster, inventory=sched, execute=True,
                          warm_start=True)
    kubelet.start()
    try:
        def run_gang(gen):
            names = []
            for i in range(2):
                pod = gang_pod(f"wg{gen}-p{i}", f"wg{gen}", 2, index=i)
                pod.spec.containers[0].command = [sys.executable, "-m", "platform"]
                cluster.pods.create(pod)
                names.append(pod.metadata.name)
            for n in names:
                wait_for(lambda n=n: cluster.pods.get("default", n)
                         .status.phase == PHASE_SUCCEEDED, timeout=90)
            return names

        run_gang(0)
        zygote_pid = kubelet._pool._zygote.pid
        spawned = kubelet._pool._next_id
        assert spawned >= 2
        # "Readmission": a second gang (the controller would recreate the
        # pods after a preemption) forks from the SAME warm zygote.
        run_gang(1)
        assert kubelet._pool._zygote.pid == zygote_pid
        assert kubelet._pool._next_id >= spawned + 2
        # The only Popen allowed is the zygote itself (the warm pool);
        # pod processes never cold-started.
        pod_popens = [a for a in cold_popens if "zygote" not in str(a)]
        assert not pod_popens
    finally:
        kubelet.stop()
