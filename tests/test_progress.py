"""Progress-plane tests: heartbeat subresource, workload reporter +
kubelet ingestion, stall/straggler detection, job-level rollup, the CLI
surface, and the end-to-end stall demo the acceptance criteria name."""

import json
import os
import time

import pytest

from kubeflow_controller_tpu.api.core import (
    Container,
    PHASE_RUNNING,
    Pod,
    PodProgress,
    PodTemplateSpec,
)
from kubeflow_controller_tpu.api.labels import LABEL_INDEX
from kubeflow_controller_tpu.api.meta import ObjectMeta
from kubeflow_controller_tpu.api.tfjob import (
    ReplicaType,
    TFJob,
    TFJobConditionType,
    TFJobPhase,
    TFReplicaSpec,
)
from kubeflow_controller_tpu.checker import StallPolicy, StallTracker, check_health
from kubeflow_controller_tpu.cluster import Cluster, FakeKubelet, PhasePolicy
from kubeflow_controller_tpu.cluster.apiserver import FakeAPIServer
from kubeflow_controller_tpu.cluster.rest import Kubeconfig, RestCluster
from kubeflow_controller_tpu.cluster.store import NotFound
from kubeflow_controller_tpu.controller import Controller
from kubeflow_controller_tpu.controller.events import EventRecorder
from kubeflow_controller_tpu.obs.metrics import REGISTRY
from kubeflow_controller_tpu.updater.status import compute_progress, compute_status
from kubeflow_controller_tpu.workloads.progress import (
    ENV_POD_NAME,
    ENV_POD_NAMESPACE,
    ENV_PROGRESS_DIR,
    ProgressReporter,
    drop_filename,
)


def mk_template(restart="OnFailure"):
    t = PodTemplateSpec()
    t.spec.containers.append(Container(name="tensorflow", image="img"))
    t.spec.restart_policy = restart
    return t


def mk_job(name, *types_and_replicas):
    job = TFJob(metadata=ObjectMeta(name=name, namespace="default"))
    for typ, n in types_and_replicas:
        job.spec.tf_replica_specs.append(
            TFReplicaSpec(replicas=n, tf_replica_type=typ, template=mk_template()))
    return job


def wait_for(fn, timeout=15.0, interval=0.02):
    deadline = time.time() + timeout
    while time.time() < deadline:
        v = fn()
        if v:
            return v
        time.sleep(interval)
    raise AssertionError("condition not met within timeout")


# ---------------------------------------------------------------------------
# The progress subresource (store + HTTP + REST client)
# ---------------------------------------------------------------------------

class TestProgressSubresource:
    def test_store_update_progress_stamps_and_notifies(self):
        cluster = Cluster()
        pod = Pod(metadata=ObjectMeta(name="p0", namespace="default"))
        cluster.pods.create(pod)
        w = cluster.pods.watch()
        before_rv = cluster.pods.get("default", "p0").metadata.resource_version
        cluster.pods.update_progress(
            "default", "p0", PodProgress(step=7, examples_per_sec=12.5))
        got = cluster.pods.get("default", "p0")
        assert got.status.progress.step == 7
        assert got.status.progress.timestamp > 0  # server-stamped
        assert got.metadata.resource_version != before_rv
        ev = w.next(timeout=2.0)
        assert ev is not None and ev.type == "MODIFIED"
        w.stop()

    def test_store_progress_unknown_pod_404(self):
        cluster = Cluster()
        with pytest.raises(NotFound):
            cluster.pods.update_progress("default", "ghost", PodProgress(step=1))

    def test_rest_update_progress_roundtrip(self):
        srv = FakeAPIServer()
        url = srv.start()
        try:
            rest = RestCluster(Kubeconfig(server=url))
            rest.pods.create(Pod(metadata=ObjectMeta(name="p0", namespace="default")))
            out = rest.pods.update_progress(
                "default", "p0",
                PodProgress(step=42, examples_per_sec=5.0, loss=0.25, phase="fit"))
            assert out.status.progress.step == 42
            assert out.status.progress.phase == "fit"
            assert out.status.progress.timestamp > 0
            # Last-write-wins: a second beat replaces, no Conflict dance.
            out = rest.pods.update_progress("default", "p0", PodProgress(step=43))
            assert out.status.progress.step == 43
        finally:
            srv.stop()


# ---------------------------------------------------------------------------
# Workload reporter (file-drop) + kubelet ingestion
# ---------------------------------------------------------------------------

class TestReporterAndIngestion:
    def test_file_drop_merges_fields(self, tmp_path):
        rep = ProgressReporter(namespace="default", name="p0",
                               drop_dir=str(tmp_path))
        rep.beat(step=5, examples_per_sec=100.0)
        rep.beat(phase="fit")  # step/rate must carry over
        body = json.loads((tmp_path / drop_filename("default", "p0")).read_text())
        assert body == {"step": 5, "examplesPerSec": 100.0, "phase": "fit"}

    def test_disabled_reporter_is_inert(self, tmp_path):
        rep = ProgressReporter.from_env(env={})  # no name/transport
        assert not rep.enabled
        rep.beat(step=1)  # must not raise
        rep = ProgressReporter.from_env(env={
            ENV_POD_NAMESPACE: "ns1", ENV_POD_NAME: "p1",
            ENV_PROGRESS_DIR: str(tmp_path)})
        assert rep.enabled and rep.namespace == "ns1"

    def test_executed_pod_env_contract_roundtrip(self):
        """The whole file-drop path with a REAL subprocess: the kubelet
        injects KCTPU_POD_* / KCTPU_PROGRESS_DIR into the executed pod,
        the workload-side reporter reads them from its env and drops a
        beat, the kubelet ingests it into the progress subresource."""
        import sys

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        cluster = Cluster()
        kubelet = FakeKubelet(cluster, execute=True, warm_start=False)
        pod = Pod(metadata=ObjectMeta(name="beater", namespace="default"))
        pod.spec.restart_policy = "Never"
        pod.spec.containers.append(Container(
            name="c", image="img",
            command=[sys.executable, "-c",
                     "from kubeflow_controller_tpu.workloads.progress import "
                     "ProgressReporter; "
                     "ProgressReporter.from_env().beat(step=9, phase='fit')"],
            working_dir=repo))
        kubelet.start()
        try:
            cluster.pods.create(pod)
            wait_for(lambda: (
                cluster.pods.get("default", "beater").status.progress
                is not None))
            pr = cluster.pods.get("default", "beater").status.progress
            assert (pr.step, pr.phase) == (9, "fit")
        finally:
            kubelet.stop()

    def test_kubelet_ingests_drops_into_subresource(self):
        cluster = Cluster()
        kubelet = FakeKubelet(cluster)
        cluster.pods.create(Pod(metadata=ObjectMeta(name="p0", namespace="default")))
        kubelet.start()
        try:
            rep = ProgressReporter(namespace="default", name="p0",
                                   drop_dir=kubelet._progress_dir)
            rep.beat(step=3, loss=0.5, phase="fit")
            wait_for(lambda: (
                cluster.pods.get("default", "p0").status.progress is not None))
            pr = cluster.pods.get("default", "p0").status.progress
            assert (pr.step, pr.loss, pr.phase) == (3, 0.5, "fit")
            assert pr.timestamp > 0
            # A rewritten drop (same file, new mtime) re-ingests.
            time.sleep(0.02)  # mtime granularity
            rep.beat(step=4)
            wait_for(lambda: (
                cluster.pods.get("default", "p0").status.progress.step == 4))
        finally:
            kubelet.stop()


# ---------------------------------------------------------------------------
# Stall detection (checker)
# ---------------------------------------------------------------------------

class TestStallTracker:
    def test_heartbeat_deadline(self):
        tr = StallTracker(StallPolicy(heartbeat_deadline_s=10, step_deadline_s=0))
        t0 = 1000.0
        assert not tr.observe("k", PodProgress(step=1, timestamp=t0), now=t0 + 5)
        assert tr.observe("k", PodProgress(step=1, timestamp=t0), now=t0 + 11)
        # Fresh beat clears it.
        assert not tr.observe("k", PodProgress(step=1, timestamp=t0 + 11),
                              now=t0 + 12)

    def test_step_deadline_needs_history(self):
        tr = StallTracker(StallPolicy(heartbeat_deadline_s=0, step_deadline_s=10))
        t0 = 1000.0
        # Heartbeats keep arriving but the counter is frozen.
        assert not tr.observe("k", PodProgress(step=5, timestamp=t0), now=t0)
        assert not tr.observe("k", PodProgress(step=5, timestamp=t0 + 5), now=t0 + 5)
        assert tr.observe("k", PodProgress(step=5, timestamp=t0 + 11), now=t0 + 11)
        # Advancement resets the clock...
        assert not tr.observe("k", PodProgress(step=6, timestamp=t0 + 12), now=t0 + 12)
        # ...and a DECREASE (in-place workload restart) does too.
        assert not tr.observe("k", PodProgress(step=0, timestamp=t0 + 23), now=t0 + 23)

    def test_forget_drops_history(self):
        tr = StallTracker(StallPolicy())
        tr.observe("k", PodProgress(step=1, timestamp=1.0), now=1.0)
        assert len(tr) == 1
        tr.forget("k")
        assert len(tr) == 0


def _running_pod(name, idx, step, beat_at):
    p = Pod(metadata=ObjectMeta(name=name, namespace="default",
                                labels={LABEL_INDEX: str(idx)}))
    p.status.phase = PHASE_RUNNING
    p.status.progress = PodProgress(step=step, examples_per_sec=10.0,
                                    loss=1.0 / max(step, 1), timestamp=beat_at)
    return p


class TestHealthAndRollup:
    def test_stalled_replica_degrades_health(self):
        job = mk_job("j", (ReplicaType.WORKER, 2))
        now = 1000.0
        pods = {ReplicaType.WORKER: [
            _running_pod("j-w-0", 0, 10, now - 60),  # silent for a minute
            _running_pod("j-w-1", 1, 12, now - 1),
        ]}
        tr = StallTracker(StallPolicy(heartbeat_deadline_s=30, step_deadline_s=0))
        health = check_health(job, pods, now=now, tracker=tr)
        rh = health.replicas[ReplicaType.WORKER]
        assert rh.stalled_indices == [0]
        assert rh.health.value == "Degraded"
        # Without a tracker the same pods are Healthy (legacy behavior).
        health = check_health(job, pods)
        assert health.replicas[ReplicaType.WORKER].health.value == "Healthy"

    def test_compute_progress_min_max_lag(self):
        job = mk_job("j", (ReplicaType.WORKER, 2))
        pods = {ReplicaType.WORKER: [
            _running_pod("j-w-0", 0, 10, 100.0),
            _running_pod("j-w-1", 1, 14, 101.0),
        ]}
        p = compute_progress(job, pods, {ReplicaType.WORKER: [0]})
        assert (p.step, p.max_step, p.straggler_lag) == (10, 14, 4)
        assert p.examples_per_sec == pytest.approx(20.0)
        assert p.reporting == 2
        assert p.stalled_replicas == ["Worker-0"]
        assert p.stalled
        assert p.last_heartbeat == 101.0
        assert [r.index for r in p.replicas] == [0, 1]

    def test_compute_progress_none_without_beats(self):
        job = mk_job("j", (ReplicaType.WORKER, 1))
        pod = Pod(metadata=ObjectMeta(name="p", namespace="default",
                                      labels={LABEL_INDEX: "0"}))
        pod.status.phase = PHASE_RUNNING
        assert compute_progress(job, {ReplicaType.WORKER: [pod]}) is None

    def test_status_ready_message_names_stalled_index_and_lag(self):
        job = mk_job("j", (ReplicaType.WORKER, 2))
        now = 1000.0
        pods = {ReplicaType.WORKER: [
            _running_pod("j-w-0", 0, 10, now - 60),
            _running_pod("j-w-1", 1, 14, now - 1),
        ]}
        tr = StallTracker(StallPolicy(heartbeat_deadline_s=30, step_deadline_s=0))
        status = compute_status(job, pods, now=now, tracker=tr)
        ready = next(c for c in status.conditions
                     if c.type == TFJobConditionType.READY)
        assert ready.status == "False"
        assert ready.reason == "TrainingStalled"
        assert "stalled [0]" in ready.message
        assert "straggler lag=4 steps" in ready.message
        assert status.progress.stalled_replicas == ["Worker-0"]


# ---------------------------------------------------------------------------
# End-to-end: the acceptance demo
# ---------------------------------------------------------------------------

@pytest.fixture
def rig():
    """Cluster + controller with sub-second stall deadlines + kubelet whose
    simulated workers run long (the test beats pods manually for full
    control over who stalls)."""
    cluster = Cluster()
    kubelet = FakeKubelet(cluster, policy=PhasePolicy(run_s=60.0))
    ctrl = Controller(cluster, resync_period_s=5.0,
                      stall_policy=StallPolicy(heartbeat_deadline_s=0.4,
                                               step_deadline_s=0.0,
                                               check_interval_s=0.1))
    kubelet.start()
    ctrl.run(threadiness=2)
    yield cluster, ctrl, kubelet
    ctrl.stop()
    kubelet.stop()


class TestStallEndToEnd:
    def _pods_by_index(self, cluster):
        return {p.metadata.labels[LABEL_INDEX]: p
                for p in cluster.pods.list("default")}

    def _beat(self, cluster, pod, step):
        cluster.pods.update_progress(
            "default", pod.metadata.name,
            PodProgress(step=step, examples_per_sec=50.0,
                        loss=1.0 / step, phase="fit"))

    def test_stall_detect_and_resume(self, rig):
        cluster, ctrl, kubelet = rig
        cluster.tfjobs.create(mk_job("demo", (ReplicaType.WORKER, 2)))
        wait_for(lambda: len(cluster.pods.list("default")) == 2)
        pods = self._pods_by_index(cluster)

        # Healthy steady state: both replicas beat, job step advances
        # monotonically, nothing is stalled.
        seen_steps = []
        for step in (1, 2, 3):
            for p in pods.values():
                self._beat(cluster, p, step)
            wait_for(lambda s=step: (
                (cluster.tfjobs.get("default", "demo").status.progress or
                 None) is not None
                and cluster.tfjobs.get("default", "demo").status.progress.step == s))
            seen_steps.append(
                cluster.tfjobs.get("default", "demo").status.progress.step)
        assert seen_steps == sorted(seen_steps)  # monotone advance
        assert REGISTRY.gauge(
            "kctpu_job_step", "", ("namespace", "tfjob")).labels(
                "default", "demo").value == 3

        # Replica 0 goes silent; replica 1 keeps beating (and advancing).
        stall_start = time.time()
        for step in range(4, 30):
            self._beat(cluster, pods["1"], step)
            events = ctrl.recorder.events_for("default", "demo")
            if any(e.reason == "TrainingStalled" for e in events):
                break
            time.sleep(0.1)
        events = wait_for(lambda: [
            e for e in ctrl.recorder.events_for("default", "demo")
            if e.reason == "TrainingStalled"])
        # Within (generously) 10x the deadline.
        assert time.time() - stall_start < 4.0
        assert events[0].type == "Warning"
        assert "Worker-0" in events[0].message

        job = cluster.tfjobs.get("default", "demo")
        ready = next(c for c in job.status.conditions
                     if c.type == TFJobConditionType.READY)
        assert ready.status == "False"
        assert "stalled [0]" in ready.message  # names the replica index
        assert job.status.progress.stalled_replicas == ["Worker-0"]
        assert job.status.progress.straggler_lag > 0
        g = REGISTRY.gauge("kctpu_job_stalled", "", ("namespace", "tfjob"))
        assert g.labels("default", "demo").value == 1.0
        # Degraded health from the same inputs `kctpu describe` renders.
        health = check_health(
            job, {ReplicaType.WORKER: list(cluster.pods.list("default"))},
            tracker=ctrl.stall_tracker)
        assert health.overall.value == "Degraded"

        # Heartbeats return: TrainingResumed, gauge drops to 0, READY heals.
        def resumed():
            self._beat(cluster, pods["0"], 40)
            self._beat(cluster, pods["1"], 40)
            return any(e.reason == "TrainingResumed"
                       for e in ctrl.recorder.events_for("default", "demo"))
        wait_for(resumed)
        wait_for(lambda: g.labels("default", "demo").value == 0.0)
        job = cluster.tfjobs.get("default", "demo")
        assert job.status.progress.stalled_replicas == []
        ready = next(c for c in job.status.conditions
                     if c.type == TFJobConditionType.READY)
        assert ready.status == "True"

        # Deletion removes the per-job gauge series (no dead series leak).
        cluster.tfjobs.delete("default", "demo")
        wait_for(lambda: not cluster.pods.list("default"))
        wait_for(lambda: "demo" not in REGISTRY.render().split(
            "kctpu_job_stalled", 1)[-1].split("# HELP")[0])

    def test_simulated_heartbeats_drive_progress(self, rig):
        """PhasePolicy.heartbeat_s: the kubelet's simulated beats alone
        populate job progress (what metrics-smoke and the scale bench use)."""
        cluster, ctrl, kubelet = rig
        kubelet.policy.run_s = 2.0
        kubelet.policy.heartbeat_s = 0.05
        cluster.tfjobs.create(mk_job("sim", (ReplicaType.WORKER, 1)))
        wait_for(lambda: (
            cluster.tfjobs.get("default", "sim").status.progress is not None
            and cluster.tfjobs.get("default", "sim").status.progress.step >= 2))
        p = cluster.tfjobs.get("default", "sim").status.progress
        assert p.examples_per_sec > 0
        assert not p.stalled


# ---------------------------------------------------------------------------
# Satellites: event aggregation, sink recreate, log tail
# ---------------------------------------------------------------------------

class _Obj:
    def __init__(self, ns, name, uid="u1"):
        self.kind = "TFJob"
        self.metadata = ObjectMeta(name=name, namespace=ns, uid=uid)


class TestEventAggregation:
    def test_interleaved_events_still_dedup(self):
        rec = EventRecorder()
        a, b = _Obj("default", "job-a"), _Obj("default", "job-b")
        for _ in range(3):  # a,b,a,b,a,b — the interleaving that broke dedup
            rec.event(a, "Normal", "SuccessfulCreate", "created pod x")
            rec.event(b, "Normal", "SuccessfulCreate", "created pod x")
        events = rec.all_events()
        assert len(events) == 2  # one aggregate per (object, reason, message)
        assert sorted(e.object_key for e in events) == [
            "default/job-a", "default/job-b"]
        assert all(e.count == 3 for e in events)

    def test_first_timestamp_kept_last_bumped(self):
        rec = EventRecorder()
        a = _Obj("default", "job-a")
        rec.event(a, "Normal", "R", "m")
        first = rec.all_events()[0]
        t_first = first.first_timestamp
        time.sleep(0.02)
        rec.event(a, "Normal", "R", "m")
        ev = rec.all_events()[0]
        assert ev.count == 2
        assert ev.first_timestamp == t_first
        assert ev.timestamp > ev.first_timestamp

    def test_distinct_messages_do_not_aggregate(self):
        rec = EventRecorder()
        a = _Obj("default", "job-a")
        rec.event(a, "Normal", "R", "m1")
        rec.event(a, "Normal", "R", "m2")
        assert [e.count for e in rec.all_events()] == [1, 1]

    def test_sink_recreates_deleted_event_object(self):
        """The _write_sink NotFound branch: a GC'd Event API object is
        recreated on the next aggregated emission instead of being lost."""
        cluster = Cluster()
        rec = EventRecorder(sink=cluster.events)
        a = _Obj("default", "job-a")
        rec.event(a, "Normal", "R", "m")
        ev = wait_for(lambda: cluster.events.list("default"))[0]
        assert ev.count == 1
        cluster.events.delete("default", ev.metadata.name)  # "TTL expiry"
        rec.event(a, "Normal", "R", "m")
        recreated = wait_for(lambda: cluster.events.list("default"))[0]
        assert recreated.metadata.name != ev.metadata.name
        assert recreated.count == 1  # fresh object, not a resurrected count
        rec.close()


class TestLogTail:
    def _kubelet_with_logs(self, lines_per_file):
        cluster = Cluster()
        kubelet = FakeKubelet(cluster)
        for i, n in enumerate(lines_per_file):
            f, _ = kubelet._new_log_file("default/p0", f"f{i}")
            f.write(b"".join(f"file{i} line{j}\n".encode() for j in range(n)))
            f.close()
        return cluster, kubelet

    def test_tail_within_last_file(self):
        _, kubelet = self._kubelet_with_logs([5, 5])
        out = kubelet.logs("default", "p0", tail_lines=2).decode()
        assert out == "file1 line3\nfile1 line4\n"

    def test_tail_spans_files_and_caps_at_total(self):
        _, kubelet = self._kubelet_with_logs([2, 3])
        out = kubelet.logs("default", "p0", tail_lines=4).decode()
        assert out == ("file0 line1\nfile1 line0\nfile1 line1\nfile1 line2\n")
        assert kubelet.logs("default", "p0", tail_lines=100).decode().count(
            "\n") == 5
        # tail=0 keeps the full-read behavior.
        assert kubelet.logs("default", "p0").decode().count("\n") == 5

    def test_rest_tail_param_plumbs_to_kubelet(self):
        cluster, kubelet = self._kubelet_with_logs([5])
        cluster.pods.create(Pod(metadata=ObjectMeta(name="p0",
                                                    namespace="default")))
        srv = FakeAPIServer(cluster.store, kubelet=kubelet)
        url = srv.start()
        try:
            rest = RestCluster(Kubeconfig(server=url))
            out = rest.pods.read_log("default", "p0", tail_lines=2)
            assert out == "file0 line3\nfile0 line4\n"
            assert rest.pods.read_log("default", "p0").count("\n") == 5
        finally:
            srv.stop()


# ---------------------------------------------------------------------------
# CLI surface
# ---------------------------------------------------------------------------

class TestCLIProgress:
    @pytest.fixture
    def served_job(self):
        from kubeflow_controller_tpu.api.tfjob import (
            JobProgress,
            ReplicaProgress,
        )

        cluster = Cluster()
        srv = FakeAPIServer(cluster.store)
        url = srv.start()
        job = mk_job("trainer", (ReplicaType.WORKER, 2))
        cluster.tfjobs.create(job)
        j = cluster.tfjobs.get("default", "trainer")
        j.status.phase = TFJobPhase.RUNNING
        j.status.progress = JobProgress(
            step=10, max_step=14, straggler_lag=4, examples_per_sec=123.5,
            loss=0.25, reporting=2, stalled_replicas=["Worker-0"],
            last_heartbeat=time.time() - 5,
            replicas=[
                ReplicaProgress(type=ReplicaType.WORKER, index=0, step=10,
                                examples_per_sec=60.0, loss=0.3, phase="fit",
                                last_heartbeat=time.time() - 65, stalled=True),
                ReplicaProgress(type=ReplicaType.WORKER, index=1, step=14,
                                examples_per_sec=63.5, loss=0.2, phase="fit",
                                last_heartbeat=time.time() - 5),
            ])
        cluster.tfjobs.update_status(j)
        yield url
        srv.stop()

    def test_get_shows_step_and_rate(self, served_job, capsys):
        from kubeflow_controller_tpu.cli.main import main

        assert main(["-master", served_job, "get"]) == 0
        out = capsys.readouterr().out
        assert "STEP" in out and "RATE" in out
        assert "10..14!" in out  # min..max, ! = stalled
        assert "123.5" in out

    def test_top_lists_progress(self, served_job, capsys):
        from kubeflow_controller_tpu.cli.main import main

        assert main(["-master", served_job, "top"]) == 0
        out = capsys.readouterr().out
        assert "STALLED" in out and "Worker-0" in out
        assert "LAG" in out
        lines = [ln for ln in out.splitlines() if "trainer" in ln]
        assert lines and "123.5" in lines[0]

    def test_describe_progress_section_and_event_age(self, served_job, capsys):
        from kubeflow_controller_tpu.cli.main import main
        from kubeflow_controller_tpu.api.core import EventObject, ObjectReference

        # Plant an Event object with a last-seen 90 s ago.
        rest = RestCluster(Kubeconfig(server=served_job))
        ev = EventObject()
        ev.metadata.generate_name = "trainer."
        ev.metadata.namespace = "default"
        ev.involved_object = ObjectReference(kind="TFJob", namespace="default",
                                             name="trainer")
        ev.reason = "SuccessfulCreate"
        ev.message = "created pod trainer-worker-0"
        ev.first_timestamp = time.time() - 300
        ev.last_timestamp = time.time() - 90
        rest.events.create(ev)

        assert main(["-master", served_job, "describe", "trainer"]) == 0
        out = capsys.readouterr().out
        assert "Progress:  step=10 (max 14, lag 4)" in out
        assert "STALLED ['Worker-0']" in out
        assert "Worker-1: step=14" in out
        assert "beat 1m5s ago" in out  # per-replica heartbeat age
        assert "1m30s" in out          # event age = last-seen, not first