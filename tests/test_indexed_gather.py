"""Indexed reconcile hot path: informer indices, the indexed gather in
Helper (with its live full-LIST adoption fallback), the status CAS fast
path, locked metrics counters, and the terminal-resync skip.

The load-bearing contract (ISSUE 2): a steady-state sync of a job with no
orphans performs ZERO full-namespace LISTs — `kctpu_gather_full_lists_total`
stays flat across the sync — while RefManager adopt/release semantics are
preserved bit-for-bit (orphans are still adopted, via the fallback).
"""

import threading
import time

import pytest

from kubeflow_controller_tpu.api.core import Container, Pod, PodTemplateSpec
from kubeflow_controller_tpu.api.labels import (
    LABEL_DOMAIN,
    LABEL_JOB_NAME,
    LABEL_JOB_TYPE,
    LABEL_RUNTIME_ID,
    job_selector,
    job_selector_index_key,
    job_selector_index_keys,
)
from kubeflow_controller_tpu.api.meta import ObjectMeta, key_of
from kubeflow_controller_tpu.api.tfjob import (
    ReplicaType,
    TFJob,
    TFJobPhase,
    TFReplicaSpec,
)
from kubeflow_controller_tpu.cluster import Cluster, FakeKubelet, PhasePolicy
from kubeflow_controller_tpu.controller import Controller, ReconcileMetrics, SharedInformer
from kubeflow_controller_tpu.controller.helper import (
    JOB_SELECTOR_INDEX,
    OWNER_UID_INDEX,
    register_gather_indexers,
)


def wait_for(fn, timeout=10.0, interval=0.02):
    deadline = time.time() + timeout
    while time.time() < deadline:
        v = fn()
        if v:
            return v
        time.sleep(interval)
    raise AssertionError("condition not met within timeout")


def mk_pod(name, ns="ns", labels=None):
    p = Pod(metadata=ObjectMeta(name=name, namespace=ns, labels=labels or {}))
    return p


def mk_job(name, *types_and_replicas):
    job = TFJob(metadata=ObjectMeta(name=name, namespace="default"))
    for typ, n in types_and_replicas:
        t = PodTemplateSpec()
        t.spec.containers.append(Container(name="tensorflow", image="img"))
        t.spec.restart_policy = "OnFailure"
        job.spec.tf_replica_specs.append(
            TFReplicaSpec(replicas=n, tf_replica_type=typ, template=t))
    return job


# ---- informer indices ----


def test_by_index_maintained_across_add_update_delete():
    c = Cluster()
    inf = SharedInformer(c.pods, resync_period_s=0, name="pods")
    inf.add_indexer("by_app", lambda o: [o.metadata.labels["app"]]
                    if "app" in o.metadata.labels else [])
    inf.start()
    try:
        c.pods.create(mk_pod("a", labels={"app": "x"}))
        c.pods.create(mk_pod("b", labels={"app": "x"}))
        c.pods.create(mk_pod("c", labels={"app": "y"}))
        wait_for(lambda: len(inf.by_index("by_app", "x")) == 2)
        assert {p.metadata.name for p in inf.by_index("by_app", "y")} == {"c"}
        # Relabel: the object must move buckets, not duplicate.
        c.pods.patch_meta("ns", "b", lambda m: m.labels.update({"app": "y"}))
        wait_for(lambda: len(inf.by_index("by_app", "y")) == 2)
        assert {p.metadata.name for p in inf.by_index("by_app", "x")} == {"a"}
        c.pods.delete("ns", "c")
        wait_for(lambda: {p.metadata.name for p in inf.by_index("by_app", "y")}
                 == {"b"})
        # Unknown key: empty, not KeyError.
        assert inf.by_index("by_app", "nope") == []
    finally:
        inf.stop()


def test_indexer_registered_late_backfills_from_cache():
    c = Cluster()
    c.pods.create(mk_pod("pre", labels={"app": "x"}))
    inf = SharedInformer(c.pods, resync_period_s=0, name="pods")
    inf.start()
    try:
        inf.add_indexer("by_app", lambda o: [o.metadata.labels.get("app", "")])
        assert {p.metadata.name for p in inf.by_index("by_app", "x")} == {"pre"}
    finally:
        inf.stop()


def test_index_consistent_under_concurrent_mutation():
    """Hammer the store from several writer threads while the informer
    applies events; at quiescence every index bucket must exactly match a
    ground-truth scan of the cache."""
    c = Cluster()
    inf = SharedInformer(c.pods, resync_period_s=0, name="pods")
    inf.add_indexer("by_app", lambda o: [o.metadata.labels["app"]]
                    if "app" in o.metadata.labels else [])
    inf.start()
    apps = ("red", "green", "blue")

    def writer(wid):
        for i in range(30):
            name = f"w{wid}-p{i}"
            c.pods.create(mk_pod(name, labels={"app": apps[i % 3]}))
            if i % 3 == 0:
                c.pods.patch_meta(
                    "ns", name,
                    lambda m: m.labels.update({"app": apps[(i + 1) % 3]}))
            if i % 5 == 0:
                c.pods.delete("ns", name)

    threads = [threading.Thread(target=writer, args=(w,)) for w in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    try:
        # Quiesce: cache caught up with the store.
        expected = {key_of(p.metadata) for p in c.pods.list()}
        wait_for(lambda: {key_of(p.metadata) for p in inf.list()} == expected)
        for app in apps:
            truth = {key_of(p.metadata) for p in inf.list()
                     if p.metadata.labels.get("app") == app}
            got = {key_of(p.metadata) for p in inf.by_index("by_app", app)}
            assert got == truth, f"index diverged for {app}"
    finally:
        inf.stop()


class _GappyWatcher:
    """Watcher wrapper that can swallow events (a watch gap) and then
    report it via the ``gaps`` counter, as the REST transport does."""

    def __init__(self, inner):
        self._inner = inner
        self.gaps = 0
        self.dropping = False

    def next(self, timeout=None):
        ev = self._inner.next(timeout)
        if self.dropping:
            return None  # event lost in the gap
        return ev

    def stop(self):
        self._inner.stop()


class _GappyClient:
    def __init__(self, client):
        self._client = client
        self.kind = client.kind
        self.watcher = None

    def list(self, *a, **kw):
        return self._client.list(*a, **kw)

    def watch(self, *a, **kw):
        self.watcher = _GappyWatcher(self._client.watch(*a, **kw))
        return self.watcher


def test_index_consistent_across_watch_gap_relist():
    c = Cluster()
    gappy = _GappyClient(c.pods)
    inf = SharedInformer(gappy, resync_period_s=0, name="pods")
    inf.add_indexer("by_app", lambda o: [o.metadata.labels.get("app", "")])
    c.pods.create(mk_pod("survivor", labels={"app": "x"}))
    c.pods.create(mk_pod("doomed", labels={"app": "x"}))
    inf.start()
    try:
        wait_for(lambda: len(inf.by_index("by_app", "x")) == 2)
        # Open the gap: everything in it is lost to the watch stream.
        gappy.watcher.dropping = True
        c.pods.delete("ns", "doomed")
        c.pods.create(mk_pod("newcomer", labels={"app": "x"}))
        c.pods.patch_meta("ns", "survivor",
                          lambda m: m.labels.update({"app": "y"}))
        time.sleep(0.1)
        gappy.watcher.dropping = False
        gappy.watcher.gaps += 1  # reconnect signal -> informer re-lists
        wait_for(lambda: {p.metadata.name
                          for p in inf.by_index("by_app", "x")} == {"newcomer"})
        assert {p.metadata.name for p in inf.by_index("by_app", "y")} == {"survivor"}
        assert inf.get("ns", "doomed") is None
    finally:
        inf.stop()


def test_job_selector_index_keys_roundtrip():
    labels = job_selector("jobx", "rt123")
    assert job_selector_index_keys(labels) == [job_selector_index_key("jobx", "rt123")]
    assert job_selector_index_keys({LABEL_DOMAIN: "true"}) == []
    # The 4-label per-type selector lands in the same (job-level) bucket.
    labels4 = dict(labels, **{LABEL_JOB_TYPE: "PS"})
    assert job_selector_index_keys(labels4) == job_selector_index_keys(labels)


# ---- the indexed gather through a live controller ----


@pytest.fixture
def rig():
    cluster = Cluster()
    kubelet = FakeKubelet(cluster, policy=PhasePolicy(run_s=0.05))
    ctrl = Controller(cluster, resync_period_s=0.5)
    kubelet.start()
    ctrl.run(threadiness=2)
    yield cluster, ctrl, kubelet
    ctrl.stop()
    kubelet.stop()


def test_steady_state_sync_zero_full_lists(rig):
    """THE acceptance gate: a sync of a settled job with no orphans reads
    only the informer indices — kctpu_gather_full_lists_total is unchanged
    across the sync."""
    cluster, ctrl, _ = rig
    cluster.tfjobs.create(mk_job("steady", (ReplicaType.PS, 2)))  # runs forever
    wait_for(lambda: len(cluster.pods.list("default")) == 2)
    wait_for(lambda: cluster.tfjobs.get("default", "steady").status.phase
             == TFJobPhase.RUNNING)
    # Let in-flight syncs drain, then drive one more sync by hand.
    time.sleep(0.3)
    before = ctrl.metrics.snapshot()
    ctrl.queue.add("default/steady")
    wait_for(lambda: ctrl.metrics.snapshot()["syncs"] > before["syncs"])
    after = ctrl.metrics.snapshot()
    assert after["gather_full_lists"] == before["gather_full_lists"]
    assert after["gather_indexed"] > before["gather_indexed"]
    assert after["sync_errors"] == before["sync_errors"]


def test_orphan_adopted_via_label_index_fallback(rig):
    """An orphan only reachable through the selector index still gets
    adopted — the indexed path detects it and falls back to the live full
    LIST so adoption runs on fresh state."""
    cluster, ctrl, _ = rig
    cluster.tfjobs.create(mk_job("adopt", (ReplicaType.PS, 1)))
    wait_for(lambda: len(cluster.pods.list("default")) == 1)
    job = cluster.tfjobs.get("default", "adopt")
    full_before = ctrl.metrics.snapshot()["gather_full_lists"]
    # Orphan matching the job selector; a replica type outside the spec so
    # the planner never schedules it for deletion.
    orphan = mk_pod("stray", ns="default", labels={
        LABEL_DOMAIN: "true",
        LABEL_JOB_NAME: "adopt",
        LABEL_RUNTIME_ID: job.spec.runtime_id,
        LABEL_JOB_TYPE: "Worker",
    })
    cluster.pods.create(orphan)
    # The resync backstop re-queues the (non-terminal) job; adoption stamps
    # our controller ownerRef on the stray pod.
    wait_for(lambda: any(
        r.uid == job.metadata.uid and r.controller
        for r in cluster.pods.get("default", "stray").metadata.owner_references
    ))
    assert ctrl.metrics.snapshot()["gather_full_lists"] > full_before
    # With the orphan claimed, gathers return to the indexed path.
    settled = ctrl.metrics.snapshot()
    ctrl.queue.add("default/adopt")
    wait_for(lambda: ctrl.metrics.snapshot()["syncs"] > settled["syncs"])
    assert (ctrl.metrics.snapshot()["gather_full_lists"]
            == settled["gather_full_lists"])


def test_release_happens_on_cached_path(rig):
    """Owned-but-selector-mismatched children are released without a full
    LIST (release is found via the owner-UID index)."""
    cluster, ctrl, _ = rig
    cluster.tfjobs.create(mk_job("rel", (ReplicaType.PS, 1)))
    wait_for(lambda: len(cluster.pods.list("default")) == 1)
    job = cluster.tfjobs.get("default", "rel")
    pod_name = cluster.pods.list("default")[0].metadata.name
    full_before = ctrl.metrics.snapshot()["gather_full_lists"]
    # Break the selector match: the pod stays owned but mismatched.
    cluster.pods.patch_meta("default", pod_name,
                            lambda m: m.labels.pop(LABEL_RUNTIME_ID))
    wait_for(lambda: cluster.pods.get("default", pod_name)
             .metadata.owner_references == [])
    assert ctrl.metrics.snapshot()["gather_full_lists"] == full_before
    # The controller replaces the released replica.
    wait_for(lambda: any(
        p.metadata.name != pod_name
        and p.metadata.labels.get(LABEL_RUNTIME_ID) == job.spec.runtime_id
        for p in cluster.pods.list("default")))


# ---- status CAS fast path ----


def test_status_update_cas_skips_get():
    cluster = Cluster()
    ctrl = Controller(cluster, resync_period_s=0)  # never run()
    try:
        job = cluster.tfjobs.create(mk_job("cas", (ReplicaType.PS, 1)))
        gets = []
        orig_get = cluster.tfjobs.get
        cluster.tfjobs.get = lambda ns, n: (gets.append(n), orig_get(ns, n))[1]
        new_status = cluster.tfjobs.get("default", "cas").status
        gets.clear()
        new_status.phase = TFJobPhase.RUNNING
        # Fresh RV in hand: the CAS lands with zero GETs.
        ctrl._update_status_inner(orig_get("default", "cas"), new_status)
        assert gets == []
        assert orig_get("default", "cas").status.phase == TFJobPhase.RUNNING
        assert ctrl.metrics.status_updates == 1
        # Stale RV: falls back to the GET+retry loop, still lands.
        stale = orig_get("default", "cas")
        bump = orig_get("default", "cas")
        cluster.tfjobs.update_status(bump)  # bump RV so `stale` conflicts
        new_status.phase = TFJobPhase.SUCCEEDED
        gets.clear()
        ctrl._update_status_inner(stale, new_status)
        assert gets == ["cas"]  # exactly one fallback GET
        assert orig_get("default", "cas").status.phase == TFJobPhase.SUCCEEDED
        assert ctrl.metrics.status_updates == 2
    finally:
        ctrl.stop()


# ---- satellite: locked counters ----


def test_reconcile_metrics_counters_thread_safe():
    m = ReconcileMetrics()

    def hammer():
        for _ in range(2000):
            m.inc_creates()
            m.inc_deletes()
            m.inc_status_updates()
            m.inc_gather_indexed()
            m.inc_gather_full_lists()

    threads = [threading.Thread(target=hammer) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    snap = m.snapshot()
    assert snap["creates"] == 16000
    assert snap["deletes"] == 16000
    assert snap["status_updates"] == 16000
    assert snap["gather_indexed"] == 16000
    assert snap["gather_full_lists"] == 16000


# ---- satellite: terminal jobs skip the resync churn ----


def test_terminal_job_resync_not_enqueued():
    cluster = Cluster()
    ctrl = Controller(cluster, resync_period_s=0)  # handlers wired, not run
    try:
        job = mk_job("done", (ReplicaType.WORKER, 1))
        job.metadata.resource_version = "7"
        job.status.phase = TFJobPhase.SUCCEEDED
        # Same-RV resync of a settled terminal job: dropped.
        ctrl._on_tfjob_update(job, job)
        assert ctrl.queue.get(timeout=0.05) is None
        # Real edge (RV changed): enqueued even when terminal.
        import copy
        newer = copy.deepcopy(job)
        newer.metadata.resource_version = "8"
        ctrl._on_tfjob_update(job, newer)
        assert ctrl.queue.get(timeout=1.0) == "default/done"
        ctrl.queue.done("default/done")
        # Same-RV resync of a NON-terminal job: still the level-trigger.
        job.status.phase = TFJobPhase.RUNNING
        ctrl._on_tfjob_update(job, job)
        assert ctrl.queue.get(timeout=1.0) == "default/done"
        ctrl.queue.done("default/done")
        # Terminal but deleting: resync must still drive finalization.
        job.status.phase = TFJobPhase.FAILED
        job.metadata.deletion_timestamp = time.time()
        ctrl._on_tfjob_update(job, job)
        assert ctrl.queue.get(timeout=1.0) == "default/done"
        ctrl.queue.done("default/done")
    finally:
        ctrl.stop()


def test_terminal_job_stops_syncing_after_recycle(rig):
    """End-to-end: once a job is Succeeded and recycled, resyncs stop
    producing syncs for it — the sync count goes flat."""
    cluster, ctrl, _ = rig
    cluster.tfjobs.create(mk_job("flat", (ReplicaType.WORKER, 1)))
    wait_for(lambda: cluster.tfjobs.get("default", "flat").status.phase
             == TFJobPhase.SUCCEEDED)
    wait_for(lambda: cluster.services.list("default") == [])  # recycled
    time.sleep(0.6)  # drain the recycle tail (resync period is 0.5s)
    s0 = ctrl.metrics.snapshot()["syncs"]
    time.sleep(1.2)  # > 2 resync periods
    assert ctrl.metrics.snapshot()["syncs"] == s0
