"""KV-cache decode correctness: the cached path must match the dense path."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_controller_tpu.models import LlamaConfig, llama_forward, llama_init
from kubeflow_controller_tpu.models.generate import (
    forward_with_cache,
    generate,
    init_cache,
)
from kubeflow_controller_tpu.parallel.compat import set_mesh as compat_set_mesh


def setup():
    cfg = LlamaConfig.tiny()
    params = llama_init(jax.random.PRNGKey(0), cfg)
    return cfg, params


@pytest.mark.slow
class TestKVCache:
    def test_prefill_matches_dense_forward(self):
        cfg, params = setup()
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab_size)
        dense = llama_forward(params, tokens, cfg)
        cache = init_cache(cfg, 2, 32)
        cached, _ = forward_with_cache(params, tokens, cache, 0, cfg)
        np.testing.assert_allclose(np.asarray(cached), np.asarray(dense),
                                   atol=1e-4, rtol=1e-4)

    def test_incremental_decode_matches_dense(self):
        """Feeding tokens one at a time through the cache must reproduce the
        dense forward's last-position logits at every step."""
        cfg, params = setup()
        T = 10
        tokens = jax.random.randint(jax.random.PRNGKey(2), (1, T), 0, cfg.vocab_size)
        cache = init_cache(cfg, 1, T)
        for t in range(T):
            step_logits, cache = forward_with_cache(
                params, tokens[:, t:t + 1], cache, t, cfg)
            dense = llama_forward(params, tokens[:, :t + 1], cfg)
            np.testing.assert_allclose(
                np.asarray(step_logits[0, -1]), np.asarray(dense[0, -1]),
                atol=2e-4, rtol=2e-4,
            )

    def test_greedy_generate_matches_dense_argmax_loop(self):
        cfg, params = setup()
        prompt = jax.random.randint(jax.random.PRNGKey(3), (1, 5), 0, cfg.vocab_size)
        out = generate(params, prompt, cfg, max_new_tokens=6)
        assert out.shape == (1, 11)
        np.testing.assert_array_equal(np.asarray(out[:, :5]), np.asarray(prompt))
        # Oracle: iterative dense forward + argmax.
        cur = prompt
        for _ in range(6):
            logits = llama_forward(params, cur, cfg)
            nxt = jnp.argmax(logits[:, -1], axis=-1)
            cur = jnp.concatenate([cur, nxt[:, None]], axis=1)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(cur))

    def test_blocked_cache_reads_match_dense_path(self):
        """The length-masked blocked read (_cache_attention_blocked) must
        reproduce the full-S masked read at every step, including prefill
        spanning several blocks and steps mid-block."""
        cfg, params = setup()
        T = 11
        tokens = jax.random.randint(jax.random.PRNGKey(4), (2, T), 0, cfg.vocab_size)
        S = 16  # 4 blocks of 4
        cache_b = init_cache(cfg, 2, S)
        cache_d = init_cache(cfg, 2, S)
        # Prefill 6 tokens (crosses a block edge), then single-token steps.
        lb, cache_b = forward_with_cache(params, tokens[:, :6], cache_b, 0,
                                         cfg, kv_block=4)
        ld, cache_d = forward_with_cache(params, tokens[:, :6], cache_d, 0,
                                         cfg, kv_block=S)  # S == block -> dense
        np.testing.assert_allclose(np.asarray(lb), np.asarray(ld),
                                   atol=2e-4, rtol=2e-4)
        for t in range(6, T):
            lb, cache_b = forward_with_cache(params, tokens[:, t:t + 1],
                                             cache_b, t, cfg, kv_block=4)
            ld, cache_d = forward_with_cache(params, tokens[:, t:t + 1],
                                             cache_d, t, cfg, kv_block=S)
            np.testing.assert_allclose(np.asarray(lb), np.asarray(ld),
                                       atol=2e-4, rtol=2e-4)

    def test_blocked_generate_matches_default(self):
        cfg, params = setup()
        prompt = jax.random.randint(jax.random.PRNGKey(8), (2, 5), 0,
                                    cfg.vocab_size)
        ref = generate(params, prompt, cfg, max_new_tokens=7)
        out = generate(params, prompt, cfg, max_new_tokens=7, kv_block=4)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))

    def test_quantized_cache_blocked_matches_quantized_dense(self):
        """int8 KV: the blocked read must agree tightly with the dense read
        over the SAME quantized cache (identical quantized values, two read
        paths)."""
        cfg, params = setup()
        tokens = jax.random.randint(jax.random.PRNGKey(5), (2, 10), 0,
                                    cfg.vocab_size)
        cb = init_cache(cfg, 2, 16, quantize=True)
        cd = init_cache(cfg, 2, 16, quantize=True)
        lb, cb = forward_with_cache(params, tokens[:, :6], cb, 0, cfg, kv_block=4)
        ld, cd = forward_with_cache(params, tokens[:, :6], cd, 0, cfg, kv_block=16)
        np.testing.assert_allclose(np.asarray(lb), np.asarray(ld),
                                   atol=3e-4, rtol=3e-4)
        for t in range(6, 10):
            lb, cb = forward_with_cache(params, tokens[:, t:t + 1], cb, t,
                                        cfg, kv_block=4)
            ld, cd = forward_with_cache(params, tokens[:, t:t + 1], cd, t,
                                        cfg, kv_block=16)
            np.testing.assert_allclose(np.asarray(lb), np.asarray(ld),
                                       atol=3e-4, rtol=3e-4)

    def test_quantized_cache_tracks_fp_cache(self):
        """int8-per-row quantization is lossy but must stay CLOSE to the
        fp cache's logits (loose tolerance — the trade decode makes for
        halved cache bandwidth)."""
        cfg, params = setup()
        tokens = jax.random.randint(jax.random.PRNGKey(6), (2, 8), 0,
                                    cfg.vocab_size)
        cq = init_cache(cfg, 2, 8, quantize=True)
        cf = init_cache(cfg, 2, 8)
        lq, _ = forward_with_cache(params, tokens, cq, 0, cfg)
        lf, _ = forward_with_cache(params, tokens, cf, 0, cfg)
        lq, lf = np.asarray(lq), np.asarray(lf)
        assert np.max(np.abs(lq - lf)) < 0.25, np.max(np.abs(lq - lf))
        # And the ranking the decode actually consumes survives: argmax
        # agrees for the overwhelming majority of positions.
        agree = np.mean(lq.argmax(-1) == lf.argmax(-1))
        assert agree > 0.9, agree

    def test_quantized_generate_runs_and_is_deterministic(self):
        cfg, params = setup()
        prompt = jnp.zeros((2, 3), jnp.int32)
        a = generate(params, prompt, cfg, max_new_tokens=5, kv_quant=True)
        b = generate(params, prompt, cfg, max_new_tokens=5, kv_quant=True)
        assert a.shape == (2, 8)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_no_per_token_cache_copies_in_compiled_decode(self):
        """Regression lock for the cache-as-scan-carry fix: threading the
        KV caches through the layer scan as xs->ys made XLA COPY both
        [L,B,S,kvH,D] caches once per generated token (~4GB/step at real
        sizes).  The carry form must compile with at most the one-time
        zero-init copies — none proportional to generated tokens."""
        cfg, params = setup()
        prompt = jnp.zeros((2, 8), jnp.int32)
        fn = jax.jit(lambda p, t: generate(p, t, cfg, max_new_tokens=24,
                                           kv_block=16))
        txt = fn.lower(params, prompt).compile().as_text()
        # Derive the cache-shape signature from init_cache itself so config
        # drift cannot silently detach the grep from the real cache.
        cache_shape = init_cache(cfg, 2, 32)["k"].shape  # 8+24 rounds to 32
        shape_sig = ",".join(map(str, cache_shape))
        flat = [ln.replace(" ", "") for ln in txt.splitlines()]
        # Positive control: the cache shape must appear in the HLO at all —
        # otherwise the copy-grep below would pass vacuously.
        assert any(shape_sig in ln for ln in flat), shape_sig
        copies = [ln for ln in flat if "copy(" in ln and shape_sig in ln]
        # Zero-init copies (of broadcasts) are fine; copies of loop tuple
        # elements are the per-token re-stacking this test forbids.
        loop_copies = [ln for ln in copies if "broadcast" not in ln]
        assert not loop_copies, "\n".join(ln[:120] for ln in loop_copies)

    def test_sampled_generate_shape_and_determinism(self):
        cfg, params = setup()
        prompt = jnp.zeros((2, 3), jnp.int32)
        a = generate(params, prompt, cfg, max_new_tokens=4, temperature=0.8,
                     top_k=20, key=jax.random.PRNGKey(7))
        b = generate(params, prompt, cfg, max_new_tokens=4, temperature=0.8,
                     top_k=20, key=jax.random.PRNGKey(7))
        assert a.shape == (2, 7)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.slow
class TestShardedDecode:
    """tp/dp-sharded decode on the 8-device mesh vs the unsharded paths
    (VERDICT round-1 item 5: sharded inference is table stakes)."""

    def _sharded(self, cfg, params, mesh):
        from jax.sharding import NamedSharding

        from kubeflow_controller_tpu.models.llama import llama_param_pspecs

        pspecs = llama_param_pspecs(cfg)
        return jax.tree.map(
            lambda a, s: jax.device_put(a, NamedSharding(mesh, s)),
            params, pspecs)

    def test_sharded_prefill_matches_dense(self):
        from kubeflow_controller_tpu.parallel import MeshSpec, build_mesh

        cfg, params = setup()
        tokens = jax.random.randint(jax.random.PRNGKey(5), (4, 16), 0,
                                    cfg.vocab_size)
        dense = llama_forward(params, tokens, cfg)
        mesh = build_mesh(MeshSpec(dp=2, tp=2, fsdp=2))
        sharded = self._sharded(cfg, params, mesh)
        with compat_set_mesh(mesh):
            def prefill(p, t):
                cache = init_cache(cfg, 4, 16)
                return forward_with_cache(p, t, cache, 0, cfg)[0]

            out = jax.jit(prefill)(sharded, tokens)
        np.testing.assert_allclose(np.asarray(out), np.asarray(dense),
                                   atol=2e-4, rtol=2e-4)

    def test_sharded_greedy_generate_matches_unsharded(self):
        from kubeflow_controller_tpu.parallel import MeshSpec, build_mesh

        cfg, params = setup()
        prompt = jax.random.randint(jax.random.PRNGKey(6), (4, 8), 0,
                                    cfg.vocab_size)
        ref = generate(params, prompt, cfg, max_new_tokens=6)
        mesh = build_mesh(MeshSpec(dp=2, tp=2, fsdp=2))
        sharded = self._sharded(cfg, params, mesh)
        with compat_set_mesh(mesh):
            out = jax.jit(
                lambda p, t: generate(p, t, cfg, max_new_tokens=6)
            )(sharded, prompt)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))

    def test_sharded_blocked_decode_matches_unsharded(self):
        """Blocked cache reads under tp/dp sharding (the production decode
        layout) must still match the unsharded result."""
        from kubeflow_controller_tpu.parallel import MeshSpec, build_mesh

        cfg, params = setup()
        prompt = jax.random.randint(jax.random.PRNGKey(9), (4, 6), 0,
                                    cfg.vocab_size)
        ref = generate(params, prompt, cfg, max_new_tokens=6)
        mesh = build_mesh(MeshSpec(dp=2, tp=2, fsdp=2))
        sharded = self._sharded(cfg, params, mesh)
        with compat_set_mesh(mesh):
            out = jax.jit(
                lambda p, t: generate(p, t, cfg, max_new_tokens=6, kv_block=4)
            )(sharded, prompt)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))

    def test_cache_pspecs_cover_cache_tree(self):
        from kubeflow_controller_tpu.models.generate import cache_pspecs

        cfg, _ = setup()
        cache = init_cache(cfg, 2, 8)
        specs = cache_pspecs()
        assert set(specs) == set(cache)
