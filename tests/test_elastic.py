"""Elastic plane: width as a runtime property of a gang — API validation,
width-keyed planning/materialization, the transition engine
(degrade/harvest/re-expand), the WidthHarvested restart exemption, the
reshard stall hold, scheduler width harvesting, the gang-width-env vet
rule, the controller e2e, and re-shard numerical continuity."""

import os
import time

import pytest

from kubeflow_controller_tpu.api.core import (
    PHASE_FAILED,
    PHASE_PENDING,
    PHASE_RUNNING,
    PHASE_SUCCEEDED,
    Container,
    Pod,
    PodProgress,
    PodTemplateSpec,
)
from kubeflow_controller_tpu.api.labels import (
    ANNOTATION_ELASTIC_MIN_SLICES,
    ANNOTATION_ELASTIC_MIN_WIDTH,
    ANNOTATION_GANG_GENERATION,
    ANNOTATION_GANG_WIDTH,
    LABEL_INDEX,
    LABEL_JOB_TYPE,
)
from kubeflow_controller_tpu.api.meta import ObjectMeta
from kubeflow_controller_tpu.api.tfjob import (
    ElasticSpec,
    ReplicaType,
    TFJob,
    TFJobConditionType,
    TFJobPhase,
    TFReplicaSpec,
    TPUSpec,
    ValidationError,
    validate_tfjob,
)
from kubeflow_controller_tpu.elastic import (
    KIND_DEGRADE,
    KIND_EXPAND,
    KIND_HARVEST,
    ElasticEngine,
    ElasticPolicy,
)
from kubeflow_controller_tpu.planner.materialize import (
    ENV_GANG_WIDTH,
    ENV_NUM_PROCESSES,
    ENV_NUM_SLICES,
    gang_width,
    make_pod,
)
from kubeflow_controller_tpu.planner.plan import plan_job
from kubeflow_controller_tpu.planner.types import Action
from kubeflow_controller_tpu.recovery import RestartPolicyConfig, RestartTracker
from kubeflow_controller_tpu.updater import compute_status

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def mk_elastic_job(name="ejob", n=3, min_width=2, gang=True,
                   restart="OnFailure", runtime_id="rid"):
    job = TFJob(metadata=ObjectMeta(name=name, namespace="default"))
    job.metadata.uid = f"uid-{name}"
    job.spec.runtime_id = runtime_id
    t = PodTemplateSpec()
    t.spec.containers.append(Container(name="c", image="img"))
    t.spec.restart_policy = restart
    job.spec.elastic = ElasticSpec(min_width=min_width)
    job.spec.tf_replica_specs = [TFReplicaSpec(
        replicas=n, tf_replica_type=ReplicaType.WORKER, template=t,
        gang_restart=gang)]
    return job


def mk_tpu_elastic_job(name="tjob", num_slices=2, min_width=2,
                       runtime_id="rid"):
    job = TFJob(metadata=ObjectMeta(name=name, namespace="default"))
    job.metadata.uid = f"uid-{name}"
    job.spec.runtime_id = runtime_id
    t = PodTemplateSpec()
    t.spec.containers.append(Container(name="c", image="img"))
    t.spec.restart_policy = "OnFailure"
    job.spec.elastic = ElasticSpec(min_width=min_width)
    job.spec.tf_replica_specs = [TFReplicaSpec(
        replicas=2 * num_slices, tf_replica_type=ReplicaType.TPU, template=t,
        tpu=TPUSpec(accelerator_type="v5e-8", num_hosts=2,
                    num_slices=num_slices))]
    return job


def mk_member(name, index, phase=PHASE_RUNNING, gen=0, reason="",
              typ="Worker", job="ejob", fit_step=None):
    p = Pod(metadata=ObjectMeta(name=name, namespace="default"))
    p.metadata.labels = {LABEL_JOB_TYPE: typ, LABEL_INDEX: str(index),
                         "tf_job_name": job}
    p.metadata.annotations = {ANNOTATION_GANG_GENERATION: str(gen)}
    p.status.phase = phase
    p.status.reason = reason
    if fit_step is not None:
        p.status.progress = PodProgress(step=fit_step, phase="fit",
                                        timestamp=time.time())
    return p


def set_width(job, width, gen):
    job.metadata.annotations[ANNOTATION_GANG_WIDTH] = str(width)
    job.metadata.annotations[ANNOTATION_GANG_GENERATION] = str(gen)


# ---------------------------------------------------------------------------
# API validation + width keying
# ---------------------------------------------------------------------------

class TestElasticSpecValidation:
    def test_valid_elastic_worker_gang(self):
        validate_tfjob(mk_elastic_job())

    def test_min_width_above_spec_rejected(self):
        with pytest.raises(ValidationError, match="minWidth"):
            validate_tfjob(mk_elastic_job(n=3, min_width=4))

    def test_min_width_zero_rejected(self):
        with pytest.raises(ValidationError, match="minWidth"):
            validate_tfjob(mk_elastic_job(min_width=0))

    def test_elastic_requires_a_gang_spec(self):
        job = mk_elastic_job(gang=False)
        with pytest.raises(ValidationError, match="gang replica set"):
            validate_tfjob(job)

    def test_tpu_min_width_must_be_slice_granular(self):
        job = mk_tpu_elastic_job(num_slices=2, min_width=3)
        with pytest.raises(ValidationError, match="slice host count"):
            validate_tfjob(job)

    def test_tpu_slice_granular_floor_ok(self):
        validate_tfjob(mk_tpu_elastic_job(num_slices=2, min_width=2))

    def test_max_width_out_of_range_rejected(self):
        job = mk_elastic_job(n=3, min_width=2)
        job.spec.elastic.max_width = 5
        with pytest.raises(ValidationError, match="maxWidth"):
            validate_tfjob(job)


class TestGangWidth:
    def test_defaults_to_spec_width(self):
        job = mk_elastic_job(n=3)
        assert gang_width(job, job.spec.tf_replica_specs[0]) == 3

    def test_annotation_overrides_and_clamps(self):
        job = mk_elastic_job(n=3, min_width=2)
        spec = job.spec.tf_replica_specs[0]
        set_width(job, 2, 1)
        assert gang_width(job, spec) == 2
        set_width(job, 1, 2)  # below the floor: clamped up
        assert gang_width(job, spec) == 2
        set_width(job, 9, 3)  # above spec: clamped down
        assert gang_width(job, spec) == 3

    def test_non_elastic_spec_ignores_annotation(self):
        job = mk_elastic_job(n=3)
        job.spec.elastic = None
        set_width(job, 2, 1)
        assert gang_width(job, job.spec.tf_replica_specs[0]) == 3

    def test_worker_pods_materialize_at_current_width(self):
        job = mk_elastic_job(n=3, min_width=2)
        spec = job.spec.tf_replica_specs[0]
        set_width(job, 2, 1)
        pod = make_pod(job, spec, 0)
        env = {e.name: e.value for e in pod.spec.containers[0].env}
        assert env[ENV_NUM_PROCESSES] == "2"
        assert env[ENV_GANG_WIDTH] == "2"
        assert pod.metadata.annotations[ANNOTATION_GANG_WIDTH] == "2"
        assert pod.metadata.annotations[ANNOTATION_ELASTIC_MIN_WIDTH] == "2"

    def test_tpu_pods_follow_width_slice_granularly(self):
        job = mk_tpu_elastic_job(num_slices=2, min_width=2)  # width 4
        spec = job.spec.tf_replica_specs[0]
        set_width(job, 2, 1)  # degraded to one slice
        pod = make_pod(job, spec, 0)
        env = {e.name: e.value for e in pod.spec.containers[0].env}
        assert env[ENV_NUM_PROCESSES] == "2"
        assert env[ENV_NUM_SLICES] == "1"
        assert env[ENV_GANG_WIDTH] == "2"
        assert pod.metadata.annotations[ANNOTATION_ELASTIC_MIN_SLICES] == "1"


# ---------------------------------------------------------------------------
# Planner: stale-generation re-shard
# ---------------------------------------------------------------------------

class _StubDecision:
    def __init__(self, action):
        self.action = action


class _StubRecovery:
    def __init__(self, decisions):
        self._d = decisions

    def decision_for(self, typ, index):
        a = self._d.get(index)
        return _StubDecision(a) if a else None


class TestPlannerReshard:
    def _pods(self, job, n=3, gen=0, failed=()):
        return {ReplicaType.WORKER: [
            mk_member(f"p{i}", i, gen=gen,
                      phase=PHASE_FAILED if i in failed else PHASE_RUNNING)
            for i in range(n)]}

    def test_stale_generation_replaces_at_current_width(self):
        job = mk_elastic_job(n=3, min_width=2)
        set_width(job, 2, 1)  # transition applied; pods still at gen 0
        plan = plan_job(job, self._pods(job, n=3, gen=0, failed=(1,)), {})
        deletes = [e for e in plan.events if e.action == Action.DELETE_POD]
        adds = [e for e in plan.events if e.action == Action.ADD_POD]
        assert len(deletes) == 3  # every record, survivors included
        assert all(e.reason == "reshard" for e in deletes + adds)
        assert sorted(e.index for e in adds) == [0, 1]  # the new width

    def test_reshard_ignores_backoff_verdicts(self):
        job = mk_elastic_job(n=3, min_width=2)
        set_width(job, 2, 1)
        plan = plan_job(job, self._pods(job, n=3, gen=0, failed=(1,)), {},
                        recovery=_StubRecovery({1: "backoff"}))
        adds = [e for e in plan.events if e.action == Action.ADD_POD]
        assert sorted(e.index for e in adds) == [0, 1]

    def test_exhausted_budget_blocks_the_reshard(self):
        job = mk_elastic_job(n=3, min_width=2)
        set_width(job, 2, 1)
        plan = plan_job(job, self._pods(job, n=3, gen=0, failed=(1,)), {},
                        recovery=_StubRecovery({1: "exhausted"}))
        assert not [e for e in plan.events
                    if e.action in (Action.ADD_POD, Action.DELETE_POD)]

    def test_same_generation_healthy_gang_is_left_alone(self):
        job = mk_elastic_job(n=3)
        plan = plan_job(job, self._pods(job, n=3, gen=0), {})
        assert not [e for e in plan.events if e.action == Action.ADD_POD]


# ---------------------------------------------------------------------------
# The transition engine
# ---------------------------------------------------------------------------

class TestElasticEngine:
    def test_member_death_degrades_to_survivor_width(self):
        eng = ElasticEngine(ElasticPolicy(warmup_s=5.0))
        job = mk_elastic_job(n=3, min_width=2)
        pods = {ReplicaType.WORKER: [
            mk_member("a", 0), mk_member("b", 1),
            mk_member("c", 2, phase=PHASE_FAILED, reason="Error: exit -9")]}
        a = eng.assess("default/ejob", job, pods, None, now=100.0)
        assert a.transition is not None
        assert a.transition.kind == KIND_DEGRADE
        assert (a.transition.from_width, a.transition.to_width) == (3, 2)
        assert a.requeue_after_s == 5.0  # the warm-up hold

    def test_floor_crossing_defers_to_whole_gang_recovery(self):
        eng = ElasticEngine()
        job = mk_elastic_job(n=3, min_width=2)
        set_width(job, 2, 1)
        pods = {ReplicaType.WORKER: [
            mk_member("a", 0, gen=1),
            mk_member("b", 1, gen=1, phase=PHASE_FAILED, reason="Error")]}
        a = eng.assess("default/ejob", job, pods, None, now=100.0)
        assert a.transition is None  # 2-1 < min_width: recovery owns it

    def test_harvested_reason_yields_harvest_kind(self):
        eng = ElasticEngine()
        job = mk_elastic_job(n=3, min_width=2)
        pods = {ReplicaType.WORKER: [
            mk_member("a", 0), mk_member("b", 1),
            mk_member("c", 2, phase=PHASE_FAILED,
                      reason="WidthHarvested: 1 slice(s) for gang hi")]}
        a = eng.assess("default/ejob", job, pods, None, now=100.0)
        assert a.transition.kind == KIND_HARVEST

    def test_stale_generation_corpses_do_not_re_shrink(self):
        eng = ElasticEngine()
        job = mk_elastic_job(n=3, min_width=2)
        set_width(job, 2, 1)  # degrade already applied
        pods = {ReplicaType.WORKER: [
            mk_member("a", 0, gen=0, phase=PHASE_FAILED, reason="Error")]}
        a = eng.assess("default/ejob", job, pods, None, now=100.0)
        assert a.transition is None

    def test_expand_waits_out_warmup_then_fires(self):
        eng = ElasticEngine(ElasticPolicy(warmup_s=2.0))
        job = mk_elastic_job(n=3, min_width=2)
        pods = {ReplicaType.WORKER: [
            mk_member("a", 0), mk_member("b", 1),
            mk_member("c", 2, phase=PHASE_FAILED, reason="Error")]}
        assert eng.assess("k", job, pods, None, now=100.0).transition is not None
        set_width(job, 2, 1)  # the degrade was applied
        degraded = {ReplicaType.WORKER: [
            mk_member("d", 0, gen=1, fit_step=41),
            mk_member("e", 1, gen=1, fit_step=41)]}
        mid = eng.assess("k", job, degraded, None, now=101.0)
        assert mid.transition is None  # hold still open
        assert mid.requeue_after_s == pytest.approx(1.0, abs=0.01)
        done = eng.assess("k", job, degraded, None, now=102.5)
        assert done.transition is not None
        assert done.transition.kind == KIND_EXPAND
        assert done.transition.to_width == 3
        assert done.transition.complete

    def test_expand_requires_the_whole_degraded_gang_running(self):
        eng = ElasticEngine(ElasticPolicy(warmup_s=0.0, min_degraded_s=0.0))
        job = mk_elastic_job(n=3, min_width=2)
        set_width(job, 2, 1)
        half = {ReplicaType.WORKER: [mk_member("d", 0, gen=1)]}
        assert eng.assess("k", job, half, None, now=100.0).transition is None

    def test_tpu_expand_gated_on_free_slices(self):
        class Inv:
            def __init__(self, free):
                self.free = free

            def free_slice_count(self, accel=""):
                return self.free

        eng = ElasticEngine(ElasticPolicy(warmup_s=0.0, min_degraded_s=0.0,
                                          capacity_poll_s=0.5))
        job = mk_tpu_elastic_job(num_slices=2, min_width=2)  # width 4
        set_width(job, 2, 1)
        degraded = {ReplicaType.TPU: [
            mk_member("d", 0, gen=1, typ="TPU", fit_step=41),
            mk_member("e", 1, gen=1, typ="TPU", fit_step=41)]}
        short = eng.assess("k", job, degraded, None, now=100.0,
                           inventory=Inv(0))
        assert short.transition is None
        assert short.requeue_after_s == 0.5  # capacity poll
        ok = eng.assess("k", job, degraded, None, now=100.0,
                        inventory=Inv(1))
        assert ok.transition is not None
        assert ok.transition.to_width == 4

    def test_non_elastic_job_returns_none(self):
        eng = ElasticEngine()
        job = mk_elastic_job()
        job.spec.elastic = None
        assert eng.assess("k", job, {}, None, now=0.0) is None


# ---------------------------------------------------------------------------
# Restart accounting exemption + reshard stall hold
# ---------------------------------------------------------------------------

class TestHarvestedExemption:
    def test_width_harvested_failures_are_not_restarts(self):
        tr = RestartTracker(RestartPolicyConfig(jitter=0.0))
        job = mk_elastic_job(n=2)
        pods = {ReplicaType.WORKER: [
            mk_member("h", 0, phase=PHASE_FAILED,
                      reason="WidthHarvested: 1 slice(s) for gang hi"),
            mk_member("x", 1, phase=PHASE_FAILED, reason="Error: exit 1")]}
        a = tr.assess("default/ejob", job, pods, 0.0)
        assert a.restarts_for(ReplicaType.WORKER) == 1  # only the crash
        assert (ReplicaType.WORKER, 0) not in a.decisions


class TestReshardStallHold:
    def test_reshard_phase_holds_frozen_step_deadline(self):
        from kubeflow_controller_tpu.checker import StallPolicy, StallTracker

        trk = StallTracker(StallPolicy(heartbeat_deadline_s=0.0,
                                       step_deadline_s=10.0))
        t0 = 1000.0
        assert not trk.observe("k", PodProgress(step=50, timestamp=t0), now=t0)
        # A width transition: the step counter freezes in phase="reshard"
        # far past the deadline — held, not stalled.
        assert not trk.observe(
            "k", PodProgress(step=50, phase="reshard", timestamp=t0 + 30),
            now=t0 + 30)
        assert not trk.observe(
            "k", PodProgress(step=50, phase="reshard", timestamp=t0 + 45),
            now=t0 + 45)
        # Training resumes, then freezes WITHOUT the phase: real stall.
        assert not trk.observe(
            "k", PodProgress(step=51, phase="fit", timestamp=t0 + 46),
            now=t0 + 46)
        assert trk.observe(
            "k", PodProgress(step=51, phase="fit", timestamp=t0 + 60),
            now=t0 + 60)


# ---------------------------------------------------------------------------
# Status surface: width rollup + Degraded condition
# ---------------------------------------------------------------------------

class TestWidthStatus:
    def _cond(self, st, typ):
        return next((c for c in st.conditions if c.type == typ), None)

    def test_degraded_condition_while_width_reduced(self):
        job = mk_elastic_job(n=3, min_width=2)
        set_width(job, 2, 1)
        pods = {ReplicaType.WORKER: [mk_member("a", 0, gen=1),
                                     mk_member("b", 1, gen=1)]}
        st = compute_status(job, pods)
        assert st.width is not None
        assert (st.width.current, st.width.spec, st.width.min) == (2, 3, 2)
        c = self._cond(st, TFJobConditionType.DEGRADED)
        assert c.status == "True" and c.reason == "WidthReduced"
        # Degraded-but-whole: Scheduled/Ready measure the CURRENT width.
        assert self._cond(st, TFJobConditionType.SCHEDULED).status == "True"
        assert self._cond(st, TFJobConditionType.READY).status == "True"

    def test_full_width_clears_the_condition(self):
        job = mk_elastic_job(n=3, min_width=2)
        pods = {ReplicaType.WORKER: [mk_member(f"p{i}", i)
                                     for i in range(3)]}
        st = compute_status(job, pods)
        assert (st.width.current, st.width.spec) == (3, 3)
        c = self._cond(st, TFJobConditionType.DEGRADED)
        assert c.status == "False" and c.reason == "FullWidth"

    def test_non_elastic_jobs_carry_no_width_surface(self):
        job = mk_elastic_job(n=3)
        job.spec.elastic = None
        pods = {ReplicaType.WORKER: [mk_member(f"p{i}", i)
                                     for i in range(3)]}
        st = compute_status(job, pods)
        assert st.width is None
        assert self._cond(st, TFJobConditionType.DEGRADED) is None

    def test_degraded_gang_succeeds_at_current_width(self):
        job = mk_elastic_job(n=3, min_width=2)
        set_width(job, 2, 1)
        pods = {ReplicaType.WORKER: [
            mk_member("a", 0, gen=1, phase=PHASE_SUCCEEDED),
            mk_member("b", 1, gen=1, phase=PHASE_SUCCEEDED)]}
        st = compute_status(job, pods)
        assert st.phase == TFJobPhase.SUCCEEDED


# ---------------------------------------------------------------------------
# Scheduler width harvesting + inventory growth
# ---------------------------------------------------------------------------

class TestSchedulerHarvest:
    def _rig(self, n_slices=4):
        from kubeflow_controller_tpu.cluster import TPUInventory, TPUSlice
        from kubeflow_controller_tpu.scheduler import (
            GangScheduler,
            SchedulerPolicy,
        )

        inv = TPUInventory([TPUSlice(f"s{i}", "v5e-8", num_hosts=2)
                            for i in range(n_slices)])
        sched = GangScheduler(inv, SchedulerPolicy())
        evictions = []
        sched.set_evictor(lambda keys, reason: evictions.append(
            (sorted(keys), reason)))
        return inv, sched, evictions

    def _admit(self, sched, job, n):
        pods = [make_pod(job, job.spec.tf_replica_specs[0], i)
                for i in range(n)]
        for i, p in enumerate(pods):
            p.metadata.name = f"{job.metadata.name}-{i}"
        results = [sched.offer(p) for p in pods]
        sched.pod_started(pods[0])
        results = [sched.offer(p) for p in pods]
        return pods, results

    def _preempt_count(self):
        from kubeflow_controller_tpu.obs.metrics import REGISTRY

        c = REGISTRY.counter("kctpu_sched_preemptions_total", "",
                             ("priority_class",))
        with c._lock:
            return sum(c._values.values())

    def test_blocked_high_gang_harvests_instead_of_preempting(self):
        inv, sched, evictions = self._rig()
        low = mk_tpu_elastic_job("low", num_slices=4, min_width=4)
        low.spec.priority_class_name = "low"
        self._admit(sched, low, 8)
        gang_low = "low-rid"
        assert len(sched.gang_slices(gang_low)) == 4
        before = self._preempt_count()

        high = mk_tpu_elastic_job("high", num_slices=2, min_width=2)
        high.spec.elastic = None
        high.spec.priority_class_name = "high"
        _, results = self._admit(sched, high, 4)
        assert any(results)  # the high gang was admitted
        assert len(sched.gang_slices("high-rid")) == 2
        # The victim lost exactly its surplus: down to the floor of 2.
        assert len(sched.gang_slices(gang_low)) == 2
        # Only the pods on the harvested slices were failed, with the
        # WidthHarvested reason — zero whole-gang preemptions.
        assert len(evictions) == 1
        keys, reason = evictions[0]
        assert reason.startswith("WidthHarvested")
        assert len(keys) == 4  # 2 slices x 2 hosts
        assert self._preempt_count() == before

    def test_non_elastic_victim_is_still_preempted_whole(self):
        inv, sched, evictions = self._rig(n_slices=2)
        low = mk_tpu_elastic_job("plain", num_slices=2, min_width=2)
        low.spec.elastic = None
        low.spec.priority_class_name = "low"
        self._admit(sched, low, 4)
        before = self._preempt_count()
        high = mk_tpu_elastic_job("urgent", num_slices=2, min_width=2)
        high.spec.elastic = None
        high.spec.priority_class_name = "high"
        self._admit(sched, high, 4)
        assert self._preempt_count() == before + 1
        assert any(r.startswith("Preempted") for _, r in evictions)

    def test_release_slices_keeps_the_coordinator_slice(self):
        inv, sched, _ = self._rig()
        low = mk_tpu_elastic_job("low2", num_slices=4, min_width=2)
        self._admit(sched, low, 8)
        slices = sched.gang_slices("low2-rid")
        released = inv.release_slices("low2-rid", 99)  # over-ask clamps
        assert sched.gang_slices("low2-rid") == slices[:1]
        assert sorted(released) == sorted(slices[1:])

    def test_grow_gang_binds_freed_capacity_back(self):
        inv, sched, _ = self._rig()
        low = mk_tpu_elastic_job("low3", num_slices=4, min_width=2)
        self._admit(sched, low, 8)
        inv.release_slices("low3-rid", 2)
        assert inv.free_slice_count("v5e-8") == 2
        grown = sched.grow_gang("low3-rid", "v5e-8", 2)
        assert grown is not None and len(grown) == 2
        assert len(sched.gang_slices("low3-rid")) == 4
        assert sched.free_slice_count("v5e-8") == 0


# ---------------------------------------------------------------------------
# vet: the gang-width-env rule
# ---------------------------------------------------------------------------

class TestGangWidthEnvRule:
    FIXTURES = os.path.join(REPO_ROOT, "tests", "fixtures", "vet",
                            "workloads")

    def _vet(self, name):
        from kubeflow_controller_tpu.analysis import vet

        findings = vet.run([os.path.join(self.FIXTURES, name)],
                           root=REPO_ROOT, skip_catalogue=True)
        return findings, {f.rule for f in findings}

    def test_bad_fixture_flagged(self):
        findings, rules = self._vet("bad_widthenv.py")
        assert rules == {"gang-width-env"}
        assert len(findings) == 2  # the spec chain + the bare spec read
        assert all("KCTPU_GANG_WIDTH" in f.message for f in findings)

    def test_good_fixture_clean(self):
        findings, _ = self._vet("good_widthenv.py")
        assert findings == []

    def test_rule_is_scoped_to_workloads(self):
        # The planner legitimately reads spec.replicas — it is what turns
        # spec width into runtime width.
        from kubeflow_controller_tpu.analysis import vet

        path = os.path.join(REPO_ROOT, "kubeflow_controller_tpu",
                            "planner", "plan.py")
        findings = vet.run([path], root=REPO_ROOT, skip_catalogue=True)
        assert not [f for f in findings if f.rule == "gang-width-env"]


# ---------------------------------------------------------------------------
# Controller e2e: kill → degraded width → re-expand (simulated)
# ---------------------------------------------------------------------------

def wait_for(fn, timeout=20.0, interval=0.02):
    deadline = time.time() + timeout
    while time.time() < deadline:
        v = fn()
        if v:
            return v
        time.sleep(interval)
    raise AssertionError("condition not met within timeout")


@pytest.fixture
def rig():
    from kubeflow_controller_tpu.cluster import Cluster, FakeKubelet, PhasePolicy
    from kubeflow_controller_tpu.controller import Controller

    cluster = Cluster()
    kubelet = FakeKubelet(cluster, policy=PhasePolicy(run_s=3.0,
                                                      heartbeat_s=0.05))
    ctrl = Controller(cluster, resync_period_s=0.5,
                      restart_config=RestartPolicyConfig(
                          initial_backoff_s=0.05, jitter=0.0),
                      elastic_policy=ElasticPolicy(warmup_s=0.3,
                                                   min_degraded_s=0.3))
    kubelet.start()
    ctrl.run(threadiness=2)
    yield cluster, ctrl, kubelet
    ctrl.stop()
    kubelet.stop()


class TestControllerElasticE2E:
    def test_kill_degrade_reexpand_cycle(self, rig):
        cluster, ctrl, kubelet = rig
        job = mk_elastic_job("el", n=3, min_width=2, runtime_id="")
        cluster.tfjobs.create(job)
        wait_for(lambda: len([p for p in cluster.pods.list("default")
                              if p.status.phase == PHASE_RUNNING]) == 3)
        victim = sorted(cluster.pods.list("default"),
                        key=lambda p: p.metadata.labels[LABEL_INDEX])[2]
        kubelet.set_phase("default", victim.metadata.name, PHASE_FAILED,
                          reason="Error: exit -9: killed")

        # Degrade: width annotation 2, exactly 2 active members at gen 1,
        # the Degraded condition and the GangDegraded event.
        def degraded():
            j = cluster.tfjobs.get("default", "el")
            if j.metadata.annotations.get(ANNOTATION_GANG_WIDTH) != "2":
                return None
            live = [p for p in cluster.pods.list("default")
                    if p.status.phase == PHASE_RUNNING]
            return (len(live) == 2 and all(
                p.metadata.annotations[ANNOTATION_GANG_GENERATION] == "1"
                for p in live)) or None
        wait_for(degraded)
        j = cluster.tfjobs.get("default", "el")
        assert j.status.width is not None
        evs = {e.reason for e in ctrl.recorder.events_for("default", "el")}
        assert "GangDegraded" in evs

        # Re-expand after the warm-up hold: width back to 3, a THIRD
        # generation of pods, the GangRestored event, Degraded=False.
        def restored():
            j = cluster.tfjobs.get("default", "el")
            if j.metadata.annotations.get(ANNOTATION_GANG_WIDTH) != "3":
                return None
            live = [p for p in cluster.pods.list("default")
                    if p.status.phase in (PHASE_RUNNING, PHASE_SUCCEEDED)]
            return (len(live) == 3 and all(
                p.metadata.annotations[ANNOTATION_GANG_GENERATION] == "2"
                for p in live)) or None
        wait_for(restored)
        evs = {e.reason for e in ctrl.recorder.events_for("default", "el")}
        assert "GangRestored" in evs
        wait_for(lambda: cluster.tfjobs.get("default", "el").status.phase
                 == TFJobPhase.SUCCEEDED, timeout=25.0)
        j = cluster.tfjobs.get("default", "el")
        cond = next(c for c in j.status.conditions
                    if c.type == TFJobConditionType.DEGRADED)
        assert cond.status == "False"

    def test_floor_kill_falls_back_to_whole_gang_recovery(self, rig):
        cluster, ctrl, kubelet = rig
        job = mk_elastic_job("fl", n=2, min_width=2, runtime_id="")
        cluster.tfjobs.create(job)
        wait_for(lambda: len([p for p in cluster.pods.list("default")
                              if p.status.phase == PHASE_RUNNING]) == 2)
        before = {p.metadata.name for p in cluster.pods.list("default")}
        victim = sorted(before)[0]
        kubelet.set_phase("default", victim, PHASE_FAILED,
                          reason="Error: exit -9")

        # Whole-gang replacement at FULL width (no degrade possible).
        def regenerated():
            pods = [p for p in cluster.pods.list("default")
                    if p.metadata.name not in before
                    and p.status.phase == PHASE_RUNNING]
            return len(pods) == 2 or None
        wait_for(regenerated)
        j = cluster.tfjobs.get("default", "fl")
        assert j.metadata.annotations.get(ANNOTATION_GANG_WIDTH, "") in ("", "2")
        evs = {e.reason for e in ctrl.recorder.events_for("default", "fl")}
        assert "GangDegraded" not in evs


# ---------------------------------------------------------------------------
# Re-shard numerical continuity: degraded batch ≠ divergence
# ---------------------------------------------------------------------------

class TestReshardNumericalContinuity:
    def _mk(self, bs):
        import numpy as np

        from kubeflow_controller_tpu.models import mnist as m
        from kubeflow_controller_tpu.parallel import (
            AXIS_DATA,
            MeshSpec,
            build_mesh,
        )
        from kubeflow_controller_tpu.workloads import data as d
        from kubeflow_controller_tpu.workloads.trainer import (
            default_optimizer,
            global_batches,
            make_dist_step,
        )

        mesh = build_mesh(MeshSpec(dp=-1, fsdp=1))
        opt = default_optimizer(5e-3)
        step = make_dist_step(lambda p, b: m.mlp_loss(p, b[0], b[1]), opt,
                              mesh, AXIS_DATA, donate=False)
        spe = 4
        x, y = d.synthetic_mnist_np(1, 64)
        idx = (np.arange(spe)[:, None] * bs
               + np.arange(bs)[None, :]) % x.shape[0]
        x_all, y_all = global_batches(
            mesh, AXIS_DATA, (x[idx], y[idx].astype(np.int32)), bs)
        return mesh, opt, step, x_all, y_all

    def _fresh(self, mesh, opt):
        from kubeflow_controller_tpu.models import mnist as m
        from kubeflow_controller_tpu.workloads.trainer import (
            numpy_opt_state,
            replicate_pytree,
        )

        params = replicate_pytree(mesh, m.mlp_init(0))
        opt_state = replicate_pytree(
            mesh, numpy_opt_state(opt, m.mlp_init(0)))
        return params, opt_state

    def test_kill_degrade_expand_matches_uninterrupted_within_tolerance(
            self, tmp_path):
        """Kill at step S → degraded window (smaller global batch — the
        re-shard analog a 1-device host can express) → re-expand must
        track the uninterrupted run's loss trajectory within tolerance,
        and each transition's lost steps stay ≤ the checkpoint
        interval."""
        from kubeflow_controller_tpu.workloads.checkpoint import (
            CheckpointManager,
        )
        from kubeflow_controller_tpu.workloads.trainer import (
            train_step_loop_dist,
        )

        steps, every, kill_at, expand_at = 30, 5, 12, 22
        mesh, opt, step_full, x_f, y_f = self._mk(bs=16)
        _, _, step_deg, x_d, y_d = self._mk(bs=8)

        # Uninterrupted baseline at full width.
        p0, s0 = self._fresh(mesh, opt)
        _, _, base_loss = train_step_loop_dist(
            step_full, p0, s0, x_f, y_f, steps)
        base_loss = float(base_loss)

        # Interrupted run: full → (kill) → degraded → (expand) → full.
        mgr = CheckpointManager(str(tmp_path / "ck"))
        mgr.write_width(2)
        p, s = self._fresh(mesh, opt)
        train_step_loop_dist(
            step_full, p, s, x_f, y_f, kill_at, checkpoint_every=every,
            checkpoint_fn=lambda n, a, b: mgr.save(n, a, b, wait=False))
        mgr.wait()
        # Degrade: restore the latest checkpoint, re-shard marker flips.
        p, s = self._fresh(mesh, opt)
        p, s, start = mgr.restore(p, s)
        assert kill_at - start <= every  # lost ≤ interval (transition 1)
        assert mgr.read_width() == 2
        mgr.write_width(1)
        train_step_loop_dist(
            step_deg, p, s, x_d, y_d, expand_at, start_step=start,
            checkpoint_every=every,
            checkpoint_fn=lambda n, a, b: mgr.save(n, a, b, wait=False))
        mgr.wait()
        # Expand: resume the degraded run's checkpoint at full width —
        # never restore-from-scratch.
        p, s = self._fresh(mesh, opt)
        p, s, start2 = mgr.restore(p, s)
        assert start2 > start  # degraded training really progressed
        assert expand_at - start2 <= every  # lost ≤ interval (transition 2)
        _, _, loss = train_step_loop_dist(
            step_full, p, s, x_f, y_f, steps, start_step=start2)
        loss = float(loss)

        # The re-sharded trajectory lands where the uninterrupted one
        # does: converging, and within tolerance of the baseline.
        assert loss < 1.0
        assert abs(loss - base_loss) < 0.25
