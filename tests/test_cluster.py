"""Fake-cluster substrate tests: store semantics, watch, kubelet, TPU gangs."""

import sys
import time

import pytest

from kubeflow_controller_tpu.api.core import (
    PHASE_FAILED,
    PHASE_PENDING,
    PHASE_RUNNING,
    PHASE_SUCCEEDED,
    Container,
    EnvVar,
    Pod,
    ResourceRequirements,
)
from kubeflow_controller_tpu.api.labels import (
    ANNOTATION_ACCELERATOR,
    ANNOTATION_GANG_NAME,
    ANNOTATION_GANG_SIZE,
    LABEL_JOB_TYPE,
)
from kubeflow_controller_tpu.api.meta import ObjectMeta, OwnerReference
from kubeflow_controller_tpu.api.tfjob import TFJob, TFJobPhase
from kubeflow_controller_tpu.cluster import (
    AlreadyExists,
    Cluster,
    Conflict,
    FakeKubelet,
    NotFound,
    PhasePolicy,
    TPUInventory,
    TPUSlice,
)
from kubeflow_controller_tpu.cluster.store import ADDED, DELETED, MODIFIED


def mk_pod(name, ns="default", labels=None, annotations=None, command=None, tpu=False):
    pod = Pod(metadata=ObjectMeta(name=name, namespace=ns))
    pod.metadata.labels = labels or {}
    pod.metadata.annotations = annotations or {}
    c = Container(name="main")
    if command:
        c.command = command
    if tpu:
        c.resources = ResourceRequirements(requests={"google.com/tpu": "4"})
    pod.spec.containers.append(c)
    return pod


def wait_for(fn, timeout=5.0, interval=0.01):
    deadline = time.time() + timeout
    while time.time() < deadline:
        v = fn()
        if v:
            return v
        time.sleep(interval)
    raise AssertionError("condition not met within timeout")


# ---- store semantics ----

def test_create_get_update_conflict():
    c = Cluster()
    job = TFJob(metadata=ObjectMeta(name="j", namespace="ns"))
    created = c.tfjobs.create(job)
    assert created.metadata.uid and created.metadata.resource_version
    stale = c.tfjobs.get("ns", "j")
    fresh = c.tfjobs.get("ns", "j")
    fresh.status.phase = TFJobPhase.RUNNING
    c.tfjobs.update(fresh)
    stale.status.phase = TFJobPhase.FAILED
    with pytest.raises(Conflict):
        c.tfjobs.update(stale)
    with pytest.raises(AlreadyExists):
        c.tfjobs.create(TFJob(metadata=ObjectMeta(name="j", namespace="ns")))
    with pytest.raises(NotFound):
        c.tfjobs.get("ns", "nope")


def test_update_status_rv_semantics():
    c = Cluster()
    c.tfjobs.create(TFJob(metadata=ObjectMeta(name="j", namespace="ns")))
    j = c.tfjobs.get("ns", "j")
    j.status.phase = TFJobPhase.RUNNING
    c.tfjobs.update_status(j)  # fresh rv: accepted
    assert c.tfjobs.get("ns", "j").status.phase == TFJobPhase.RUNNING
    # Stale rv -> Conflict (the status subresource honors optimistic locking).
    j.status.phase = TFJobPhase.FAILED
    j.metadata.resource_version = "1"
    with pytest.raises(Conflict):
        c.tfjobs.update_status(j)
    # Empty rv -> last-write-wins.
    j.metadata.resource_version = ""
    c.tfjobs.update_status(j)
    assert c.tfjobs.get("ns", "j").status.phase == TFJobPhase.FAILED


def test_generate_name_and_store_isolation():
    c = Cluster()
    pod = Pod(metadata=ObjectMeta(generate_name="dist-mnist-worker-", namespace="ns"))
    created = c.pods.create(pod)
    assert created.metadata.name.startswith("dist-mnist-worker-")
    assert len(created.metadata.name) == len("dist-mnist-worker-") + 5
    # Mutating the returned copy must not touch the store.
    created.metadata.labels["x"] = "y"
    assert "x" not in c.pods.get("ns", created.metadata.name).metadata.labels


def test_list_selector_and_namespace():
    c = Cluster()
    c.pods.create(mk_pod("a", ns="n1", labels={"t": "w"}))
    c.pods.create(mk_pod("b", ns="n1", labels={"t": "ps"}))
    c.pods.create(mk_pod("c", ns="n2", labels={"t": "w"}))
    assert {p.metadata.name for p in c.pods.list("n1")} == {"a", "b"}
    assert {p.metadata.name for p in c.pods.list("n1", selector={"t": "w"})} == {"a"}
    assert len(c.pods.list()) == 3


def test_watch_ordering_and_namespace_filter():
    c = Cluster()
    w = c.pods.watch("ns")
    c.pods.create(mk_pod("p1", ns="ns"))
    c.pods.create(mk_pod("other", ns="elsewhere"))
    p = c.pods.get("ns", "p1")
    p.status.phase = PHASE_RUNNING
    c.store.update_status("pods", p)
    c.pods.delete("ns", "p1")
    evs = [w.next(timeout=1) for _ in range(3)]
    assert [e.type for e in evs] == [ADDED, MODIFIED, DELETED]
    assert all(e.object.metadata.name == "p1" for e in evs)
    w.stop()
    assert w.next(timeout=1) is None


def test_cascade_delete_owned_objects():
    c = Cluster()
    job = c.tfjobs.create(TFJob(metadata=ObjectMeta(name="j", namespace="ns")))
    pod = mk_pod("p", ns="ns")
    pod.metadata.owner_references.append(
        OwnerReference(kind="TFJob", name="j", uid=job.metadata.uid, controller=True)
    )
    c.pods.create(pod)
    orphan = mk_pod("orphan", ns="ns")
    c.pods.create(orphan)
    c.tfjobs.delete("ns", "j")
    with pytest.raises(NotFound):
        c.pods.get("ns", "p")
    assert c.pods.get("ns", "orphan")


def test_patch_meta_adoption():
    c = Cluster()
    c.pods.create(mk_pod("p", ns="ns"))
    c.pods.patch_meta(
        "ns", "p",
        lambda m: m.owner_references.append(OwnerReference(name="j", uid="u", controller=True)),
    )
    got = c.pods.get("ns", "p")
    assert got.metadata.owner_references[0].uid == "u"


def test_object_patch_merge_semantics():
    """Full-object RFC 7386 merge patch (the PatchService analog): nested
    maps merge per-key, null deletes, scalars replace; immutable metadata
    survives, the resourceVersion bumps, and watchers see MODIFIED."""
    from kubeflow_controller_tpu.api.core import Service, ServiceSpec

    c = Cluster()
    svc = Service(metadata=ObjectMeta(name="s", namespace="ns",
                                      labels={"a": "1", "b": "2"}),
                  spec=ServiceSpec(selector={"job": "x", "idx": "0"}))
    created = c.services.create(svc)
    w = c.services.watch("ns")
    patched = c.services.patch("ns", "s", {
        "metadata": {"labels": {"b": None, "c": "3"}},
        "spec": {"selector": {"idx": "1"}},
    })
    # Per-key merge: untouched keys survive, null deletes, new keys land.
    assert patched.metadata.labels == {"a": "1", "c": "3"}
    assert patched.spec.selector == {"job": "x", "idx": "1"}
    assert patched.metadata.uid == created.metadata.uid
    assert patched.metadata.resource_version != created.metadata.resource_version
    ev = w.next(timeout=2.0)
    assert ev.type == MODIFIED and ev.object.metadata.name == "s"
    w.stop()


# ---- fake kubelet: simulated ----

def test_kubelet_worker_succeeds_ps_runs_forever():
    c = Cluster()
    kubelet = FakeKubelet(c, policy=PhasePolicy(run_s=0.01))
    kubelet.start()
    try:
        c.pods.create(mk_pod("w0", labels={LABEL_JOB_TYPE: "Worker"}))
        c.pods.create(mk_pod("ps0", labels={LABEL_JOB_TYPE: "PS"}))
        wait_for(lambda: c.pods.get("default", "w0").status.phase == PHASE_SUCCEEDED)
        assert c.pods.get("default", "ps0").status.phase == PHASE_RUNNING
    finally:
        kubelet.stop()


def test_kubelet_fault_injection():
    c = Cluster()
    kubelet = FakeKubelet(c, policy=PhasePolicy(run_s=0.01, fail_once={"w0"}))
    kubelet.start()
    try:
        c.pods.create(mk_pod("w0", labels={LABEL_JOB_TYPE: "Worker"}))
        wait_for(lambda: c.pods.get("default", "w0").status.phase == PHASE_FAILED)
    finally:
        kubelet.stop()


# ---- fake kubelet: executed subprocesses ----

def test_kubelet_executes_real_process_with_env():
    c = Cluster()
    kubelet = FakeKubelet(c, execute=True)
    kubelet.start()
    try:
        pod = mk_pod("runner", command=[sys.executable, "-c", "import os,sys; sys.exit(0 if os.environ.get('TASK_INDEX')=='3' else 1)"])
        pod.spec.containers[0].env.append(EnvVar(name="TASK_INDEX", value="3"))
        c.pods.create(pod)
        # Subprocess spawn can take seconds under parallel-test load;
        # the default 5s window flakes.
        wait_for(lambda: c.pods.get("default", "runner").status.phase == PHASE_SUCCEEDED,
                 timeout=30.0)
    finally:
        kubelet.stop()


def test_kubelet_execute_failure_after_restarts():
    c = Cluster()
    kubelet = FakeKubelet(c, execute=True, max_restarts=1)
    kubelet.start()
    try:
        pod = mk_pod("bad", command=[sys.executable, "-c", "raise SystemExit(3)"])
        pod.spec.restart_policy = "OnFailure"
        c.pods.create(pod)
        # Generous timeout: the warm-pool prewarm competes for CPU on
        # single-core hosts; this asserts restart semantics, not latency.
        got = wait_for(
            lambda: (lambda p: p if p.status.phase == PHASE_FAILED else None)(c.pods.get("default", "bad")),
            timeout=30.0,
        )
        assert "exit 3" in got.status.reason
    finally:
        kubelet.stop()


# ---- TPU inventory: gang admission ----

def tpu_pod(name, gang, size, accel="v5e-8"):
    return mk_pod(
        name,
        tpu=True,
        annotations={
            ANNOTATION_GANG_NAME: gang,
            ANNOTATION_GANG_SIZE: str(size),
            ANNOTATION_ACCELERATOR: accel,
        },
    )


def test_gang_all_or_nothing():
    inv = TPUInventory([TPUSlice("slice-0", "v5e-8", num_hosts=2)])
    p0, p1 = tpu_pod("h0", "g1", 2), tpu_pod("h1", "g1", 2)
    assert not inv.offer(p0)  # incomplete gang: hold
    assert inv.offer(p1)      # gang complete: admitted
    assert inv.offer(p0)      # first pod re-offers, now admitted
    assert inv.gang_slice("g1") == "slice-0"


def test_gang_blocks_without_capacity_then_admits_after_release():
    inv = TPUInventory([TPUSlice("slice-0", "v5e-8", num_hosts=2)])
    assert inv.offer(tpu_pod("a0", "g1", 1))
    assert inv.gang_slice("g1") == "slice-0"
    assert not inv.offer(tpu_pod("b0", "g2", 1))  # no free slice
    inv.release_gang("g1")
    assert inv.offer(tpu_pod("b0", "g2", 1))


def test_gang_accelerator_type_must_match():
    inv = TPUInventory([TPUSlice("slice-0", "v5p-32", num_hosts=8)])
    assert not inv.offer(tpu_pod("a0", "g1", 1, accel="v5e-8"))
    assert inv.offer(tpu_pod("b0", "g2", 1, accel="v5p-32"))


def test_kubelet_gates_tpu_pods_on_gang_admission():
    c = Cluster()
    inv = TPUInventory([TPUSlice("slice-0", "v5e-8", num_hosts=2)])
    kubelet = FakeKubelet(c, policy=PhasePolicy(run_s=0.01), inventory=inv)
    kubelet.start()
    try:
        c.pods.create(tpu_pod("h0", "g1", 2))
        time.sleep(0.1)
        assert c.pods.get("default", "h0").status.phase == PHASE_PENDING
        c.pods.create(tpu_pod("h1", "g1", 2))
        wait_for(lambda: c.pods.get("default", "h0").status.phase == PHASE_SUCCEEDED)
        wait_for(lambda: c.pods.get("default", "h1").status.phase == PHASE_SUCCEEDED)
    finally:
        kubelet.stop()


def test_slice_failure_domain():
    inv = TPUInventory([TPUSlice("slice-0", "v5e-8", num_hosts=2),
                        TPUSlice("slice-1", "v5e-8", num_hosts=2)])
    inv.offer(tpu_pod("h0", "g1", 2))
    inv.offer(tpu_pod("h1", "g1", 2))
    assert sorted(inv.fail_slice("slice-0")) == ["default/h0", "default/h1"]
    # The failed slice is quarantined and its gang evicted: a replacement
    # gang must land on different hardware.
    assert inv.slices["slice-0"].healthy is False
    assert inv.slices["slice-0"].bound_gang == ""
    inv.offer(tpu_pod("r0", "g2", 2))
    assert inv.offer(tpu_pod("r1", "g2", 2))
    assert inv.gang_slice("g2") == "slice-1"


def test_idle_gang_release_is_namespace_aware():
    """A same-named pod in ANOTHER namespace must not keep a dead gang's
    slice bound (advisor round-2: bare-name live sets leak slices)."""
    inv = TPUInventory([TPUSlice("slice-0", "v5e-8", num_hosts=2)])
    inv.offer(tpu_pod("h0", "g1", 2))
    inv.offer(tpu_pod("h1", "g1", 2))
    assert inv.gang_slice("g1") == "slice-0"
    # Gang pods (namespace "default") are all dead; an unrelated live pod
    # named "h0" exists in namespace "other".
    live = {"other/h0"}
    inv.release_idle_gangs(live)          # first scan: candidate
    released = inv.release_idle_gangs(live)  # second scan: confirmed
    assert released == ["g1"]
    assert inv.slices["slice-0"].bound_gang == ""


# ---- Multislice (DCN) gang scheduling ----

def multislice_pod(name, gang, size, n_slices, accel="v5e-8"):
    from kubeflow_controller_tpu.api.labels import ANNOTATION_NUM_SLICES

    p = tpu_pod(name, gang, size, accel)
    p.metadata.annotations[ANNOTATION_NUM_SLICES] = str(n_slices)
    return p


def test_multislice_gang_binds_n_slices():
    inv = TPUInventory([TPUSlice(f"slice-{i}", "v5e-8", num_hosts=2)
                        for i in range(3)])
    # Gang of 4 pods spanning 2 slices (2 hosts each).
    pods = [multislice_pod(f"h{i}", "g1", 4, 2) for i in range(4)]
    assert not inv.offer(pods[0])
    assert not inv.offer(pods[1])
    assert not inv.offer(pods[2])
    assert inv.offer(pods[3])  # complete: admitted onto 2 slices atomically
    bound = inv.gang_slices("g1")
    assert len(bound) == 2
    assert sum(1 for s in inv.slices.values() if s.bound_gang == "g1") == 2
    # A second 2-slice gang cannot fit (only 1 slice left).
    pods2 = [multislice_pod(f"x{i}", "g2", 4, 2) for i in range(4)]
    for p in pods2:
        admitted = inv.offer(p)
    assert not admitted
    # Releasing the first frees both its slices; g2 then fits.
    inv.release_gang("g1")
    assert inv.offer(pods2[0])  # complete gang re-offer: admitted now
    assert len(inv.gang_slices("g2")) == 2


def test_multislice_fail_one_slice_evicts_whole_gang():
    inv = TPUInventory([TPUSlice(f"slice-{i}", "v5e-8", num_hosts=2)
                        for i in range(3)])
    pods = [multislice_pod(f"h{i}", "g1", 4, 2) for i in range(4)]
    for p in pods:
        inv.offer(p)
    s0, s1 = inv.gang_slices("g1")
    assert sorted(inv.fail_slice(s0)) == [
        "default/h0", "default/h1", "default/h2", "default/h3"]
    # Failed slice quarantined; the OTHER slice is healthy and free again.
    assert inv.slices[s0].healthy is False
    assert inv.slices[s1].healthy is True
    assert inv.slices[s1].bound_gang == ""
