"""Analysis plane: `kctpu vet` rules against paired good/bad fixtures, the
runtime lock-order detector, the schedule-fuzz harness, and the planner's
shared-template regression (the reference bug, design_doc.md:262-268)."""

import os
import threading

import pytest

from kubeflow_controller_tpu.analysis import interleave, lockcheck, vet
from kubeflow_controller_tpu.utils import locks

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO_ROOT, "tests", "fixtures", "vet")


def vet_rules(path):
    """Rule names found in one fixture file (catalogue check skipped)."""
    findings = vet.run([os.path.join(FIXTURES, path)], root=REPO_ROOT,
                       skip_catalogue=True)
    return findings, {f.rule for f in findings}


# ---------------------------------------------------------------------------
# kctpu vet: rules against paired fixtures
# ---------------------------------------------------------------------------

class TestVetRules:
    def test_lock_blocking_bad(self):
        findings, rules = vet_rules("bad_lock_blocking.py")
        assert rules == {"lock-blocking-call"}
        # one per blocking call: sleep, queue.get, socket() + connect, run
        assert len(findings) == 5
        msgs = " ".join(f.message for f in findings)
        assert "time.sleep" in msgs and "queue" in msgs
        assert all(f.line > 0 and f.path.endswith("bad_lock_blocking.py")
                   for f in findings)

    def test_lock_blocking_good(self):
        findings, _ = vet_rules("good_lock_blocking.py")
        assert findings == []

    def test_template_bad_reproduces_reference_bug(self):
        findings, rules = vet_rules("bad_template.py")
        assert rules == {"template-copy"}
        # the buggy binding mutation + two direct .template. writes
        assert len(findings) == 3

    def test_template_good(self):
        findings, _ = vet_rules("good_template.py")
        assert findings == []

    def test_snapshot_bad(self):
        findings, rules = vet_rules("bad_snapshot.py")
        assert rules == {"snapshot-mutation"}
        assert len(findings) == 3  # direct, list-element mutator, alias

    def test_snapshot_good(self):
        findings, _ = vet_rules("good_snapshot.py")
        assert findings == []

    def test_misc_bad(self):
        findings, rules = vet_rules("bad_misc.py")
        assert rules == {"hot-path-deepcopy", "thread-hygiene",
                         "metric-prefix", "event-reason-style"}
        by_rule = {}
        for f in findings:
            by_rule.setdefault(f.rule, []).append(f)
        assert len(by_rule["thread-hygiene"]) == 2
        assert len(by_rule["event-reason-style"]) == 3  # constant + 2 calls

    def test_misc_good(self):
        findings, _ = vet_rules("good_misc.py")
        assert findings == []

    def test_inline_suppression(self):
        findings, _ = vet_rules("suppressed.py")
        assert findings == []

    def test_findings_carry_file_line_rule(self):
        findings, _ = vet_rules("bad_misc.py")
        rendered = [f.render() for f in findings]
        assert all(":" in r and "[" in r for r in rendered)

    def test_rawlock_bad(self):
        findings, rules = vet_rules("bad_rawlock.py")
        assert rules == {"raw-lock"}
        # module Lock, RLock, Condition, bare-imported Lock
        assert len(findings) == 4
        assert all("facade" in f.message for f in findings)

    def test_rawlock_good(self):
        findings, _ = vet_rules("good_rawlock.py")
        assert findings == []

    def test_sim_thread_per_object_bad(self):
        findings, rules = vet_rules("cluster/bad_simspawn.py")
        assert rules == {"sim-thread-per-object"}
        # Only the per-pod spawn is flagged; the start() loop thread is
        # the allowed fixed-thread shape.
        assert len(findings) == 1
        assert "_spawn" in findings[0].message

    def test_sim_thread_per_object_good(self):
        findings, _ = vet_rules("cluster/good_simspawn.py")
        assert findings == []

    def test_sim_thread_rule_scoped_to_simulated_paths(self):
        """The threaded FakeKubelet (cluster/kubelet.py) legitimately
        spawns per-pod threads for executed pods — the rule must not fire
        outside cluster/sim* modules."""
        findings = vet.run(
            [os.path.join(REPO_ROOT, "kubeflow_controller_tpu", "cluster",
                          "kubelet.py")],
            root=REPO_ROOT, skip_catalogue=True)
        assert not [f for f in findings if f.rule == "sim-thread-per-object"]

    def test_tenant_label_bad(self):
        findings, rules = vet_rules("bad_tenant.py")
        assert rules == {"tenant-label"}
        # guarded .get(LABEL_TENANT), annotation subscript, literal key
        assert len(findings) == 3
        assert all("tenant_of" in f.message for f in findings)

    def test_tenant_label_good(self):
        """Resolver calls, annotation WRITES (the planner's stamping),
        and non-tenant label reads all pass."""
        findings, _ = vet_rules("good_tenant.py")
        assert findings == []

    def test_tenant_label_resolver_itself_exempt(self):
        """api/tenant.py is the one place allowed to read the raw label."""
        findings = vet.run(
            [os.path.join(REPO_ROOT, "kubeflow_controller_tpu", "api",
                          "tenant.py")],
            root=REPO_ROOT, skip_catalogue=True)
        assert not [f for f in findings if f.rule == "tenant-label"]

    def test_lockgraph_bad_cycle_and_blocking(self):
        """The whole-program rule: an inversion split across two call
        chains and a blocking call one hop away — each function is
        locally clean, only the graph sees either bug."""
        findings, rules = vet_rules("bad_lockgraph.py")
        assert rules == {"lock-graph"}
        msgs = [f.message for f in findings]
        assert any("potential lock-order cycle" in m
                   and "fixture.accounts" in m and "fixture.audit" in m
                   for m in msgs)
        assert any("reaches blocking time.sleep" in m for m in msgs)
        assert len(findings) == 2

    def test_lockgraph_good(self):
        findings, _ = vet_rules("good_lockgraph.py")
        assert findings == []

    def test_lockgraph_suppression(self, tmp_path):
        src = (
            "from kubeflow_controller_tpu.utils import locks\n"
            "import time\n"
            "_a = locks.named_lock('tmp.a')\n"
            "def slow():\n"
            "    time.sleep(0.1)\n"
            "def run():\n"
            "    with _a:\n"
            "        slow()  # kctpu: vet-ok(lock-graph) - justified stall\n")
        mod = tmp_path / "suppressed_graph.py"
        mod.write_text(src)
        findings = vet.run([str(mod)], root=REPO_ROOT, skip_catalogue=True)
        assert findings == []

    def test_vet_json_output_schema(self, capsys):
        """`kctpu vet --json`: the stable machine-readable envelope."""
        import json

        rc = vet.main(["--json", "--no-catalogue",
                       os.path.join(FIXTURES, "bad_rawlock.py")])
        doc = json.loads(capsys.readouterr().out)
        assert rc == 1
        assert doc["tool"] == "kctpu-vet" and doc["schema_version"] == 1
        assert doc["clean"] is False and doc["files"] == 1
        f = doc["findings"][0]
        assert set(f) == {"path", "line", "col", "rule", "message"}
        assert f["rule"] == "raw-lock" and f["line"] > 0

    def test_vet_json_clean(self, capsys):
        import json

        rc = vet.main(["--json", "--no-catalogue",
                       os.path.join(FIXTURES, "good_rawlock.py")])
        doc = json.loads(capsys.readouterr().out)
        assert rc == 0
        assert doc["clean"] is True and doc["findings"] == []

    def test_repo_is_vet_clean(self):
        """The acceptance gate: `make vet` exits 0 on the repo — now
        including raw-lock (facade enforcement) and lock-graph (zero
        potential cycles / blocking-under-lock) repo-wide."""
        findings = vet.run(root=REPO_ROOT)
        assert findings == [], "\n".join(f.render() for f in findings)

    def test_repo_lock_graph_matches_known_order(self):
        """The static graph must at least see the store's documented
        nesting (shard -> meta) and the scheduler -> inventory order, and
        stay acyclic."""
        from kubeflow_controller_tpu.analysis.lockgraph import LockGraph
        from kubeflow_controller_tpu.analysis.vet import (
            DEFAULT_TARGETS, FileContext, iter_py_files)

        g = LockGraph()
        for path in iter_py_files([os.path.join(REPO_ROOT, t)
                                   for t in DEFAULT_TARGETS]):
            with open(path, encoding="utf-8") as fh:
                g.add_file(FileContext(path, fh.read()))
        edges, findings = g.analyze()
        assert findings == [], "\n".join(f.render() for f in findings)
        names = set(edges)
        assert ("store.shard:*", "store.meta") in names
        assert ("scheduler.gang-queue", "tpu.inventory") in names
        from kubeflow_controller_tpu.analysis.lockcheck import find_cycles
        graph = {}
        for a, b in names:
            graph.setdefault(a, set()).add(b)
        assert find_cycles(graph) == []

    def test_metric_catalogue_drift_detected(self, tmp_path):
        """A registered-but-undocumented metric is catalogue drift."""
        mod = tmp_path / "drifty.py"
        mod.write_text(
            "def reg(registry):\n"
            "    return registry.counter('kctpu_not_in_catalogue_total', 'x')\n")
        findings = vet.run([str(mod)], root=REPO_ROOT)
        assert any(f.rule == "metric-catalogue"
                   and "kctpu_not_in_catalogue_total" in f.message
                   for f in findings)


# ---------------------------------------------------------------------------
# Runtime lock-order detector
# ---------------------------------------------------------------------------

class _FakeLock:
    _reentrant = False

    def __init__(self, name, allow_blocking=False):
        self.name = name
        self.allow_blocking = allow_blocking
        self._owner = threading.get_ident()  # "held by this thread"


class TestLockcheck:
    def test_seeded_ab_ba_cycle_is_flagged(self):
        checker = lockcheck.LockChecker()
        a, b = _FakeLock("lock.A"), _FakeLock("lock.B")
        checker.acquired(a, False)
        checker.acquired(b, False)  # A -> B
        checker.released(b)
        checker.released(a)
        checker.acquired(b, False)
        checker.acquired(a, False)  # B -> A: the inversion
        checker.released(a)
        checker.released(b)
        report = checker.report()
        assert len(report.cycles) == 1
        assert set(report.cycles[0]) == {"lock.A", "lock.B"}
        assert not report.clean
        assert "LOCK-ORDER CYCLE" in report.render()
        # edges carry the first-seen site for the report
        assert all(site for site in report.edges.values())

    def test_consistent_order_is_clean(self):
        checker = lockcheck.LockChecker()
        a, b = _FakeLock("lock.A"), _FakeLock("lock.B")
        for _ in range(3):
            checker.acquired(a, False)
            checker.acquired(b, False)
            checker.released(b)
            checker.released(a)
        report = checker.report()
        assert report.clean and report.cycles == []
        assert ("lock.A", "lock.B") in report.edges

    def test_reentrant_reacquire_records_no_self_edge(self):
        checker = lockcheck.LockChecker()
        a = _FakeLock("lock.A")
        checker.acquired(a, False)
        checker.acquired(a, True)  # RLock re-entry
        checker.released(a)
        report = checker.report()
        assert report.edges == {} and report.clean

    def test_blocking_call_under_lock_detected(self):
        checker = lockcheck.LockChecker()
        a = _FakeLock("lock.A")
        checker.acquired(a, False)
        for _ in range(2):  # same call site: dedups into one, count=2
            checker.blocking_call("time.sleep")
        checker.released(a)
        checker.blocking_call("time.sleep")  # not held: no violation
        report = checker.report()
        assert len(report.blocking) >= 1
        v = report.blocking[0]
        assert v.what == "time.sleep" and v.held == ("lock.A",)
        assert v.count >= 2

    def test_blocking_ok_region_is_exempt(self):
        """locks.blocking_ok() declares a deliberate stall (tests freezing
        one shard's critical section on purpose): no violation inside,
        violations resume after."""
        checker = lockcheck.LockChecker()
        a = _FakeLock("lock.A")
        checker.acquired(a, False)
        with locks.blocking_ok():
            checker.blocking_call("time.sleep")
        assert checker.report().clean
        checker.blocking_call("time.sleep")
        checker.released(a)
        assert not checker.report().clean

    def test_allow_blocking_lock_is_exempt(self):
        checker = lockcheck.LockChecker()
        io = _FakeLock("warmpool.stdin", allow_blocking=True)
        checker.acquired(io, False)
        checker.blocking_call("subprocess.Popen")
        checker.released(io)
        assert checker.report().clean

    def test_patched_sleep_feeds_live_checker(self):
        """End to end through the facade: a real named lock held across a
        real (patched) time.sleep lands in the report."""
        import time as _time

        prev = locks.get_checker()
        fresh = lockcheck.installed() is None
        lockcheck.install()
        mine = lockcheck.LockChecker()
        locks.set_checker(mine)
        try:
            lk = locks.named_lock("test.sleepy")
            with lk:
                _time.sleep(0.001)
            report = mine.report()
            assert any(v.what == "time.sleep" and "test.sleepy" in v.held
                       for v in report.blocking)
        finally:
            locks.set_checker(prev)
            if fresh:
                lockcheck.uninstall()

    def test_real_nested_named_locks_record_edge(self):
        prev = locks.get_checker()
        mine = lockcheck.LockChecker()
        locks.set_checker(mine)
        try:
            outer = locks.named_lock("test.outer")
            inner = locks.named_lock("test.inner")
            with outer:
                with inner:
                    pass
            assert ("test.outer", "test.inner") in mine.report().edges
        finally:
            locks.set_checker(prev)

    def test_named_lock_condition_interop(self):
        """threading.Condition over a facade lock: notify/wait work and the
        held stack stays balanced through wait's release/reacquire."""
        prev = locks.get_checker()
        mine = lockcheck.LockChecker()
        locks.set_checker(mine)
        try:
            lk = locks.named_lock("test.cond")
            cond = threading.Condition(lk)
            hits = []

            def waiter():
                with cond:
                    while not hits:
                        cond.wait(timeout=2.0)
                    hits.append("woke")

            t = threading.Thread(target=waiter, name="cond-waiter", daemon=True)
            t.start()
            import time as _time
            _time.sleep(0.05)
            with cond:
                hits.append("set")
                cond.notify()
            t.join(timeout=2.0)
            assert not t.is_alive() and "woke" in hits
            assert mine.report().clean
        finally:
            locks.set_checker(prev)

    def test_detector_silent_on_real_concurrency(self):
        """The store scenario (writers/readers/watchers over named locks)
        must produce zero cycles and zero blocking-call violations."""
        prev = locks.get_checker()
        fresh = lockcheck.installed() is None
        lockcheck.install()
        mine = lockcheck.LockChecker()
        locks.set_checker(mine)
        try:
            interleave.scenario_store(0.3)
            report = mine.report()
            assert report.clean, report.render()
            assert report.acquires > 0
        finally:
            locks.set_checker(prev)
            if fresh:
                lockcheck.uninstall()

    def test_find_cycles_units(self):
        f = lockcheck.find_cycles
        assert f({"a": {"b"}, "b": {"c"}}) == []
        assert f({"a": {"b"}, "b": {"a"}}) == [["a", "b"]] or \
            f({"a": {"b"}, "b": {"a"}}) == [["b", "a"]]
        assert f({"a": {"a"}}) == [["a"]]
        three = f({"a": {"b"}, "b": {"c"}, "c": {"a"}})
        assert len(three) == 1 and set(three[0]) == {"a", "b", "c"}


# ---------------------------------------------------------------------------
# Schedule-fuzz harness
# ---------------------------------------------------------------------------

class TestInterleave:
    def test_seed_decisions_reproducible(self):
        """The race-smoke reproducibility contract: the decision stream is
        a pure function of (seed, thread name)."""
        a = interleave.ScheduleFuzzer(101)
        b = interleave.ScheduleFuzzer(101)
        assert a.decisions("worker-1", 64) == b.decisions("worker-1", 64)
        assert a.decisions("worker-1", 64) != a.decisions("worker-2", 64)
        assert (interleave.ScheduleFuzzer(101).decisions("w", 64)
                != interleave.ScheduleFuzzer(202).decisions("w", 64))

    def test_install_shrinks_switch_interval_and_uninstall_restores(self):
        import sys
        before = sys.getswitchinterval()
        try:
            interleave.install(7)
            assert sys.getswitchinterval() == pytest.approx(
                interleave.FUZZ_SWITCH_INTERVAL)
            assert locks.get_fuzzer() is not None
        finally:
            interleave.uninstall()
        assert sys.getswitchinterval() == pytest.approx(before)
        assert locks.get_fuzzer() is None

    def test_fuzzer_injects_yields_through_the_facade(self):
        try:
            fuzzer = interleave.install(31, p_yield=1.0, max_sleep_us=1.0)
            lk = locks.named_lock("test.fuzzed")
            for _ in range(10):
                with lk:
                    pass
            assert fuzzer.yields >= 10
        finally:
            interleave.uninstall()

    @pytest.mark.slow
    def test_run_seed_full_pass_clean(self):
        out = interleave.run_seed(101, duration_s=0.2)
        assert out["scenarios"] == {"store": True, "workqueue": True,
                                    "inventory": True}
        assert out["report"].clean, out["report"].render()
        assert out["yields"] > 0


# ---------------------------------------------------------------------------
# Planner shared-template regression (the reference bug)
# ---------------------------------------------------------------------------

class TestPlannerTemplateCopy:
    def _job(self):
        from kubeflow_controller_tpu.api.tfjob import (
            ReplicaType, TFJob, TFJobSpec, TFReplicaSpec)
        from kubeflow_controller_tpu.api.core import (
            Container, PodTemplateSpec)

        job = TFJob()
        job.metadata.namespace = "default"
        job.metadata.name = "tmpl-regress"
        tmpl = PodTemplateSpec()
        c = Container(name="tensorflow", command=["python"],
                      args=["--flag=base"])
        tmpl.spec.containers.append(c)
        spec = TFReplicaSpec(tf_replica_type=ReplicaType.WORKER, replicas=3,
                             template=tmpl)
        job.spec = TFJobSpec(tf_replica_specs=[spec])
        return job, spec

    def test_make_pod_leaves_spec_template_untouched(self):
        """Per-replica arg injection must land on a deep copy: building
        pods for indices 0..2 leaves the shared template bit-identical
        (the reference mutated it once per replica, design_doc.md:262-268)."""
        from kubeflow_controller_tpu.planner.materialize import make_pod
        from kubeflow_controller_tpu.utils import serde

        job, spec = self._job()
        before = serde.to_dict(spec.template)
        pods = [make_pod(job, spec, i) for i in range(3)]
        assert serde.to_dict(spec.template) == before
        # and the per-pod wiring really is per-pod, not accumulated
        args0 = pods[0].spec.containers[0].args
        args2 = pods[2].spec.containers[0].args
        assert args0 != args2  # distinct task indices injected
        assert spec.template.spec.containers[0].args == ["--flag=base"]

    def test_pods_do_not_share_container_objects(self):
        from kubeflow_controller_tpu.planner.materialize import make_pod

        job, spec = self._job()
        p0 = make_pod(job, spec, 0)
        p1 = make_pod(job, spec, 1)
        assert p0.spec.containers[0] is not p1.spec.containers[0]
        assert p0.spec.containers[0] is not spec.template.spec.containers[0]
        p0.spec.containers[0].args.append("--mutate")
        assert "--mutate" not in p1.spec.containers[0].args
        assert "--mutate" not in spec.template.spec.containers[0].args
