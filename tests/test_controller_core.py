"""Workqueue, expectations, informer, refmanager unit tests — the vendored-
primitive semantics of SURVEY.md §2.3, which are load-bearing for the
reconcile loop."""

import threading
import time

import pytest

from kubeflow_controller_tpu.api.core import Pod
from kubeflow_controller_tpu.api.meta import ObjectMeta, OwnerReference
from kubeflow_controller_tpu.api.tfjob import TFJob
from kubeflow_controller_tpu.cluster import Cluster
from kubeflow_controller_tpu.controller import (
    ControllerExpectations,
    RateLimitingQueue,
    RefManager,
    SharedInformer,
    ShutDown,
)


def drain(q, n, timeout=2.0):
    out = []
    for _ in range(n):
        item = q.get(timeout=timeout)
        if item is None:
            break
        out.append(item)
    return out


# ---- workqueue ----

def test_queue_dedups_while_queued():
    q = RateLimitingQueue()
    q.add("a")
    q.add("a")
    q.add("b")
    assert q.get() == "a"
    assert q.get() == "b"
    q.done("a")
    q.done("b")
    assert q.get(timeout=0.05) is None


def test_queue_requeues_item_added_during_processing():
    q = RateLimitingQueue()
    q.add("a")
    item = q.get()
    q.add("a")  # while processing: must not be delivered concurrently
    assert q.get(timeout=0.05) is None
    q.done(item)
    assert q.get(timeout=0.5) == "a"


def test_queue_rate_limited_backoff_and_forget():
    q = RateLimitingQueue()
    q.add_rate_limited("x")  # failure #1: ~base delay
    assert q.get(timeout=1.0) == "x"
    q.done("x")
    assert q.num_requeues("x") == 1
    q.forget("x")
    assert q.num_requeues("x") == 0


def test_queue_shutdown_raises():
    q = RateLimitingQueue()
    results = []

    def worker():
        try:
            q.get()
        except ShutDown:
            results.append("shutdown")

    t = threading.Thread(target=worker)
    t.start()
    time.sleep(0.05)
    q.shut_down()
    t.join(timeout=1)
    assert results == ["shutdown"]


# ---- expectations ----

def test_expectations_lifecycle():
    e = ControllerExpectations()
    key = "ns/job"
    assert e.satisfied_expectations(key)  # no record -> sync
    e.expect_creations(key, 2)
    assert not e.satisfied_expectations(key)
    e.creation_observed(key)
    assert not e.satisfied_expectations(key)
    e.creation_observed(key)
    assert e.satisfied_expectations(key)
    # Over-observation (watch races) keeps it satisfied.
    e.creation_observed(key)
    assert e.satisfied_expectations(key)


def test_expectations_ttl_expiry():
    e = ControllerExpectations(ttl_s=0.05)
    e.expect_creations("k", 5)
    assert not e.satisfied_expectations("k")
    time.sleep(0.08)
    assert e.satisfied_expectations("k")  # expired -> sync anyway


def test_expectations_combined_and_lower():
    e = ControllerExpectations()
    e.expect("k", adds=1, dels=1)
    assert not e.satisfied_expectations("k")
    e.lower_expectations("k", add_delta=1)
    assert not e.satisfied_expectations("k")
    e.deletion_observed("k")
    assert e.satisfied_expectations("k")


# ---- informer ----

def test_informer_sync_add_update_delete_and_cache():
    c = Cluster()
    c.tfjobs.create(TFJob(metadata=ObjectMeta(name="pre", namespace="ns")))
    adds, updates, deletes = [], [], []
    inf = SharedInformer(c.tfjobs, resync_period_s=0, name="t")
    inf.add_event_handler(
        on_add=lambda o: adds.append(o.metadata.name),
        on_update=lambda o, n: updates.append(n.metadata.name),
        on_delete=lambda o: deletes.append(o.metadata.name),
    )
    assert not inf.has_synced
    inf.start()
    assert inf.has_synced
    assert adds == ["pre"]
    assert inf.get("ns", "pre") is not None

    c.tfjobs.create(TFJob(metadata=ObjectMeta(name="post", namespace="ns")))
    j = c.tfjobs.get("ns", "post")
    c.tfjobs.update(j)
    c.tfjobs.delete("ns", "post")

    deadline = time.time() + 2
    while time.time() < deadline and "post" not in deletes:
        time.sleep(0.01)
    assert "post" in adds and "post" in updates and "post" in deletes
    assert inf.get("ns", "post") is None
    inf.stop()


def test_informer_resync_refires_updates():
    c = Cluster()
    c.tfjobs.create(TFJob(metadata=ObjectMeta(name="j", namespace="ns")))
    updates = []
    inf = SharedInformer(c.tfjobs, resync_period_s=0.05, name="t")
    inf.add_event_handler(on_update=lambda o, n: updates.append(n.metadata.resource_version))
    inf.start()
    time.sleep(0.2)
    inf.stop()
    assert len(updates) >= 2
    # Resync delivers old == new (same resourceVersion).
    assert all(rv == updates[0] for rv in updates)


# ---- ref manager ----

def _mk_owner(c, name="job"):
    return c.tfjobs.create(TFJob(metadata=ObjectMeta(name=name, namespace="ns")))


def _mk_pod(c, name, labels=None, owner=None):
    p = Pod(metadata=ObjectMeta(name=name, namespace="ns", labels=labels or {}))
    p.spec.containers = []
    if owner is not None:
        p.metadata.owner_references.append(
            OwnerReference(kind="TFJob", name=owner.metadata.name,
                           uid=owner.metadata.uid, controller=True,
                           block_owner_deletion=True)
        )
    return c.pods.create(p)


def _mgr(c, owner, selector):
    def can_adopt():
        fresh = c.tfjobs.get("ns", owner.metadata.name)
        if fresh.metadata.uid != owner.metadata.uid:
            raise RuntimeError("uid changed")

    return RefManager(c.pods, owner.metadata, "TFJob", "kubeflow.caicloud.io/v1alpha1",
                      selector, can_adopt)


def test_refmanager_adopts_matching_orphan():
    c = Cluster()
    owner = _mk_owner(c)
    _mk_pod(c, "orphan", labels={"app": "x"})
    claimed = _mgr(c, owner, {"app": "x"}).claim(c.pods.list("ns"))
    assert [p.metadata.name for p in claimed] == ["orphan"]
    stored = c.pods.get("ns", "orphan")
    assert stored.metadata.owner_references[0].uid == owner.metadata.uid


def test_refmanager_releases_owned_nonmatching():
    c = Cluster()
    owner = _mk_owner(c)
    _mk_pod(c, "mine", labels={"app": "other"}, owner=owner)
    claimed = _mgr(c, owner, {"app": "x"}).claim(c.pods.list("ns"))
    assert claimed == []
    assert c.pods.get("ns", "mine").metadata.owner_references == []


def test_refmanager_skips_foreign_and_keeps_matching():
    c = Cluster()
    owner = _mk_owner(c, "a")
    other = _mk_owner(c, "b")
    _mk_pod(c, "foreign", labels={"app": "x"}, owner=other)
    _mk_pod(c, "mine", labels={"app": "x"}, owner=owner)
    claimed = _mgr(c, owner, {"app": "x"}).claim(c.pods.list("ns"))
    assert [p.metadata.name for p in claimed] == ["mine"]
    # Foreign pod untouched.
    assert c.pods.get("ns", "foreign").metadata.owner_references[0].uid == other.metadata.uid


def test_refmanager_adoption_vetoed_on_stale_uid():
    c = Cluster()
    owner = _mk_owner(c)
    _mk_pod(c, "orphan", labels={"app": "x"})
    # Delete and recreate the job under the same name: new UID.
    c.tfjobs.delete("ns", "job")
    _mk_owner(c)
    with pytest.raises(RuntimeError, match="uid changed"):
        _mgr(c, owner, {"app": "x"}).claim(c.pods.list("ns"))
    assert c.pods.get("ns", "orphan").metadata.owner_references == []
