"""Status updater tests: phase rules, histograms, conditions, chief policy."""

from kubeflow_controller_tpu.api.core import (
    PHASE_FAILED,
    PHASE_PENDING,
    PHASE_RUNNING,
    PHASE_SUCCEEDED,
)
from kubeflow_controller_tpu.api.tfjob import (
    ChiefSpec,
    ReplicaType,
    TerminationPolicySpec,
    TFJobConditionType,
    TFJobPhase,
    TFReplicaState,
)
from kubeflow_controller_tpu.checker import check_health
from kubeflow_controller_tpu.checker.health import Health
from kubeflow_controller_tpu.updater import compute_status, should_update

from test_planner import mk_job, mk_pod


def cond(status, ctype):
    return next(c for c in status.conditions if c.type == ctype)


def test_fresh_job_pending_and_unscheduled():
    job = mk_job((ReplicaType.WORKER, 2))
    st = compute_status(job, {})
    assert st.phase == TFJobPhase.PENDING
    assert cond(st, TFJobConditionType.SCHEDULED).status == "False"


def test_running_then_succeeded_workers_ps_ignored():
    job = mk_job((ReplicaType.PS, 1), (ReplicaType.WORKER, 2))
    pods = {
        ReplicaType.WORKER: [mk_pod(job, ReplicaType.WORKER, i, PHASE_RUNNING) for i in range(2)],
        ReplicaType.PS: [mk_pod(job, ReplicaType.PS, 0, PHASE_RUNNING)],
    }
    st = compute_status(job, pods)
    assert st.phase == TFJobPhase.RUNNING
    assert cond(st, TFJobConditionType.READY).status == "True"
    # The READY message carries the per-replica health report.
    assert "Worker=Healthy 2/2 running" in cond(st, TFJobConditionType.READY).message
    # All workers done; PS still running -> Succeeded (ref: distributed.go:51-55).
    pods[ReplicaType.WORKER] = [
        mk_pod(job, ReplicaType.WORKER, i, PHASE_SUCCEEDED) for i in range(2)
    ]
    job.status = st
    st2 = compute_status(job, pods)
    assert st2.phase == TFJobPhase.SUCCEEDED
    assert cond(st2, TFJobConditionType.RECYCLING).status == "True"  # PS alive


def test_histograms_states_and_podnames_populated():
    job = mk_job((ReplicaType.WORKER, 2))
    pods = {ReplicaType.WORKER: [
        mk_pod(job, ReplicaType.WORKER, 0, PHASE_RUNNING, name="w0"),
        mk_pod(job, ReplicaType.WORKER, 1, PHASE_PENDING, name="w1"),
    ]}
    st = compute_status(job, pods)
    rs = st.tf_replica_statuses[0]
    assert rs.type == ReplicaType.WORKER
    assert rs.tf_replicas_states == {TFReplicaState.RUNNING: 1, TFReplicaState.WAITING: 1}
    assert rs.pod_names == ["w0", "w1"]  # never populated upstream
    assert rs.state == TFReplicaState.RUNNING


def test_terminal_failure_sets_failed_phase():
    # restartPolicy=Never + Failed pod -> phase Failed (never set upstream).
    job = mk_job((ReplicaType.WORKER, 1), restart="Never")
    pods = {ReplicaType.WORKER: [mk_pod(job, ReplicaType.WORKER, 0, PHASE_FAILED)]}
    st = compute_status(job, pods)
    assert st.phase == TFJobPhase.FAILED


def test_replaceable_failure_is_recovering_not_failed():
    job = mk_job((ReplicaType.WORKER, 1), restart="OnFailure")
    pods = {ReplicaType.WORKER: [mk_pod(job, ReplicaType.WORKER, 0, PHASE_FAILED)]}
    st = compute_status(job, pods)
    assert st.phase in (TFJobPhase.PENDING, TFJobPhase.RUNNING)
    assert cond(st, TFJobConditionType.RECOVERING).status == "True"


def test_chief_policy_decides_termination():
    job = mk_job((ReplicaType.PS, 1), (ReplicaType.WORKER, 3))
    job.spec.tf_replica_specs[1].termination_policy = TerminationPolicySpec(
        chief=ChiefSpec(tf_replica_name="Worker", tf_replica_index=0)
    )
    pods = {
        ReplicaType.WORKER: [
            mk_pod(job, ReplicaType.WORKER, 0, PHASE_SUCCEEDED),
            mk_pod(job, ReplicaType.WORKER, 1, PHASE_RUNNING),
            mk_pod(job, ReplicaType.WORKER, 2, PHASE_RUNNING),
        ],
        ReplicaType.PS: [mk_pod(job, ReplicaType.PS, 0, PHASE_RUNNING)],
    }
    st = compute_status(job, pods)
    assert st.phase == TFJobPhase.SUCCEEDED  # chief done, others still running


def test_terminal_phase_sticky():
    job = mk_job((ReplicaType.WORKER, 1))
    job.status.phase = TFJobPhase.SUCCEEDED
    st = compute_status(job, {})
    assert st.phase == TFJobPhase.SUCCEEDED


def test_should_update_semantic_comparison():
    job = mk_job((ReplicaType.WORKER, 1))
    pods = {ReplicaType.WORKER: [mk_pod(job, ReplicaType.WORKER, 0, PHASE_RUNNING)]}
    st1 = compute_status(job, pods)
    job.status = st1
    st2 = compute_status(job, pods)
    assert not should_update(st1, st2)  # no-op recompute writes nothing
    pods[ReplicaType.WORKER][0].status.phase = PHASE_SUCCEEDED
    st3 = compute_status(job, pods)
    assert should_update(st1, st3)


def test_tpu_job_succeeds_when_all_hosts_done():
    job = mk_job((ReplicaType.TPU, 2))
    pods = {ReplicaType.TPU: [
        mk_pod(job, ReplicaType.TPU, i, PHASE_SUCCEEDED) for i in range(2)
    ]}
    st = compute_status(job, pods)
    assert st.phase == TFJobPhase.SUCCEEDED


# ---- health checker ----

def test_health_report():
    job = mk_job((ReplicaType.WORKER, 2))
    pods = {ReplicaType.WORKER: [mk_pod(job, ReplicaType.WORKER, 0, PHASE_RUNNING)]}
    h = check_health(job, pods)
    rh = h.replicas[ReplicaType.WORKER]
    assert rh.running == 1 and rh.missing_indices == [1]
    assert rh.health == Health.DEGRADED
    pods[ReplicaType.WORKER].append(mk_pod(job, ReplicaType.WORKER, 1, PHASE_RUNNING))
    assert check_health(job, pods).overall == Health.HEALTHY
