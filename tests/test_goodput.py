"""Goodput-ledger tests (ISSUE 18; obs/goodput.py + its surfaces).

Covers the taxonomy decision (bucket_for), the pod ledger's
contiguous-interval invariant (sum of buckets == wall-time, always),
compile re-attribution on late provenance, retired-pod folding, the
failover bootstrap's exact-once seed, metric series lifecycle
(publish deltas stay monotonic, drop removes every series), the
DIRECTION_BELOW burn-rate objectives, the phase-registry vet rule,
status serde, and the CLI surfaces (`get` good= suffix, `top` GOODPUT
column, `kctpu goodput`).  The end-to-end attribution gates live in
bench.py --goodput (`make goodput-smoke`)."""

import json
import os
import time

import pytest

from kubeflow_controller_tpu.analysis import vet
from kubeflow_controller_tpu.api.core import Container, PodTemplateSpec
from kubeflow_controller_tpu.api.meta import ObjectMeta
from kubeflow_controller_tpu.api.tfjob import (
    JobGoodput,
    JobProgress,
    ReplicaProgress,
    ReplicaType,
    TFJob,
    TFJobPhase,
    TFJobStatus,
    TFReplicaSpec,
)
from kubeflow_controller_tpu.cluster import Cluster
from kubeflow_controller_tpu.cluster.apiserver import FakeAPIServer
from kubeflow_controller_tpu.obs import phases as P
from kubeflow_controller_tpu.obs.goodput import (
    MAX_RETIRED_PODS,
    GoodputTracker,
    JobLedger,
    PodLedger,
    PodObservation,
    bucket_for,
)
from kubeflow_controller_tpu.obs.metrics import Registry
from kubeflow_controller_tpu.obs.slo import (
    DIRECTION_ABOVE,
    DIRECTION_BELOW,
    Objective,
    SLOEngine,
    default_objectives,
)
from kubeflow_controller_tpu.obs.tsdb import TSDB
from kubeflow_controller_tpu.utils import serde

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_obs(pod_phase="Running", reason="", start_mode="", beat_phase=None,
            compile_source="", stalled=False, name="p0"):
    return PodObservation(name=name, pod_phase=pod_phase, reason=reason,
                          start_mode=start_mode, beat_phase=beat_phase,
                          compile_source=compile_source, stalled=stalled)


# ---------------------------------------------------------------------------
# The taxonomy decision
# ---------------------------------------------------------------------------

class TestBucketFor:
    @pytest.mark.parametrize("obs,bucket", [
        # Control-plane states.
        (run_obs("Pending", reason="GangQueued: position 2/5"),
         P.BUCKET_QUEUED),
        (run_obs("Pending"), P.BUCKET_SCHEDULING),
        (run_obs("Failed", reason="Preempted: 2 slice(s) to gang x"),
         P.BUCKET_PREEMPTED),
        (run_obs("Failed", reason="WidthHarvested: 1 slice(s) harvested"),
         P.BUCKET_HARVESTED),
        (run_obs("Failed", reason="Error: OOM"), P.BUCKET_TERMINAL),
        (run_obs("Succeeded"), P.BUCKET_TERMINAL),
        # Running, pre-first-beat: the start-mode annotation decides.
        (run_obs(beat_phase=None), P.BUCKET_STARTING_COLD),
        (run_obs(beat_phase=None, start_mode="cold"),
         P.BUCKET_STARTING_COLD),
        (run_obs(beat_phase=None, start_mode="warm"),
         P.BUCKET_STARTING_WARM),
        # Running + beating: the beat phase maps through obs/phases.py.
        (run_obs(beat_phase=P.PHASE_FIT), P.BUCKET_TRAIN),
        (run_obs(beat_phase=P.PHASE_SERVING), P.BUCKET_SERVING),
        (run_obs(beat_phase=P.PHASE_RENDEZVOUS), P.BUCKET_RENDEZVOUS),
        (run_obs(beat_phase=P.PHASE_INIT), P.BUCKET_RENDEZVOUS),
        (run_obs(beat_phase=P.PHASE_COMPILE), P.BUCKET_COMPILE_MISS),
        (run_obs(beat_phase=P.PHASE_COMPILE, compile_source="cache-hit"),
         P.BUCKET_COMPILE_CACHED),
        (run_obs(beat_phase=P.PHASE_RESTORE), P.BUCKET_RESTORE),
        (run_obs(beat_phase=P.PHASE_LOAD), P.BUCKET_RESTORE),
        (run_obs(beat_phase=P.PHASE_RESHARD), P.BUCKET_RESHARD),
        (run_obs(beat_phase=P.PHASE_DRAIN), P.BUCKET_DRAIN),
        # Empty/unknown phase on a beating replica counts as train.
        (run_obs(beat_phase=""), P.BUCKET_TRAIN),
        (run_obs(beat_phase="no-such-phase"), P.BUCKET_TRAIN),
    ])
    def test_taxonomy(self, obs, bucket):
        assert bucket_for(obs) == bucket

    def test_stall_verdict_overrides_beat(self):
        obs = run_obs(beat_phase=P.PHASE_FIT, stalled=True)
        assert bucket_for(obs) == P.BUCKET_STALLED

    def test_unknown_pod_phase_holds_interval_open(self):
        assert bucket_for(run_obs(pod_phase="Unknown")) is None

    def test_every_decision_lands_in_the_closed_taxonomy(self):
        cases = [
            run_obs("Pending", reason="GangQueued: q"), run_obs("Pending"),
            run_obs("Failed", reason="Preempted: x"),
            run_obs("Failed", reason="WidthHarvested: x"),
            run_obs("Failed"), run_obs("Succeeded"),
            run_obs(beat_phase=None, start_mode="warm"),
            run_obs(beat_phase=None),
            run_obs(stalled=True, beat_phase=P.PHASE_FIT),
        ] + [run_obs(beat_phase=ph) for ph in sorted(P.KNOWN_PHASES)]
        for obs in cases:
            assert bucket_for(obs) in P.ALL_BUCKETS


# ---------------------------------------------------------------------------
# PodLedger: the contiguous-interval invariant
# ---------------------------------------------------------------------------

class TestPodLedger:
    def test_attributed_equals_wall_across_transitions(self):
        led = PodLedger(100.0)
        script = [
            (100.0, run_obs("Pending", reason="GangQueued: q")),
            (103.0, run_obs("Pending")),
            (104.0, run_obs(beat_phase=None)),
            (105.5, run_obs(beat_phase=P.PHASE_RENDEZVOUS)),
            (107.0, run_obs(beat_phase=P.PHASE_COMPILE)),
            (111.0, run_obs(beat_phase=P.PHASE_FIT,
                            compile_source="compiled")),
            (120.0, run_obs(beat_phase=P.PHASE_FIT, stalled=True)),
            (121.0, run_obs("Succeeded")),
        ]
        for now, obs in script:
            led.observe(obs, now)
            assert led.attributed_s(now) == pytest.approx(led.wall_s(now))
        t = led.snapshot(125.0)
        assert led.attributed_s(125.0) == pytest.approx(led.wall_s(125.0))
        assert sum(t.values()) == pytest.approx(25.0)  # 100.0 -> 125.0
        assert t[P.BUCKET_QUEUED] == pytest.approx(3.0)
        assert t[P.BUCKET_SCHEDULING] == pytest.approx(1.0)
        assert t[P.BUCKET_STARTING_COLD] == pytest.approx(1.5)
        assert t[P.BUCKET_RENDEZVOUS] == pytest.approx(1.5)
        assert t[P.BUCKET_COMPILE_MISS] == pytest.approx(4.0)
        assert t[P.BUCKET_TRAIN] == pytest.approx(9.0)
        assert t[P.BUCKET_STALLED] == pytest.approx(1.0)
        # Succeeded keeps accruing terminal until retired/observed away.
        assert t[P.BUCKET_TERMINAL] == pytest.approx(4.0)

    def test_retire_freezes_the_books(self):
        led = PodLedger(0.0)
        led.observe(run_obs(beat_phase=P.PHASE_FIT), 0.0)
        led.retire(10.0)
        assert led.snapshot(50.0) == {P.BUCKET_TRAIN: pytest.approx(10.0)}
        assert led.wall_s(50.0) == pytest.approx(10.0)
        # Further observes/retires are no-ops once the books are closed.
        led.observe(run_obs(beat_phase=P.PHASE_SERVING), 60.0)
        led.retire(70.0)
        assert led.snapshot(80.0) == {P.BUCKET_TRAIN: pytest.approx(10.0)}

    def test_clock_running_backward_never_negates(self):
        led = PodLedger(100.0)
        led.observe(run_obs(beat_phase=P.PHASE_FIT), 100.0)
        led.observe(run_obs(beat_phase=P.PHASE_RENDEZVOUS), 95.0)  # skewed
        t = led.snapshot(101.0)
        assert all(v >= 0.0 for v in t.values())
        assert led.attributed_s(101.0) == pytest.approx(led.wall_s(101.0))

    def test_cache_hit_reattributes_accrued_compile_time(self):
        led = PodLedger(0.0)
        led.observe(run_obs(beat_phase=P.PHASE_COMPILE), 0.0)
        led.observe(run_obs(beat_phase=P.PHASE_COMPILE), 4.0)
        # Provenance arrives WITH the transition out of compile — the
        # whole accrued episode moves to compile_cached.
        led.observe(run_obs(beat_phase=P.PHASE_FIT,
                            compile_source="cache-hit"), 5.0)
        t = led.snapshot(5.0)
        assert t.get(P.BUCKET_COMPILE_MISS, 0.0) == pytest.approx(0.0)
        assert t[P.BUCKET_COMPILE_CACHED] == pytest.approx(5.0)
        assert sum(t.values()) == pytest.approx(led.wall_s(5.0))

    def test_compiled_provenance_stays_compile_miss(self):
        led = PodLedger(0.0)
        led.observe(run_obs(beat_phase=P.PHASE_COMPILE), 0.0)
        led.observe(run_obs(beat_phase=P.PHASE_FIT,
                            compile_source="compiled"), 5.0)
        t = led.snapshot(5.0)
        assert t[P.BUCKET_COMPILE_MISS] == pytest.approx(5.0)
        assert P.BUCKET_COMPILE_CACHED not in t

    def test_abandoned_compile_episode_does_not_transfer_later(self):
        led = PodLedger(0.0)
        led.observe(run_obs(beat_phase=P.PHASE_COMPILE), 0.0)
        # Left compile with NO provenance: the unresolved accrual resets,
        # so a much later cache-hit beat cannot re-attribute it.
        led.observe(run_obs(beat_phase=P.PHASE_FIT), 3.0)
        led.observe(run_obs(beat_phase=P.PHASE_FIT,
                            compile_source="cache-hit"), 10.0)
        t = led.snapshot(10.0)
        assert t[P.BUCKET_COMPILE_MISS] == pytest.approx(3.0)
        assert P.BUCKET_COMPILE_CACHED not in t


# ---------------------------------------------------------------------------
# JobLedger: vanish-retire + bounded retired set
# ---------------------------------------------------------------------------

class TestJobLedger:
    def test_vanished_pod_is_retired(self):
        jl = JobLedger()
        jl.observe([run_obs(name="a", beat_phase=P.PHASE_FIT),
                    run_obs(name="b", beat_phase=P.PHASE_FIT)], 0.0)
        jl.observe([run_obs(name="b", beat_phase=P.PHASE_FIT)], 4.0)
        assert jl.pods["a"].retired_at == 4.0
        assert jl.pods["b"].retired_at is None
        # Retired wall is frozen; the survivor keeps accruing.
        t = jl.bucket_totals(10.0)
        assert t[P.BUCKET_TRAIN] == pytest.approx(4.0 + 10.0)

    def test_retired_overflow_folds_into_carried(self):
        jl = JobLedger()
        n = MAX_RETIRED_PODS + 6
        t = 0.0
        for i in range(n):
            jl.observe([run_obs(name=f"p{i}", beat_phase=P.PHASE_FIT)], t)
            t += 1.0
        jl.observe([], t)  # retire the last one too
        assert len(jl.retired_order) == MAX_RETIRED_PODS
        assert len(jl.pods) == MAX_RETIRED_PODS
        # Nothing lost in the fold: every second is still on the books.
        totals = jl.bucket_totals(t)
        assert totals[P.BUCKET_TRAIN] == pytest.approx(float(n))
        assert jl.carried[P.BUCKET_TRAIN] == pytest.approx(
            float(n - MAX_RETIRED_PODS))

    def test_summary_ratio_and_occupancy(self):
        jl = JobLedger()
        jl.observe([run_obs(name="a",
                            pod_phase="Pending",
                            reason="GangQueued: q")], 0.0)
        jl.observe([run_obs(name="a", beat_phase=P.PHASE_RENDEZVOUS)], 10.0)
        jl.observe([run_obs(name="a", beat_phase=P.PHASE_FIT)], 14.0)
        s = jl.summary(26.0)
        assert s.wall_s == pytest.approx(26.0)
        # Queue time is excluded from the denominator.
        assert s.occupied_s == pytest.approx(16.0)
        assert s.goodput_s == pytest.approx(12.0)
        assert s.ratio == pytest.approx(0.75)
        assert s.replicas == 1


# ---------------------------------------------------------------------------
# GoodputTracker: bootstrap, metric lifecycle, cluster rollup
# ---------------------------------------------------------------------------

def badput_samples(reg, ns="default", job="j"):
    fams = {f.name: f for f in reg.families()}
    fam = fams.get("kctpu_badput_seconds_total")
    if fam is None:
        return {}
    return {s.labels["bucket"]: s.value for s in fam.samples
            if s.labels.get("namespace") == ns and s.labels.get("tfjob") == job}


def ratio_samples(reg):
    fams = {f.name: f for f in reg.families()}
    fam = fams.get("kctpu_goodput_ratio")
    return {} if fam is None else {
        (s.labels["namespace"], s.labels["tfjob"]): s.value
        for s in fam.samples}


class TestGoodputTracker:
    def test_bootstrap_seeds_carried_totals_once(self):
        tr = GoodputTracker(registry=Registry())
        tr.bootstrap("default", "j", {
            "train": 30, "rendezvous": 10.0,
            "no-such-bucket": 7.0, "queued": 0.0})
        s = tr.summary("default", "j", 1000.0)
        assert s is not None
        assert s.wall_s == pytest.approx(40.0)   # junk + zero filtered
        assert s.goodput_s == pytest.approx(30.0)
        assert s.ratio == pytest.approx(0.75)
        # A second seed would double-count — it must be a no-op.
        tr.bootstrap("default", "j", {"train": 999.0})
        assert tr.summary("default", "j", 1000.0).wall_s == pytest.approx(40.0)

    def test_bootstrap_after_observe_is_noop(self):
        tr = GoodputTracker(registry=Registry())
        tr.observe("default", "j",
                   [run_obs(beat_phase=P.PHASE_FIT)], 0.0)
        tr.bootstrap("default", "j", {"train": 500.0})
        assert tr.summary("default", "j", 10.0).wall_s == pytest.approx(10.0)

    def test_failover_is_exact_once(self):
        """Controller A's persisted rollup seeds controller B: the union
        accounts every second exactly once."""
        a = GoodputTracker(registry=Registry())
        a.observe("default", "j", [run_obs(beat_phase=P.PHASE_RENDEZVOUS)],
                  0.0)
        a.observe("default", "j", [run_obs(beat_phase=P.PHASE_FIT)], 6.0)
        handoff = a.summary("default", "j", 20.0)   # what status.goodput held
        b = GoodputTracker(registry=Registry())
        b.bootstrap("default", "j", dict(handoff.buckets))
        b.observe("default", "j", [run_obs(beat_phase=P.PHASE_FIT)], 20.0)
        s = b.summary("default", "j", 30.0)
        assert s.wall_s == pytest.approx(30.0)
        assert s.buckets[P.BUCKET_RENDEZVOUS] == pytest.approx(6.0)
        assert s.goodput_s == pytest.approx(24.0)

    def test_publish_counter_stays_monotonic(self):
        reg = Registry()
        tr = GoodputTracker(registry=reg)
        tr.observe("default", "j", [run_obs(beat_phase=P.PHASE_RENDEZVOUS)],
                   0.0)
        tr.observe("default", "j", [run_obs(beat_phase=P.PHASE_FIT)], 6.0)
        tr.publish("default", "j", 6.0)
        assert badput_samples(reg)["rendezvous"] == pytest.approx(6.0)
        # Re-publishing with no new badput must not re-add the cumulative.
        tr.publish("default", "j", 6.0)
        tr.publish("default", "j", 12.0)
        assert badput_samples(reg)["rendezvous"] == pytest.approx(6.0)
        # Goodput/non-occupied buckets never become counter series.
        assert set(badput_samples(reg)) == {"rendezvous"}
        assert ratio_samples(reg)[("default", "j")] == pytest.approx(0.5)

    def test_ratio_gauge_waits_for_warmup(self):
        reg = Registry()
        tr = GoodputTracker(registry=reg)
        tr.observe("default", "j", [run_obs(beat_phase=P.PHASE_FIT)], 0.0)
        tr.publish("default", "j", 2.0)  # occupied 2s < RATIO_WARMUP_S
        assert ("default", "j") not in ratio_samples(reg)
        tr.publish("default", "j", 30.0)
        assert ratio_samples(reg)[("default", "j")] == pytest.approx(1.0)

    def test_drop_removes_state_and_every_series(self):
        reg = Registry()
        tr = GoodputTracker(registry=reg)
        tr.observe("default", "j", [run_obs(beat_phase=P.PHASE_RENDEZVOUS)],
                   0.0)
        tr.observe("default", "j", [run_obs(beat_phase=P.PHASE_FIT)], 6.0)
        tr.publish("default", "j", 10.0)
        assert badput_samples(reg) and ratio_samples(reg)
        tr.drop("default", "j")
        assert tr.summary("default", "j", 20.0) is None
        assert not tr.has_job("default", "j")
        assert badput_samples(reg) == {}
        assert ratio_samples(reg) == {}

    def test_cluster_ratio_warmup_is_one(self):
        tr = GoodputTracker(registry=Registry())
        assert tr.cluster_ratio() == 1.0  # empty cluster burns no badput
        tr.observe("default", "j", [run_obs(beat_phase=P.PHASE_FIT)],
                   time.time() - 1.0)
        assert tr.cluster_ratio() == 1.0  # under RATIO_WARMUP_S occupied

    def test_cluster_ratio_weights_by_occupied_time(self):
        tr = GoodputTracker(registry=Registry())
        t0 = time.time() - 20.0
        tr.observe("default", "good",
                   [run_obs(name="a", beat_phase=P.PHASE_FIT)], t0)
        tr.observe("default", "bad",
                   [run_obs(name="b", beat_phase=P.PHASE_RENDEZVOUS)], t0)
        # ~20s train vs ~20s rendezvous -> ratio ~0.5.
        assert 0.4 < tr.cluster_ratio() < 0.6

    def test_snapshot_is_flight_bundle_shaped(self):
        tr = GoodputTracker(registry=Registry())
        tr.observe("default", "j", [run_obs(beat_phase=P.PHASE_FIT)], 0.0)
        snap = tr.snapshot("default", "j", 8.0)
        assert snap["wall_s"] == pytest.approx(8.0)
        assert snap["buckets"] == {P.BUCKET_TRAIN: pytest.approx(8.0)}
        assert snap["pods"]["p0"]["bucket"] == P.BUCKET_TRAIN
        assert not snap["pods"]["p0"]["retired"]
        assert json.dumps(snap)  # must serialize into goodput.json as-is
        assert tr.snapshot("default", "nope", 8.0) == {}


# ---------------------------------------------------------------------------
# Status surface serde
# ---------------------------------------------------------------------------

class TestGoodputStatusSerde:
    def test_round_trip(self):
        st = TFJobStatus(phase=TFJobPhase.RUNNING)
        st.goodput = JobGoodput(goodput_s=80, occupied_s=100, wall_s=130,
                                ratio=0.8,
                                buckets={"train": 80, "rendezvous": 12,
                                         "queued": 30})
        wire = json.loads(json.dumps(serde.to_dict(st)))
        back = serde.from_dict(TFJobStatus, wire)
        assert back.goodput == st.goodput

    def test_absent_stays_none(self):
        wire = json.loads(json.dumps(serde.to_dict(TFJobStatus())))
        assert serde.from_dict(TFJobStatus, wire).goodput is None


# ---------------------------------------------------------------------------
# DIRECTION_BELOW objectives (the goodput SLOs)
# ---------------------------------------------------------------------------

def mk_ratio_rig():
    reg = Registry()
    g = reg.gauge("kctpu_cluster_goodput_ratio", "test")
    db = TSDB(registry=reg, retention_s=300.0)
    obj = Objective(
        name="cluster-goodput", description="cluster goodput >= 0.5",
        metric="kctpu_cluster_goodput_ratio", threshold=0.5,
        direction=DIRECTION_BELOW, error_budget=0.2,
        fast_window_s=10.0, slow_window_s=30.0, burn_threshold=2.0,
        subject_labels=())
    edges = []
    eng = SLOEngine(db, objectives=[obj], registry=reg,
                    notifier=lambda st, fired: edges.append(fired))
    return g, db, eng, edges


class TestGoodputSLO:
    def test_violates_respects_direction(self):
        below = Objective(name="x", description="", metric="m",
                          threshold=0.5, direction=DIRECTION_BELOW)
        above = Objective(name="y", description="", metric="m",
                          threshold=0.5, direction=DIRECTION_ABOVE)
        assert below.violates(0.4) and not below.violates(0.6)
        assert above.violates(0.6) and not above.violates(0.4)

    def test_ratio_drop_fires_and_recovery_resolves(self):
        g, db, eng, edges = mk_ratio_rig()

        def drive(t0, n, value):
            for i in range(n):
                g.set(value)
                db.sample_once(t0 + i)
                eng.evaluate_once(t0 + i)
            return t0 + n

        t = drive(1000.0, 30, 0.9)    # healthy ratio
        assert edges == []
        t = drive(t, 40, 0.1)         # sustained collapse under the floor
        assert edges == [True]
        drive(t, 40, 0.9)             # recovery
        assert edges == [True, False]

    def test_default_catalogue_has_goodput_objectives(self):
        objs = {o.name: o for o in default_objectives()}
        assert objs["cluster-goodput"].direction == DIRECTION_BELOW
        assert objs["cluster-goodput"].metric == "kctpu_cluster_goodput_ratio"
        assert objs["cluster-goodput"].subject_labels == ()
        assert objs["badput-budget"].direction == DIRECTION_BELOW
        assert objs["badput-budget"].metric == "kctpu_goodput_ratio"


# ---------------------------------------------------------------------------
# phase-registry vet rule
# ---------------------------------------------------------------------------

class TestPhaseRegistryVet:
    def run_vet(self, tmp_path, src):
        mod = tmp_path / "phasey.py"
        mod.write_text(src)
        return vet.run([str(mod)], root=REPO_ROOT, skip_catalogue=True)

    def test_unknown_beat_phase_literal_flagged(self, tmp_path):
        findings = self.run_vet(
            tmp_path,
            "def report(rep):\n"
            "    rep.beat(step=1, phase='warmup')\n")
        assert [f.rule for f in findings] == ["phase-registry"]
        assert "'warmup'" in findings[0].message

    def test_unknown_podprogress_phase_flagged(self, tmp_path):
        findings = self.run_vet(
            tmp_path,
            "from kubeflow_controller_tpu.api.core import PodProgress\n"
            "def mk():\n"
            "    return PodProgress(step=3, phase='prefetch')\n")
        assert [f.rule for f in findings] == ["phase-registry"]

    def test_known_phases_and_constants_pass(self, tmp_path):
        findings = self.run_vet(
            tmp_path,
            "from kubeflow_controller_tpu.obs.phases import PHASE_FIT\n"
            "def report(rep, ph):\n"
            "    rep.beat(step=1, phase='fit')\n"
            "    rep.beat(step=2, phase=PHASE_FIT)\n"
            "    rep.beat(step=3, phase=ph)\n"   # dynamic: not a new literal
            "    rep.beat(step=4, phase='')\n")
        assert findings == []

    def test_inline_suppression(self, tmp_path):
        findings = self.run_vet(
            tmp_path,
            "def report(rep):\n"
            "    rep.beat(step=1, phase='bogus')"
            "  # kctpu: vet-ok(phase-registry) - test literal\n")
        assert findings == []


# ---------------------------------------------------------------------------
# CLI surfaces: get suffix, top column, kctpu goodput
# ---------------------------------------------------------------------------

def mk_running_job(cluster, name, goodput=None):
    t = PodTemplateSpec()
    t.spec.containers.append(Container(name="w", image="img"))
    job = TFJob(metadata=ObjectMeta(name=name, namespace="default"))
    job.spec.tf_replica_specs.append(TFReplicaSpec(
        replicas=2, tf_replica_type=ReplicaType.WORKER, template=t))
    cluster.tfjobs.create(job)
    j = cluster.tfjobs.get("default", name)
    j.status.phase = TFJobPhase.RUNNING
    j.status.progress = JobProgress(
        step=10, max_step=10, examples_per_sec=50.0, reporting=2,
        last_heartbeat=time.time(),
        replicas=[ReplicaProgress(type=ReplicaType.WORKER, index=0, step=10,
                                  phase="fit",
                                  last_heartbeat=time.time())])
    j.status.goodput = goodput
    cluster.tfjobs.update_status(j)


class TestCLIGoodput:
    @pytest.fixture
    def served(self):
        cluster = Cluster()
        srv = FakeAPIServer(cluster.store)
        url = srv.start()
        mk_running_job(cluster, "trainer", goodput=JobGoodput(
            goodput_s=80, occupied_s=100, wall_s=130, ratio=0.8,
            buckets={"train": 80, "rendezvous": 12, "compile_miss": 8,
                     "queued": 30}))
        mk_running_job(cluster, "plain")  # no ledger yet
        yield url
        srv.stop()

    def row(self, out, name):
        hdr = next(ln for ln in out.splitlines() if ln.startswith("NAMESPACE"))
        row = next(ln for ln in out.splitlines()
                   if ln.startswith("default") and f" {name} " in f"{ln} ")
        return hdr, row

    def test_get_appends_good_suffix_without_shifting_columns(self, served,
                                                              capsys):
        from kubeflow_controller_tpu.cli.main import main

        assert main(["-master", served, "get"]) == 0
        out = capsys.readouterr().out
        hdr, row = self.row(out, "trainer")
        # The ratio rides the REPLICAS cell (the row's last, free-width
        # column) so every fixed-width column stays put.
        at = hdr.index("REPLICAS")
        assert row[at:] == "Workerx2[good=80%]"
        assert row[hdr.index("RESTARTS"):at].split() == ["0", "-"]
        _, plain = self.row(out, "plain")
        assert plain[at:] == "Workerx2"   # no ledger -> no suffix

    def test_top_has_goodput_column(self, served, capsys):
        from kubeflow_controller_tpu.cli.main import main

        assert main(["-master", served, "top"]) == 0
        out = capsys.readouterr().out
        hdr, row = self.row(out, "trainer")
        at = hdr.index("GOODPUT")
        assert row[at:at + 8].strip() == "80%"
        _, plain = self.row(out, "plain")
        assert plain[at:at + 8].strip() == "-"

    def test_goodput_fleet_table_and_cluster_rollup(self, served, capsys):
        from kubeflow_controller_tpu.cli.main import main

        assert main(["-master", served, "goodput"]) == 0
        out = capsys.readouterr().out
        hdr, row = self.row(out, "trainer")
        assert "TOP-BADPUT" in hdr
        assert row[hdr.index("GOODPUT"):].split()[0] == "80%"
        assert "rendezvous=12s" in row     # dominant badput bucket
        assert "plain" not in out          # ledgerless jobs are filtered
        assert "cluster: goodput 80% (80s of 100s occupied, 1 job(s))" in out

    def test_goodput_job_drilldown_classifies_buckets(self, served, capsys):
        from kubeflow_controller_tpu.cli.main import main

        assert main(["-master", served, "goodput", "--job", "trainer"]) == 0
        out = capsys.readouterr().out
        assert "goodput 80% (80s of 100s occupied; wall 130s)" in out
        rows = {ln.split()[0]: ln.split()[-1] for ln in out.splitlines()
                if ln and ln.split()[0] in P.ALL_BUCKETS}
        assert rows["train"] == "goodput"
        assert rows["rendezvous"] == "badput"
        assert rows["compile_miss"] == "badput"
        assert rows["queued"] == "waiting"

    def test_goodput_job_without_ledger_says_so(self, served, capsys):
        from kubeflow_controller_tpu.cli.main import main

        assert main(["-master", served, "goodput", "--job", "plain"]) == 0
        assert "no goodput ledger yet" in capsys.readouterr().out

    def test_describe_has_badput_section(self, served, capsys):
        from kubeflow_controller_tpu.cli.main import main

        assert main(["-master", served, "describe", "trainer"]) == 0
        out = capsys.readouterr().out
        assert "Goodput:   80%" in out
