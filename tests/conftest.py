"""Test configuration.

JAX tests run on a virtual 8-device CPU mesh (multi-chip TPU hardware is not
available in CI; shardings are validated on forced host devices, the same
mechanism the driver's dryrun uses).  Must be set before jax is imported
anywhere in the test process.
"""

import os

# Force, don't setdefault: the build image pins JAX_PLATFORMS=axon (one real
# TPU chip) via a site hook that overrides the env var, so the platform must
# also be forced through jax.config after import.  Sharding tests need the
# virtual 8-device CPU mesh.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session", autouse=True)
def _kctpu_lockcheck():
    """With KCTPU_LOCKCHECK=1, run the WHOLE suite under the runtime
    lock-order detector (analysis/lockcheck.py) and fail the session at
    exit on any acquisition-order cycle or blocking-call-under-lock — the
    interleaving-dependent bug classes no individual test can assert on."""
    if os.environ.get("KCTPU_LOCKCHECK", "") in ("", "0"):
        yield
        return
    from kubeflow_controller_tpu.analysis import lockcheck

    checker = lockcheck.install()
    yield
    report = checker.report()
    print("\n" + report.render())
    assert report.clean, "lockcheck found concurrency violations (above)"
