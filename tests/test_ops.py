"""Pallas kernels vs jnp oracles (interpreter mode on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_controller_tpu.ops import flash_attention
from kubeflow_controller_tpu.parallel.ring import attention_reference


def _qkv(key, b, t, h, d, dtype=jnp.float32):
    k1, k2, k3 = jax.random.split(key, 3)
    return (
        jax.random.normal(k1, (b, t, h, d), dtype=dtype),
        jax.random.normal(k2, (b, t, h, d), dtype=dtype),
        jax.random.normal(k3, (b, t, h, d), dtype=dtype),
    )


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_reference(self, causal):
        q, k, v = _qkv(jax.random.PRNGKey(0), 2, 64, 2, 16)
        out = flash_attention(q, k, v, causal=causal, block_q=32, block_k=32)
        ref = attention_reference(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)

    def test_single_block(self):
        q, k, v = _qkv(jax.random.PRNGKey(1), 1, 16, 1, 8)
        out = flash_attention(q, k, v, causal=True)
        ref = attention_reference(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)

    def test_indivisible_seq_raises(self):
        q, k, v = _qkv(jax.random.PRNGKey(2), 1, 48, 1, 8)
        with pytest.raises(ValueError):
            flash_attention(q, k, v, block_q=32, block_k=32)

    @pytest.mark.parametrize("causal", [True, False])
    def test_grads_match_reference(self, causal):
        """The custom VJP (two-kernel flash backward) against autodiff
        through the naive oracle — this is what makes the kernel trainable
        (VERDICT r1 weak #2)."""
        q, k, v = _qkv(jax.random.PRNGKey(3), 2, 64, 2, 16)

        def loss_flash(q, k, v):
            out = flash_attention(q, k, v, causal=causal, block_q=32, block_k=32)
            return jnp.sum(jnp.sin(out))  # non-trivial cotangents

        def loss_ref(q, k, v):
            return jnp.sum(jnp.sin(attention_reference(q, k, v, causal=causal)))

        gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=5e-5, rtol=5e-5)

    @pytest.mark.parametrize("bbq,bbk", [(16, 32), (32, 16), (16, 16)])
    def test_bwd_blocks_independent_of_fwd(self, bbq, bbk):
        """Backward block sizes decoupled from the forward's (round 5:
        attn_tpu.py --bwd-sweep tunes them separately) must not change
        gradients."""
        q, k, v = _qkv(jax.random.PRNGKey(5), 2, 64, 2, 16)

        def loss(q, k, v, **kw):
            out = flash_attention(q, k, v, causal=True, block_q=32,
                                  block_k=32, **kw)
            return jnp.sum(jnp.sin(out))

        g0 = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        g1 = jax.grad(
            lambda q, k, v: loss(q, k, v, bwd_block_q=bbq, bwd_block_k=bbk),
            argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g0):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-5, rtol=1e-5)

    def test_grad_under_jit_and_remat(self):
        """Composes with jax.checkpoint the way the model uses it."""
        q, k, v = _qkv(jax.random.PRNGKey(4), 1, 64, 2, 16)

        @jax.jit
        def loss(q, k, v):
            f = jax.checkpoint(
                lambda q, k, v: flash_attention(q, k, v, causal=True,
                                                block_q=32, block_k=32))
            return jnp.mean(f(q, k, v) ** 2)

        g = jax.grad(loss)(q, k, v)
        ref = jax.grad(
            lambda q, k, v: jnp.mean(attention_reference(q, k, v, causal=True) ** 2)
        )(q, k, v)
        np.testing.assert_allclose(np.asarray(g), np.asarray(ref), atol=5e-5, rtol=5e-5)
