"""End-to-end with REAL workloads: the kubelet's execute mode runs the JAX
training entrypoints as pod processes — the in-repo analog of the
reference's manual dist-mnist validation on a dev cluster (SURVEY.md §4
"the examples are the integration suite")."""

import os
import sys
import time

import pytest

# Whole-module: real subprocess workloads, each >5s — the quick CI job skips
# these; the coverage-gated full job runs them.
pytestmark = pytest.mark.slow

from kubeflow_controller_tpu.api.core import Container, EnvVar, PodTemplateSpec
from kubeflow_controller_tpu.api.meta import ObjectMeta
from kubeflow_controller_tpu.api.tfjob import (
    ReplicaType,
    TFJob,
    TFJobPhase,
    TFReplicaSpec,
    TPUSpec,
)
from kubeflow_controller_tpu.cluster import (
    Cluster,
    FakeKubelet,
    PhasePolicy,
    TPUInventory,
    TPUSlice,
)
from kubeflow_controller_tpu.controller import Controller

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def workload_container(module, *extra_args, env=None):
    c = Container(
        name="jax",
        image="local",
        command=[sys.executable, "-m", f"kubeflow_controller_tpu.workloads.{module}",
                 "--platform", "cpu", *extra_args],
        working_dir=REPO,
    )
    # Pods must not inherit the test harness's 8-virtual-device XLA_FLAGS:
    # a 2-worker gang would rendezvous 16 gloo ranks on a tiny CI host.
    all_env = {"XLA_FLAGS": "--xla_force_host_platform_device_count=1"}
    all_env.update(env or {})
    for k, v in all_env.items():
        c.env.append(EnvVar(name=k, value=v))
    return c


def mk_exec_job(name, module, *extra_args, typ=ReplicaType.LOCAL, replicas=1,
                restart="Never", env=None, model_dir=""):
    job = TFJob(metadata=ObjectMeta(name=name, namespace="default"))
    if model_dir:
        job.spec.model_dir = model_dir
    t = PodTemplateSpec()
    t.spec.containers.append(workload_container(module, *extra_args, env=env))
    t.spec.restart_policy = restart
    spec = TFReplicaSpec(replicas=replicas, tf_replica_type=typ, template=t)
    if typ == ReplicaType.TPU:
        # Single-host slice: one process, no jax.distributed rendezvous
        # (multi-process CPU rendezvous is unsupported in this image).
        spec.tpu = TPUSpec(accelerator_type="v5e-4", chips_per_host=4)
    job.spec.tf_replica_specs.append(spec)
    return job


def wait_phase(cluster, name, phase, timeout=120.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        j = cluster.tfjobs.get("default", name)
        if j.status.phase == phase:
            return j
        if phase != TFJobPhase.FAILED and j.status.phase == TFJobPhase.FAILED:
            raise AssertionError(f"job failed: {j.status.reason}")
        time.sleep(0.1)
    raise AssertionError(
        f"{name} never reached {phase}; now {j.status.phase} ({j.status.reason})"
    )


@pytest.fixture
def rig():
    cluster = Cluster()
    # Two slices: slice failure tests need healthy spare hardware for the
    # replacement gang (a failed slice is quarantined).
    inventory = TPUInventory([TPUSlice("slice-0", "v5e-4", num_hosts=1),
                              TPUSlice("slice-1", "v5e-4", num_hosts=1)])
    kubelet = FakeKubelet(cluster, policy=PhasePolicy(), inventory=inventory,
                          execute=True)
    ctrl = Controller(cluster, inventory=inventory, resync_period_s=0.5)
    kubelet.start()
    ctrl.run(threadiness=2)
    yield cluster, ctrl, kubelet
    ctrl.stop()
    kubelet.stop()


def test_local_mnist_executes_to_succeeded(rig):
    cluster, _, _ = rig
    cluster.tfjobs.create(mk_exec_job(
        "exec-local-mnist", "mnist_local",
        "--steps", "30", "--train-size", "1024", "--eval-size", "256",
    ))
    wait_phase(cluster, "exec-local-mnist", TFJobPhase.SUCCEEDED)


def test_failing_workload_marks_job_failed(rig):
    cluster, _, _ = rig
    cluster.tfjobs.create(mk_exec_job(
        "exec-fail", "mnist_local",
        "--steps", "5", "--train-size", "512", "--eval-size", "256",
        "--target-accuracy", "2.0",   # impossible -> exit 1
    ))
    wait_phase(cluster, "exec-fail", TFJobPhase.FAILED)


def test_worker_only_allreduce_job(rig):
    """The no-PS judged config (BASELINE.json configs[2]): a single Worker
    spec plans and runs — the reference's planner hardcoded exactly two
    replica specs (ref: distributed.go:201-209) and could not express this."""
    cluster, _, _ = rig
    job = mk_exec_job(
        "exec-allreduce", "cifar_allreduce",
        "--model", "cnn", "--steps", "4", "--batch-size", "16",
        "--train-size", "128", "--eval-size", "64",
        typ=ReplicaType.WORKER, replicas=2, restart="OnFailure",
    )
    cluster.tfjobs.create(job)
    wait_phase(cluster, "exec-allreduce", TFJobPhase.SUCCEEDED, timeout=180.0)
    # Worker pods got the TF-contract args with no --ps_hosts.
    pods = [p for p in cluster.pods.list("default")
            if p.metadata.labels.get("job_type") == "Worker"]
    assert len(pods) == 2
    for p in pods:
        args = p.spec.containers[0].args
        assert any(a.startswith("--worker_hosts=") for a in args)
        assert not any(a.startswith("--ps_hosts=") for a in args)


def test_tpu_job_executes_llama_with_checkpoint(rig, tmp_path):
    cluster, _, _ = rig
    model_dir = str(tmp_path / "llama-ck")
    job = mk_exec_job(
        "exec-llama", "llama_pretrain",
        "--steps", "3", "--batch-size", "4", "--seq-len", "64",
        typ=ReplicaType.TPU, model_dir=model_dir,
    )
    cluster.tfjobs.create(job)
    wait_phase(cluster, "exec-llama", TFJobPhase.SUCCEEDED, timeout=180.0)
    # MODEL_DIR was plumbed and the workload checkpointed into it.
    assert os.path.isdir(model_dir) and os.listdir(model_dir)


def test_pipeline_parallel_job_trains_and_resumes(rig, tmp_path):
    """A --pp 2 TFJob is a real product path: the manifest-shaped job runs
    the 1F1B schedule (parallel/pipeline.py:pipeline_1f1b) over a pp=2
    mesh inside the pod, checkpoints the stacked-layer params, and a
    SECOND job over the same modelDir resumes from them — the pipeline
    analog of examples/jobs/llama-pp.yaml."""
    cluster, _, _ = rig
    model_dir = str(tmp_path / "pp-ck")
    env = {"XLA_FLAGS": "--xla_force_host_platform_device_count=8"}
    job = mk_exec_job(
        "exec-pp", "llama_pretrain",
        "--steps", "3", "--batch-size", "4", "--seq-len", "64",
        "--pp", "2", "--microbatches", "2", "--fsdp", "4",
        "--checkpoint-every", "1",
        typ=ReplicaType.TPU, model_dir=model_dir, env=env,
    )
    cluster.tfjobs.create(job)
    wait_phase(cluster, "exec-pp", TFJobPhase.SUCCEEDED, timeout=240.0)

    from kubeflow_controller_tpu.workloads.checkpoint import CheckpointManager

    assert CheckpointManager(model_dir).latest_step() == 3

    # Resume: a fresh job over the same modelDir continues from step 3.
    job2 = mk_exec_job(
        "exec-pp-resume", "llama_pretrain",
        "--steps", "2", "--batch-size", "4", "--seq-len", "64",
        "--pp", "2", "--microbatches", "2", "--fsdp", "4",
        "--checkpoint-every", "1",
        typ=ReplicaType.TPU, model_dir=model_dir, env=env,
    )
    cluster.tfjobs.create(job2)
    wait_phase(cluster, "exec-pp-resume", TFJobPhase.SUCCEEDED, timeout=240.0)
    assert CheckpointManager(model_dir).latest_step() == 5, (
        "second pp job restarted from scratch instead of resuming"
    )


def test_moe_job_trains_with_expert_parallelism(rig, tmp_path):
    """An E=4 MoE TFJob is a real product path: experts shard over ep=4
    inside the pod with the DROPLESS grouped-kernel dispatch (the sharded
    grouped path, models/moe.py:_grouped_ffn_sharded — not an einsum
    fallback), and the [L, E, ...] expert param tree checkpoints and
    restores — the in-cluster analog of examples/jobs/llama-moe.yaml."""
    cluster, _, _ = rig
    model_dir = str(tmp_path / "moe-ck")
    job = mk_exec_job(
        "exec-moe", "llama_pretrain",
        "--steps", "2", "--batch-size", "4", "--seq-len", "64",
        # dim/intermediate at the 128 grain the grouped kernels need (the
        # tiny preset's dim=64 would silently fall back to einsum);
        # --strict-moe-dispatch turns any fallback into a workload FAILURE
        # so the product path cannot regress to a showpiece.  (An env
        # PYTHONWARNINGS filter would NOT work: zygote-forked pods never
        # re-initialize the warnings module.)
        "--dim", "128", "--intermediate", "256",
        "--experts", "4", "--top-k", "2", "--ep", "4", "--fsdp", "2",
        "--moe-dispatch", "grouped", "--strict-moe-dispatch",
        "--checkpoint-every", "1",
        typ=ReplicaType.TPU, model_dir=model_dir,
        env={"XLA_FLAGS": "--xla_force_host_platform_device_count=8"},
    )
    cluster.tfjobs.create(job)
    wait_phase(cluster, "exec-moe", TFJobPhase.SUCCEEDED, timeout=240.0)

    # The expert param tree (router + [L,E,D,F] weights) round-trips.
    import jax

    from kubeflow_controller_tpu.models import LlamaConfig, llama_init
    from kubeflow_controller_tpu.workloads.checkpoint import CheckpointManager
    from kubeflow_controller_tpu.workloads.trainer import default_optimizer

    # Mirror the workload's tiny overrides (--dim 128 --intermediate 256
    # implies heads dim//16, kv dim//32 — llama_pretrain.py).
    cfg = LlamaConfig.tiny(max_seq_len=64, dim=128, n_heads=8, n_kv_heads=4,
                           intermediate=256, n_experts=4, moe_top_k=2)
    params = llama_init(jax.random.PRNGKey(0), cfg)
    opt_state = default_optimizer(3e-4, weight_decay=0.1).init(params)
    _, _, step = CheckpointManager(model_dir).restore(params, opt_state)
    assert step == 2


def test_sp_job_trains_with_sequence_parallelism(rig, tmp_path):
    """A --sp 2 TFJob is a real product path: the sequence axis shards
    over sp inside the pod (ring attention exchanging KV over the sp
    ring), trains, and checkpoints — the in-cluster analog of
    examples/jobs/llama-sp.yaml and the long-context axis PERF.md names
    as the remaining T=8192 lever."""
    cluster, _, _ = rig
    model_dir = str(tmp_path / "sp-ck")
    job = mk_exec_job(
        "exec-sp", "llama_pretrain",
        "--steps", "2", "--batch-size", "4", "--seq-len", "64",
        "--sp", "2", "--fsdp", "4", "--sp-attention", "ring",
        "--checkpoint-every", "1",
        typ=ReplicaType.TPU, model_dir=model_dir,
        env={"XLA_FLAGS": "--xla_force_host_platform_device_count=8"},
    )
    cluster.tfjobs.create(job)
    wait_phase(cluster, "exec-sp", TFJobPhase.SUCCEEDED, timeout=240.0)

    from kubeflow_controller_tpu.workloads.checkpoint import CheckpointManager

    assert CheckpointManager(model_dir).latest_step() == 2


def test_slice_failure_resumes_from_checkpoint(rig, tmp_path):
    """The full recovery story the reference admits it lacks (ref:
    docs/design_doc.md:228-260): a TPU job checkpoints every step, the
    whole slice dies mid-run, the controller replaces the gang at the same
    index, and the replacement pod RESUMES from the Orbax step instead of
    step 0."""
    cluster, ctrl, kubelet = rig
    model_dir = str(tmp_path / "resume-ck")
    steps = 80
    job = mk_exec_job(
        "exec-resume", "llama_pretrain",
        "--steps", str(steps), "--batch-size", "4", "--seq-len", "64",
        "--checkpoint-every", "1",
        typ=ReplicaType.TPU, restart="OnFailure", model_dir=model_dir,
    )
    cluster.tfjobs.create(job)

    # Wait until training is demonstrably underway (>= 1 checkpoint saved).
    from kubeflow_controller_tpu.workloads.checkpoint import CheckpointManager

    deadline = time.time() + 120
    ck = None
    while time.time() < deadline:
        if os.path.isdir(model_dir):
            ck = CheckpointManager(model_dir)
            if ck.latest_step() is not None and ck.latest_step() >= 1:
                break
        time.sleep(0.2)
    assert ck is not None and ck.latest_step() >= 1, "no checkpoint appeared"
    first_pods = {p.metadata.name for p in cluster.pods.list("default")}
    assert first_pods, "no pods before failure"

    # Kill the whole slice mid-run — the TPU failure domain.
    failed = kubelet.fail_slice("slice-0")
    assert failed, "fail_slice found no bound gang"

    # The controller replaces the gang (same index, new pod) and the
    # replacement resumes; the job must still reach Succeeded.
    wait_phase(cluster, "exec-resume", TFJobPhase.SUCCEEDED, timeout=180.0)

    pods = cluster.pods.list("default")
    replacement = [p for p in pods if p.metadata.name not in first_pods]
    assert replacement, "no replacement pod was created"
    assert replacement[0].metadata.labels.get("index") == "0"
    # The dead slice is quarantined; the replacement ran on the spare.
    assert kubelet.inventory.slices["slice-0"].healthy is False

    # Resume proof: a fresh run would end at exactly `steps`; a resumed run
    # ends at failure_step + steps > steps.
    final_step = CheckpointManager(model_dir).latest_step()
    assert final_step is not None and final_step > steps, (
        f"final checkpoint step {final_step} <= {steps}: the replacement "
        "restarted from scratch instead of resuming"
    )

    # And the replacement's stdout says so (warm-pool pods log to files).
    pool = kubelet._pool
    if pool is not None:
        import glob

        outs = glob.glob(os.path.join(pool._tmpdir, "*.out"))
        texts = [open(f).read() for f in outs]
        assert any("Resumed from step" in t for t in texts), (
            "no pod log contains 'Resumed from step'"
        )
