# Build entrypoints — parity with the reference's Makefile (ref: Makefile:
# 36-42: `make test` runs go test over non-vendor packages; CI chains
# lint+test at .travis.yml:1-14).

PY ?= python

.PHONY: all ci test test-fast lint typecheck cov cov-local bench dryrun validate vet race-smoke check-smoke metrics-smoke scale-smoke scale10k-smoke stall-smoke widejob-smoke churn-smoke store-smoke sched-smoke ttfs-smoke chaos-smoke elastic-smoke multislice-smoke goodput-smoke tenants-smoke ha-smoke serve-smoke gateway-smoke slo-smoke

all: lint vet test race-smoke check-smoke

# The documented pre-merge gate (README.md): static analysis first (vet,
# incl. the whole-program lock graph + raw-lock facade enforcement), then
# the seeded race harness, then the model checkers (linearizability +
# watch-delivery exactness under deterministic simulation, self-test
# included), then tier-1 under the runtime lock-order detector.  Run
# without -j: the order is the diagnosis ladder (cheapest, most precise
# signal first).
ci: vet race-smoke check-smoke chaos-smoke elastic-smoke multislice-smoke goodput-smoke tenants-smoke serve-smoke gateway-smoke ha-smoke slo-smoke scale10k-smoke
	KCTPU_LOCKCHECK=1 JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m "not slow"

# Fast/slow split: `test-fast` (-m "not slow") is the quick signal — 214 of
# 259 tests, minutes instead of ~15; the 45 @pytest.mark.slow tests are the
# heavyweight model/kernel/e2e paths, covered by `test` and the
# coverage-gated `cov` job in CI.
test:
	$(PY) -m pytest tests/ -q

test-fast:
	$(PY) -m pytest tests/ -q -m "not slow"

# Coverage-gated FULL test run (the goveralls analog, ref: .travis.yml:12-14).
# Requires pytest-cov (CI installs it; locally falls back to plain tests).
# Floor: measured package line coverage is 81.4% (tests/_linecov.py, full
# suite, 2026-07-30); the gate is that floor minus a small margin.
cov:
	@if $(PY) -c "import pytest_cov" >/dev/null 2>&1; then \
		$(PY) -m pytest tests/ -q --cov=kubeflow_controller_tpu \
			--cov-report=term-missing:skip-covered --cov-fail-under=75; \
	else \
		echo "pytest-cov not installed; running plain tests"; \
		$(PY) -m pytest tests/ -q; \
	fi

# Zero-dependency local coverage (sys.monitoring) for images without
# pytest-cov — same quantity the CI gate measures, so the floor can be
# re-derived from a measurement: make cov-local
cov-local:
	$(PY) -m tests._linecov tests/ -q

# Static type pass (the gometalinter-breadth analog, ref: config.json:4-16).
# Requires mypy (CI installs it; locally a no-op with a notice).
typecheck:
	@if $(PY) -m mypy --version >/dev/null 2>&1; then \
		$(PY) -m mypy kubeflow_controller_tpu; \
	else \
		echo "mypy not installed; skipping typecheck"; \
	fi

lint:
	@if $(PY) -m ruff --version >/dev/null 2>&1; then \
		$(PY) -m ruff check kubeflow_controller_tpu tests; \
	else \
		echo "ruff not installed; falling back to kctpu vet"; \
		$(PY) -m kubeflow_controller_tpu.analysis.vet; \
	fi

# `kctpu vet`: zero-dependency (stdlib-ast) project linter enforcing the
# codified concurrency/controller invariants — no blocking calls under a
# lock, no copy.deepcopy on hot paths, no snapshot/template mutation,
# thread hygiene, metric-catalogue sync, event-reason style.  Rule
# catalogue + suppression syntax: docs/ANALYSIS.md.
vet:
	$(PY) -m kubeflow_controller_tpu.analysis.vet

# Schedule-fuzz race harness: the store / workqueue / slice-inventory
# concurrency invariants under seeded pre-acquire yield injection + a
# 10 us switch interval, with the runtime lock-order detector live.
# Three seeds; fails on any invariant violation, acquisition-order cycle,
# or blocking call under a lock.  ~6 s wall-clock (docs/ANALYSIS.md).
race-smoke:
	JAX_PLATFORMS=cpu $(PY) -m kubeflow_controller_tpu.analysis.interleave \
		--seeds 101,202,303 --duration 0.5

# Model-check smoke (`kctpu check`): FIRST the checkers' own known-bad
# synthetic fixtures must be rejected (stale read, lost update,
# non-monotonic list RV, duplicate/gapped/reordered watch streams — a
# checker that stops biting proves nothing), THEN 3 seeded
# deterministic-simulation passes over the REAL store/watch plane —
# writers/watchers under schedule fuzz with forced stream drops
# mid-batch, bounded-queue overflow drops, and watcher crash-points
# (killed mid-replay, RV-resumed) — must report zero linearizability,
# RV-monotonicity, or delivery violations.  --crash-restart additionally
# reruns each seed against a WAL-backed store that is killed mid-run and
# recovered (ha/wal.py), with the checkers spanning the boundary.  A red
# seed prints its exact one-line repro and exports KCTPU_FUZZ_SEED.
# ~15 s (docs/ANALYSIS.md).
check-smoke:
	JAX_PLATFORMS=cpu $(PY) -m kubeflow_controller_tpu.analysis.simcheck \
		--self-test --seeds 11,22,33 --duration 0.5 --crash-restart

validate:
	$(PY) -m kubeflow_controller_tpu.cli validate -f examples/jobs/

bench:
	$(PY) bench.py

# Observability smoke: boot the in-process cluster, run one job to
# Succeeded, scrape GET /metrics over HTTP, and fail on any malformed
# Prometheus exposition line or missing headline family
# (docs/OBSERVABILITY.md has the metric catalogue).
metrics-smoke:
	JAX_PLATFORMS=cpu $(PY) -m kubeflow_controller_tpu.obs.smoke

# SLO smoke (the observability plane's standing gate, docs/OBSERVABILITY.md
# "SLO catalogue"): one Serving job whose replica beats a throttled p99
# TTFT (2.5x over the 2s objective) through the REAL pipeline — beat ->
# rollup -> gauge -> TSDB sample -> multi-window burn eval.  Gates:
# EXACTLY ONE Warning SLOBurn fires (edge-triggered, no flapping) and
# resolves to Normal SLORecovered when the replica recovers, with
# kctpu_slo_alert_active 1 -> 0 on GET /metrics; plus the trace-continuity
# gate — the job's causal trace exists, shares one trace_id, and has ZERO
# orphan spans (every parent_id resolves).  ~5-10 s wall-clock.
slo-smoke:
	JAX_PLATFORMS=cpu $(PY) -m kubeflow_controller_tpu.obs.slo_smoke

# Stall smoke: simulated training run, heartbeats killed mid-flight; fails
# unless Warning TrainingStalled fires and kctpu_job_stalled=1 appears on
# GET /metrics within the stall deadline — then the reverse on resume.
stall-smoke:
	JAX_PLATFORMS=cpu $(PY) -m kubeflow_controller_tpu.obs.stall_smoke

# Scale smoke: boot the in-memory cluster, drive 10 concurrent simulated
# TFJobs to Succeeded via bench.py --scale, fail on regression past a
# generous wall-clock gate (post-index runs finish in <1s; 30s flags an
# order-of-magnitude regression, not scheduler noise) or malformed JSON.
scale-smoke:
	JAX_PLATFORMS=cpu $(PY) bench.py --scale 10 --max-seconds 30 \
		> /tmp/kctpu_scale_smoke.json
	@$(PY) -c "import json; d = json.load(open('/tmp/kctpu_scale_smoke.json')); \
		assert {'metric', 'value', 'unit', 'details'} <= set(d), d; \
		print('scale-smoke ok:', d['value'], d['unit'], \
		      '| syncs/sec', d['details']['syncs_per_sec'], \
		      '| index hit rate', d['details']['index_hit_rate'])"

# Scale-envelope smoke (the 10k-job / 50k-pod gate, docs/PERF.md "Scale
# envelope"): the full 10000-job simulated cluster on the event-driven
# SimKubelet — 1 PS + 4 workers per job, 50k pods, one timer-wheel thread.
# Gates: time-to-all-Succeeded under a relaxed container-friendly
# wall-clock bound (measured ~106 s, SCALE_r01.json; 480 s flags an
# order-of-magnitude regression, not scheduler noise) and peak process
# thread count <= 32 (simulated mode must stay O(1) threads in pod count
# — the threaded kubelet would need ~50k).  ~2-4 min wall-clock.
scale10k-smoke:
	JAX_PLATFORMS=cpu $(PY) bench.py --scale 10000 --simulated \
		--pods-per-job 5 --deadline 540 --max-seconds 480 \
		--max-threads 32 > /tmp/kctpu_scale10k_smoke.json
	@$(PY) -c "import json; d = json.load(open('/tmp/kctpu_scale10k_smoke.json')); \
		assert {'metric', 'value', 'unit', 'details'} <= set(d), d; \
		print('scale10k-smoke ok:', d['value'], d['unit'], \
		      '| pods', d['details']['pods_total'], \
		      '| peak threads', d['details']['peak_threads'], \
		      '| rss', d['details']['rss_mib'], 'MiB', \
		      '| p99', d['details']['reconcile_p99_ms'], 'ms', \
		      '| syncs/sec', d['details']['syncs_per_sec'])"

# Wide-job smoke: ONE TFJob with 64 Worker replicas over the pooled REST
# transport + slow-start batched manage, 5 ms injected RTT (loopback hides
# the fan-out; see docs/PERF.md "Wide-job fan-out").  Parallel runs land
# in <1s here; the 20s gate flags an order-of-magnitude regression (e.g.
# the write path going serial again), not scheduler noise.
widejob-smoke:
	JAX_PLATFORMS=cpu $(PY) bench.py --replicas 64 --rtt-ms 5 \
		--max-seconds 20 > /tmp/kctpu_widejob_smoke.json
	@$(PY) -c "import json; d = json.load(open('/tmp/kctpu_widejob_smoke.json')); \
		assert {'metric', 'value', 'unit', 'details'} <= set(d), d; \
		print('widejob-smoke ok:', d['value'], d['unit'], \
		      '| all running', d['details']['all_running_s'], 's', \
		      '| create p99', d['details']['create_latency_p99_ms'], 'ms')"

# Churn smoke: 6 simulated jobs over the REST transport while the server
# forcibly drops every watch stream 3x mid-run.  With warm RVs every
# reconnect must RESUME (server-side replay from the watch cache): the
# gate asserts ZERO full re-lists and >=1 successful resume — a relist
# means the resumable watch plane regressed to reconnect-storm re-listing
# (docs/PERF.md "Watch-plane churn").  Bounded: ~5-10s wall-clock.
churn-smoke:
	JAX_PLATFORMS=cpu $(PY) bench.py --churn 6 --drops 3 --max-relists 0 \
		--min-resumes 1 > /tmp/kctpu_churn_smoke.json
	@$(PY) -c "import json; d = json.load(open('/tmp/kctpu_churn_smoke.json')); \
		assert {'metric', 'value', 'unit', 'details'} <= set(d), d; \
		print('churn-smoke ok: relists', d['value'], \
		      '| resumes', d['details']['watch_resumes'], \
		      '| replayed', d['details']['watch_replayed_events'], \
		      '| storm p99', d['details']['storm_reconcile_p99_ms'], 'ms')"

# Store-contention smoke: the scale bench + direct 4-kind store stress,
# once on the per-kind sharded store and once on the --no-shard
# global-lock baseline (the pre-shard store: one lock, reads deep-copied
# under it).  Gates (measured: ~1.9x syncs/sec, ~4-7x store ops/sec,
# sharded lock-wait p99 <=1 ms vs 50-100 ms — docs/PERF.md "Store
# contention"): sharded must beat baseline on syncs/sec (>=1.3x) and on
# direct store throughput (>=2x), and keep its worst-shard lock-wait p99
# under 25 ms.  ~20 s wall-clock.
store-smoke:
	JAX_PLATFORMS=cpu $(PY) bench.py --scale 60 --store-contention \
		--max-lock-wait-p99-ms 25 > /tmp/kctpu_store_smoke_sharded.json
	JAX_PLATFORMS=cpu $(PY) bench.py --scale 60 --store-contention \
		--no-shard > /tmp/kctpu_store_smoke_global.json
	@$(PY) -c "import json; \
		s = json.load(open('/tmp/kctpu_store_smoke_sharded.json')); \
		g = json.load(open('/tmp/kctpu_store_smoke_global.json')); \
		ratio = s['value'] / max(g['value'], 1e-9); \
		stress = s['details']['stress_ops_per_sec'] / \
			max(g['details']['stress_ops_per_sec'], 1e-9); \
		assert ratio >= 1.3, f'sharded syncs/sec only {ratio:.2f}x baseline'; \
		assert stress >= 2.0, f'sharded store ops/sec only {stress:.2f}x baseline'; \
		print('store-smoke ok:', s['value'], 'vs', g['value'], 'syncs/sec', \
		      f'({ratio:.2f}x)', '| stress', f'{stress:.2f}x', \
		      '| lock-wait p99', s['details']['lock_wait']['p99_ms'], 'ms', \
		      'vs', g['details']['lock_wait']['p99_ms'], 'ms')"

# Scheduler smoke: 16 TPU gang jobs (high submitted last) contending for 4
# slices through the priority gang queue + preemption + backfill.  Gates
# (measured: high p99 ~1.2-1.3x uncontended, utilization ~0.85, warm
# readmission ~4x below cold — docs/PERF.md "Slice contention"): high-
# priority time-to-first-step p99 <= 2x the uncontended TTFS, aggregate
# slice utilization >= 0.8 over the storm, zero starved/failed gangs, and
# warm readmission strictly below cold admission.  ~15 s wall-clock.
sched-smoke:
	JAX_PLATFORMS=cpu $(PY) bench.py --contend 16 --slices 4 \
		--max-ttfs-ratio 2.0 --min-utilization 0.8 \
		> /tmp/kctpu_sched_smoke.json
	@$(PY) -c "import json; d = json.load(open('/tmp/kctpu_sched_smoke.json')); \
		assert {'metric', 'value', 'unit', 'details'} <= set(d), d; \
		print('sched-smoke ok: high p99', d['value'], 's', \
		      '(', d['details']['high_ttfs_ratio_vs_uncontended'], 'x uncontended )', \
		      '| util', d['details']['utilization'], \
		      '| preempts', d['details']['counters'].get('preemptions', {}), \
		      '| warm readmit', d['details']['warm_readmit_ttfs_s'], 's vs cold', \
		      d['details']['cold_admit_ttfs_s'], 's')"

# TTFS smoke: real 2-worker dist-mnist --step-loop jobs through the whole
# stack — cold with serial vs overlapped host setup, then warm on the
# populated compile cache.  Gates (measured: warm ~0.34x cold, warm
# compile 0.09s vs ~1.4s cold — docs/PERF.md "Time to first step"): warm
# TTFS <= 0.5x the overlapped cold TTFS with nonzero compile-cache hits,
# and the overlap pipeline structure (host setup running inside the
# rendezvous+compile window, serial baseline strictly ordered; the strict
# wall-clock overlap win is additionally gated only on multi-core hosts,
# where a spare core exists for the setup thread to run on).  ~90 s.
ttfs-smoke:
	JAX_PLATFORMS=cpu $(PY) bench.py --ttfs --ttfs-steps 30 --repeats 2 \
		--max-warm-ratio 0.5 --gate-overlap > /tmp/kctpu_ttfs_smoke.json
	@$(PY) -c "import json; d = json.load(open('/tmp/kctpu_ttfs_smoke.json')); \
		assert {'metric', 'value', 'unit', 'details'} <= set(d), d; \
		print('ttfs-smoke ok: warm', d['value'], 's', \
		      '(', d['details']['warm_ratio_vs_cold_overlap'], 'x cold )', \
		      '| cold serial', d['details']['cold_serial_ttfs_s'], 's', \
		      '| overlap gain', d['details']['overlap_gain_s'], 's', \
		      '| cache hits', d['details']['warm_compile_cache_hits'])"

# Chaos smoke (the recovery plane's standing robustness gate): 2 real
# dist-mnist --step-loop gang jobs with async Orbax checkpoints every 40
# steps, 2 workers SIGKILLed at seeded random mid-fit steps.  Gates
# (docs/RECOVERY.md methodology; measured: lost steps 7-30 <= 40,
# recovery p50/p99 ~1.7/2.1 s — CHAOS_r01.json): every kill recovers and
# every job reaches Succeeded, lost steps <= spec.checkpoint_every_steps
# (resume really restored, not restarted from 0), recovery-time p99
# bounded, and the restart_policy Never probe lands terminal Failed with
# a policy reason (no hang, no zombie restart).  ~60 s wall-clock.
chaos-smoke:
	JAX_PLATFORMS=cpu $(PY) bench.py --chaos 2 --kills 2 --seed 7 \
		--max-recovery-p99 60 > /tmp/kctpu_chaos_smoke.json
	@$(PY) -c "import json; d = json.load(open('/tmp/kctpu_chaos_smoke.json')); \
		assert {'metric', 'value', 'unit', 'details'} <= set(d), d; \
		print('chaos-smoke ok: recovery p99', d['value'], 's', \
		      '| recovered', d['details']['recovered_rate'], \
		      '| max lost steps', d['details']['max_lost_steps'], \
		      '/', d['details']['checkpoint_every'], \
		      '| never-probe', d['details']['never_probe']['reason'][:40])"

# Elastic smoke (the degraded-width training gate, docs/RECOVERY.md
# "Elastic width"): ONE real 3-worker dist-mnist --step-loop gang with
# elastic {min_width: 2} and async checkpoints every 40 steps; 1 worker
# SIGKILLed mid-fit.  Gates: the controller re-shards the survivors to
# width 2 and steps/sec stays > 0 THROUGH the degraded window (no
# full-gang stop), the gang re-expands to full width resuming from the
# degraded run's checkpoint (never restore-from-scratch), lost steps <=
# the checkpoint interval per transition, and the scheduler contention
# probe admits a blocked high-priority gang by HARVESTING width from a
# running elastic victim — zero whole-gang preemptions.  ~60-90 s.
elastic-smoke:
	JAX_PLATFORMS=cpu $(PY) bench.py --elastic --kills 1 --seed 7 \
		> /tmp/kctpu_elastic_smoke.json
	@$(PY) -c "import json; d = json.load(open('/tmp/kctpu_elastic_smoke.json')); \
		assert {'metric', 'value', 'unit', 'details'} <= set(d), d; \
		print('elastic-smoke ok: degraded steps/sec', d['value'], \
		      '| degraded at width', [r['degraded_width'] for r in d['details']['records']], \
		      '| t-degraded', d['details']['time_to_degraded_s'], 's', \
		      '| t-restored', d['details']['time_to_restored_s'], 's', \
		      '| lost', d['details']['lost_steps'], '/', d['details']['checkpoint_every'], \
		      '| harvest', d['details']['harvest']['counters'].get('harvested_slices', {}))"

# Multi-slice placement smoke (MULTISLICE_r01.json's standing gate,
# docs/PERF.md "Multi-slice placement").  Three probes: (1) adjacency-
# scored vs random gang placement on identical fragmented pools —
# adjacency must strictly beat random on mean rendezvous AND step time
# under the DCN cost model; (2) a REAL tiny-LLaMA pretrain building its
# mesh from $KCTPU_MESH while the CLI flags lie (the env contract the
# mesh-env vet rule enforces statically); (3) a mid-run member kill on a
# pp=2 x dp=2 gang over 4 simulated slices — the gang must degrade by
# EXACTLY one inter-slice dp replica (width 8 -> 4, never 6), keep
# training through the window with a pp-preserving mesh, and restore.
# ~30-60 s (dominated by the real pretrain subprocess).
multislice-smoke:
	JAX_PLATFORMS=cpu $(PY) bench.py --multislice --trials 24 --seed 7 \
		> /tmp/kctpu_multislice_smoke.json
	@$(PY) -c "import json; d = json.load(open('/tmp/kctpu_multislice_smoke.json')); \
		assert {'metric', 'value', 'unit', 'details'} <= set(d), d; \
		pl = d['details']['placement']; k = d['details']['kill']; \
		print('multislice-smoke ok: rendezvous speedup', d['value'], 'x', \
		      '| domains', pl['adjacency']['mean_domains'], 'vs', pl['random']['mean_domains'], \
		      '| degraded width', k['degraded_width'], \
		      '| degraded steps/s', k['degraded_steps_per_sec'], \
		      '| restored', k['restored'])"

# Goodput smoke (the time-accounting ledger's standing gate,
# docs/OBSERVABILITY.md "Goodput ledger"): a compressed chaos-kill +
# warm-restore + compile-cache + width-harvest scenario through the REAL
# controller ledger (obs/goodput.py).  Gates (GOODPUT_r01.json): every
# replica's attributed time sums to 100% of its wall time (zero
# unattributed/overlapping intervals), the injected kill's badput lands
# in restore+stalled, harvest badput lands in reshard (+harvested tail),
# a compile-cache-warm rerun shows compile badput shrinking >= 2x vs
# cold, status/CLI surfaces carry the rollup, and the ledger's --scale
# orchestration overhead stays < 10% (min of 5 interleaved on/off
# pairs, docs/PERF.md "Goodput ledger overhead").  ~30-45 s.
goodput-smoke:
	JAX_PLATFORMS=cpu $(PY) bench.py --goodput \
		> /tmp/kctpu_goodput_smoke.json
	@$(PY) -c "import json; d = json.load(open('/tmp/kctpu_goodput_smoke.json')); \
		assert {'metric', 'value', 'unit', 'details'} <= set(d), d; \
		g = d['details']['gates']; \
		assert all(g.values()), {k: v for k, v in g.items() if not v}; \
		print('goodput-smoke ok: scenario ratio', d['value'], \
		      '| badput', d['details']['badput_seconds_by_bucket'], \
		      '| overhead', d['details']['scale']['ledger_overhead_pct'], '%')"

# Multi-tenant fair-share smoke (the tenancy plane's standing gate,
# docs/PERF.md "Multi-tenant contention"): 4 tenants at weights 4:2:1:1.
# Gates (TENANT_r01.json): (1) the two-level DRF queue converges each
# backlogged tenant's slice share to within 10% of its weight share
# (measured: exact); (2) an elastic borrower at 2x quota is width-
# harvested down to its floor by an entitled claimant — zero whole-gang
# preemptions, every slice conserved across the round trip; (3) a victim
# tenant's paced GET+status-PUT ops keep p99 <= 1.5x the quiet baseline
# while another tenant offers a ~10x write storm into the per-tenant
# apiserver token buckets (victim throttled 0 times, the storm 429'd).
# ~20 s.
tenants-smoke:
	JAX_PLATFORMS=cpu $(PY) bench.py --tenants \
		> /tmp/kctpu_tenant_smoke.json
	@$(PY) -c "import json; d = json.load(open('/tmp/kctpu_tenant_smoke.json')); \
		assert {'metric', 'value', 'unit', 'details'} <= set(d), d; \
		g = d['details']['gates']; \
		assert all(g.values()), {k: v for k, v in g.items() if not v}; \
		s = d['details']['storm']; \
		print('tenants-smoke ok: max share err', d['value'], \
		      '| shares', {t: v['measured'] for t, v in sorted(d['details']['share'].items())}, \
		      '| reclaim', d['details']['reclaim']['harvested_slices'], 'slices in', \
		      d['details']['reclaim']['latency_ms'], 'ms,', \
		      d['details']['reclaim']['whole_gang_preemptions'], 'preemptions', \
		      '| storm p99 ratio', s['p99_ratio'], 'at', \
		      s['storm_multiple_of_victim'], 'x')"

# Serving smoke (the serving plane's standing gate, docs/SERVING.md):
# real tiny-Llama replicas over the slot-paged KV cache, three phases —
# (1) static-batch baseline at 1 replica (burst saturation), (2) the same
# burst under continuous batching, (3) an open-loop arrival sweep against
# autoscale {1..3} with a load step and a mid-sweep rolling weight
# update.  Gates (measured: ~2.2x throughput at ~3x lower p99 TTFT,
# reaction ~0.3 s — SERVE_r01.json): continuous batching >= 1.5x the
# static baseline's tokens/sec at equal-or-better p99 TTFT, the
# autoscaler reacts to the load step (second replica READY) within 6 s,
# and ZERO dropped requests across every phase including the rolling
# update (drain = stop intake -> finish in-flight -> exit).  ~60 s.
serve-smoke:
	JAX_PLATFORMS=cpu $(PY) bench.py --serve --min-cont-ratio 1.5 \
		--max-reaction-s 6 > /tmp/kctpu_serve_smoke.json
	@$(PY) -c "import json; d = json.load(open('/tmp/kctpu_serve_smoke.json')); \
		assert {'metric', 'value', 'unit', 'details'} <= set(d), d; \
		a = d['details']['autoscale']; \
		print('serve-smoke ok:', d['value'], 'x static throughput', \
		      '| cont p99 ttft', d['details']['continuous']['ttft_p99_ms'], 'ms', \
		      'vs static', d['details']['static']['ttft_p99_ms'], 'ms', \
		      '| reaction', a['reaction_ready_s'], 's', \
		      '| rolled', a['rolled'], 'in', a['roll_s'], 's', \
		      '| dropped', a['dropped'])"

# Gateway smoke (the serving front door's standing gate, docs/SERVING.md
# "The request gateway"): multi-turn session traffic over 3 prefix-caching
# replicas, routed once through the gateway (least-loaded + session
# affinity onto the replica holding the conversation's KV pages) and once
# round-robin direct at IDENTICAL load.  Gates (measured: ~1.5x tokens/sec
# at ~2-3x lower p99 TTFT, hit ratio 0.875 — GATEWAY_r01.json): gateway
# >= 1.2x round-robin tokens/sec with strictly lower p99 TTFT, prefix-hit
# ratio >= 0.5 on the multi-turn phase, at 2x overload the batch tier
# sheds while interactive keeps p99 TTFT inside the SLO with ZERO
# interactive sheds, and a mid-sweep replica drain completes with zero
# dropped requests and the drained replica out of the routing set.  ~15 s.
gateway-smoke:
	JAX_PLATFORMS=cpu $(PY) bench.py --gateway --min-gateway-ratio 1.2 \
		--min-prefix-hit 0.5 > /tmp/kctpu_gateway_smoke.json
	@$(PY) -c "import json; d = json.load(open('/tmp/kctpu_gateway_smoke.json')); \
		assert {'metric', 'value', 'unit', 'details'} <= set(d), d; \
		r = d['details']['routing']; t = d['details']['tiers']; \
		print('gateway-smoke ok:', d['value'], 'x round-robin', \
		      '| p99 ttft', r['gateway']['ttft_p99_ms'], 'ms vs', \
		      r['round_robin']['ttft_p99_ms'], 'ms', \
		      '| prefix hit', r['gateway']['prefix_hit_ratio'], \
		      '| shed batch', t['batch']['shed'], \
		      'interactive', t['interactive']['shed'], \
		      '| roll dropped', d['details']['rolling']['dropped'])"

# HA smoke (the control plane's standing availability gate): 2 controller
# candidates over one WAL-backed store; the leader is SIGKILLed mid-storm
# (lease renewals stop dead, its controller keeps running as a zombie).
# Gates (docs/HA.md; measured: failover ~0.4 s at a 0.5 s lease, shard
# speedup ~3x — HA_r01.json): failover < 2x the lease duration, the
# deposed leader's writes ALL bounce off the fencing token (>= 1
# rejection, zero accepted), zero lost reconciles (every job Succeeded),
# WAL replay rebuilds an RV-identical store, the crash-restart
# deterministic-simulation seed passes the PR-11 linearizability +
# watch-exactness checkers across the recover boundary, and 4-shard
# --scale 200 syncs/sec >= 1.5x single-controller over REST with 3 ms
# injected RTT.  ~60-90 s wall-clock.
ha-smoke:
	JAX_PLATFORMS=cpu $(PY) bench.py --ha --controllers 4 --ha-scale 200 \
		--kill-leader --max-failover-ratio 2.0 --min-shard-speedup 1.5 \
		> /tmp/kctpu_ha_smoke.json
	@$(PY) -c "import json; d = json.load(open('/tmp/kctpu_ha_smoke.json')); \
		assert {'metric', 'value', 'unit', 'details'} <= set(d), d; \
		print('ha-smoke ok: failover', d['value'], 's', \
		      '| fencing rejections', d['details']['fencing_rejections'], \
		      '| replay', d['details']['wal_replay_s'], 's rv-identical', \
		      d['details']['wal_rv_identical'], \
		      '| shard speedup', d['details']['shard_speedup'], 'x')"

dryrun:
	JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
		$(PY) -c "from __graft_entry__ import dryrun_multichip; dryrun_multichip(8)"
