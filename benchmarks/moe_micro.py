"""Micro-benchmark of the grouped-MoE pieces on the real chip: routing
index math, the three grouped matmuls, the two row gathers — to find where
a step's time actually goes before tuning blocks.  Not an artifact bench;
a tuning tool."""

import argparse
import time

import jax
import jax.numpy as jnp


def timeit(fn, *args, reps=160):
    """Time `reps` executions inside ONE jitted lax.scan with a scalar
    carry threaded into the input — per-call dispatch through the relayed
    backend is a ~60-85 ms FIXED cost, so reps must be large enough to
    amortize it below the noise (docs/PERF.md measurement caveats)."""
    x0 = args[0]

    @jax.jit
    def scanned(x0, rest):
        def body(x, _):
            y = fn(x, *rest)
            leaves = jax.tree.leaves(y)
            s = sum(jnp.sum(l).astype(jnp.float32) for l in leaves)
            return x + (s * 0).astype(x.dtype), None

        out, _ = jax.lax.scan(body, x0, None, length=reps)
        return jnp.sum(out.astype(jnp.float32))

    float(scanned(x0, args[1:]))  # compile + complete
    t0 = time.time()
    float(scanned(x0, args[1:]))
    return (time.time() - t0) / reps * 1e3


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--bt", type=int, default=8192, help="B*T tokens")
    p.add_argument("--dim", type=int, default=1024)
    p.add_argument("--inter", type=int, default=2816)
    p.add_argument("--experts", type=int, default=8)
    p.add_argument("--topk", type=int, default=2)
    p.add_argument("--bm", type=int, default=128)
    p.add_argument("--bn", type=int, default=512)
    p.add_argument("--bk", type=int, default=512)
    a = p.parse_args()

    from kubeflow_controller_tpu.ops.grouped_matmul import gmm

    N = a.bt * a.topk
    D, F, E, bm = a.dim, a.inter, a.experts, a.bm
    M = N + E * bm
    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (a.bt, D), jnp.bfloat16)
    wg = jax.random.normal(key, (E, D, F), jnp.bfloat16)
    wd = jax.random.normal(key, (E, F, D), jnp.bfloat16)
    slot_expert = jax.random.randint(key, (N,), 0, E)

    @jax.jit
    def route(slot_expert):
        sort_idx = jnp.argsort(slot_expert)
        sorted_experts = jnp.take(slot_expert, sort_idx)
        counts = jnp.sum(jax.nn.one_hot(slot_expert, E, dtype=jnp.int32), axis=0)
        group_start = jnp.cumsum(counts) - counts
        padded = ((counts + bm - 1) // bm) * bm
        pad_off = jnp.cumsum(padded) - padded
        rank = jnp.arange(N) - jnp.take(group_start, sorted_experts)
        dest = (jnp.take(pad_off, sorted_experts) + rank).astype(jnp.int32)
        ends = pad_off + padded
        te = jnp.minimum(jnp.searchsorted(
            ends, jnp.arange(M // bm) * bm, side="right"), E - 1).astype(jnp.int32)
        inv_src = jnp.full((M,), a.bt, jnp.int32).at[dest].set(
            (sort_idx // a.topk).astype(jnp.int32))
        return te, inv_src, dest

    te, inv_src, dest = jax.block_until_ready(route(slot_expert))
    print(f"route(index math): {timeit(route, slot_expert):.2f} ms")

    @jax.jit
    def gather(x, inv_src):
        x_pad = jnp.concatenate([x, jnp.zeros((1, D), x.dtype)], axis=0)
        return jnp.take(x_pad, inv_src, axis=0)

    x_pad = jax.block_until_ready(gather(x, inv_src))
    print(f"gather [{M}x{D}]: {timeit(gather, x, inv_src):.2f} ms")

    f = jax.jit(lambda l, r: gmm(l, r, te, bm, a.bn, a.bk))
    print(f"gmm up [{M}x{D}]@[{E}x{D}x{F}] bm={bm} bn={a.bn} bk={a.bk}: "
          f"{timeit(f, x_pad, wg):.2f} ms")
    h = jax.block_until_ready(f(x_pad, wg))
    fd = jax.jit(lambda l, r: gmm(l, r, te, bm, a.bn, a.bk))
    print(f"gmm down [{M}x{F}]@[{E}x{F}x{D}]: {timeit(fd, h, wd):.2f} ms")

    flops = 2 * M * D * F
    gmm_ms = timeit(f, x_pad, wg)
    xla_ms = timeit(lambda l, r: l @ r, x_pad, wg[0])
    print(f"xla dense same-FLOPs [{M}x{D}]@[{D}x{F}]: {xla_ms:.2f} ms "
          f"({flops / 1e9 / xla_ms:.0f} TFLOP/s) vs gmm {gmm_ms:.2f} ms "
          f"({flops / 1e9 / gmm_ms:.0f} TFLOP/s)")

    # Whole-FFN comparison: grouped vs einsum vs iso-active dense SwiGLU,
    # forward and grad.
    from kubeflow_controller_tpu.models.moe import moe_ffn_stats

    B, T = 8, a.bt // 8
    x3 = jax.random.normal(key, (B, T, D), jnp.bfloat16)
    rw = jax.random.normal(key, (D, E), jnp.bfloat16) * 0.1
    wu = jax.random.normal(key, (E, D, F), jnp.bfloat16)
    wdn = jax.random.normal(key, (E, F, D), jnp.bfloat16)
    wg2, wu2, wd2 = (jax.random.normal(key, (D, 2 * F), jnp.bfloat16),
                     jax.random.normal(key, (D, 2 * F), jnp.bfloat16),
                     jax.random.normal(key, (2 * F, D), jnp.bfloat16))

    def moe_f(x, mode):
        return moe_ffn_stats(x, rw, wg, wu, wdn, top_k=a.topk,
                             dispatch=mode)[0]

    def dense_f(x):
        return jnp.einsum(
            "btf,fd->btd",
            jax.nn.silu(jnp.einsum("btd,df->btf", x, wg2))
            * jnp.einsum("btd,df->btf", x, wu2), wd2)

    for name, fn in [("grouped", lambda x: moe_f(x, "grouped")),
                     ("einsum", lambda x: moe_f(x, "einsum")),
                     ("dense-iso", dense_f)]:
        fwd = timeit(fn, x3, reps=80)
        grad = timeit(
            lambda x: jax.grad(lambda z: jnp.sum(fn(z).astype(jnp.float32)))(x),
            x3, reps=80)
        print(f"ffn {name}: fwd {fwd:.2f} ms, grad {grad:.2f} ms")


if __name__ == "__main__":
    import sys

    sys.path.insert(0, __file__.rsplit("/", 2)[0])
    sys.exit(main())
