"""Micro-benchmark of the grouped-MoE pieces on the real chip: routing
index math, the three grouped matmuls, the two row gathers — to find where
a step's time actually goes before tuning blocks.  Not an artifact bench;
a tuning tool."""

import argparse
import time

import jax
import jax.numpy as jnp


def _scan_time(fn, x0, rest, reps):
    @jax.jit
    def scanned(x0, rest):
        def body(x, _):
            y = fn(x, *rest)
            leaves = jax.tree.leaves(y)
            s = sum(jnp.sum(l).astype(jnp.float32) for l in leaves)
            # Thread the output into the next iteration through a term XLA
            # cannot fold away: ``s * 0`` is constant-folded under
            # --xla_allow_excess_precision (the whole body then hoists out
            # of the loop and the op measures as ~free); ``s * 1e-30`` is
            # a runtime value, while numerically x + ~1e-27 rounds to x,
            # so the measured op is unperturbed but never loop-invariant.
            return x + (s * 1e-30).astype(x.dtype), None

        out, _ = jax.lax.scan(body, x0, None, length=reps)
        return jnp.sum(out.astype(jnp.float32))

    float(scanned(x0, rest))  # compile + complete
    best = float("inf")
    for _ in range(2):  # best-of-2: relay hiccups are one-sided noise
        t0 = time.time()
        float(scanned(x0, rest))
        best = min(best, time.time() - t0)
    return best


def timeit(fn, *args, reps=160):
    """Per-iteration time of ``fn`` with the FIXED cost removed by
    two-point extrapolation: run the scan at ``reps`` and ``4*reps`` and
    return ``(T(4N) - T(N)) / (3N)``.

    A single scanned run still carries the relayed backend's ~60-85 ms
    per-CALL overhead, which at N=160 is a ~0.5 ms/iter phantom floor —
    large enough to dominate sub-millisecond ops and the reason round 3's
    micro-decomposition overstated the gather and tgmm costs (docs/PERF.md
    measurement caveats).  Differencing two runs cancels every
    rep-independent cost (dispatch, relay round-trip, output transfer)
    exactly; the 4x spread keeps the signal well above the relay's
    per-call jitter (a 2x spread measured 0.00 ms on a 2.6 ms op), and
    each point is best-of-2 because that jitter is one-sided."""
    x0 = args[0]
    t1 = _scan_time(fn, x0, args[1:], reps)
    t2 = _scan_time(fn, x0, args[1:], 4 * reps)
    return max(t2 - t1, 1e-9) / (3 * reps) * 1e3


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--bt", type=int, default=8192, help="B*T tokens")
    p.add_argument("--dim", type=int, default=1024)
    p.add_argument("--inter", type=int, default=2816)
    p.add_argument("--experts", type=int, default=8)
    p.add_argument("--topk", type=int, default=2)
    p.add_argument("--bm", type=int, default=256)
    p.add_argument("--bn", type=int, default=1408)
    p.add_argument("--bk", type=int, default=1408)
    a = p.parse_args()

    from kubeflow_controller_tpu.ops.grouped_matmul import gmm

    N = a.bt * a.topk
    D, F, E, bm = a.dim, a.inter, a.experts, a.bm
    M = N + E * bm
    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (a.bt, D), jnp.bfloat16)
    wg = jax.random.normal(key, (E, D, F), jnp.bfloat16)
    wd = jax.random.normal(key, (E, F, D), jnp.bfloat16)
    slot_expert = jax.random.randint(key, (N,), 0, E)

    @jax.jit
    def route(slot_expert):
        sort_idx = jnp.argsort(slot_expert)
        sorted_experts = jnp.take(slot_expert, sort_idx)
        counts = jnp.sum(jax.nn.one_hot(slot_expert, E, dtype=jnp.int32), axis=0)
        group_start = jnp.cumsum(counts) - counts
        padded = ((counts + bm - 1) // bm) * bm
        pad_off = jnp.cumsum(padded) - padded
        rank = jnp.arange(N) - jnp.take(group_start, sorted_experts)
        dest = (jnp.take(pad_off, sorted_experts) + rank).astype(jnp.int32)
        ends = pad_off + padded
        te = jnp.minimum(jnp.searchsorted(
            ends, jnp.arange(M // bm) * bm, side="right"), E - 1).astype(jnp.int32)
        inv_src = jnp.full((M,), a.bt, jnp.int32).at[dest].set(
            (sort_idx // a.topk).astype(jnp.int32))
        return te, inv_src, dest

    te, inv_src, dest = jax.block_until_ready(route(slot_expert))
    print(f"route(index math): {timeit(route, slot_expert):.2f} ms")

    @jax.jit
    def gather(x, inv_src):
        x_pad = jnp.concatenate([x, jnp.zeros((1, D), x.dtype)], axis=0)
        return jnp.take(x_pad, inv_src, axis=0)

    x_pad = jax.block_until_ready(gather(x, inv_src))
    print(f"gather [{M}x{D}]: {timeit(gather, x, inv_src):.2f} ms")

    f = jax.jit(lambda l, r: gmm(l, r, te, None, bm, a.bn, a.bk))
    print(f"gmm up [{M}x{D}]@[{E}x{D}x{F}] bm={bm} bn={a.bn} bk={a.bk}: "
          f"{timeit(f, x_pad, wg):.2f} ms")
    h = jax.block_until_ready(f(x_pad, wg))
    fd = jax.jit(lambda l, r: gmm(l, r, te, None, bm, a.bn, a.bk))
    print(f"gmm down [{M}x{F}]@[{E}x{F}x{D}]: {timeit(fd, h, wd):.2f} ms")

    flops = 2 * M * D * F
    gmm_ms = timeit(f, x_pad, wg)
    xla_ms = timeit(lambda l, r: l @ r, x_pad, wg[0])
    print(f"xla dense same-FLOPs [{M}x{D}]@[{D}x{F}]: {xla_ms:.2f} ms "
          f"({flops / 1e9 / xla_ms:.0f} TFLOP/s) vs gmm {gmm_ms:.2f} ms "
          f"({flops / 1e9 / gmm_ms:.0f} TFLOP/s)")

    # Whole-FFN comparison: grouped vs einsum vs iso-active dense SwiGLU,
    # forward and grad.
    from kubeflow_controller_tpu.models.moe import moe_ffn_stats

    B, T = 8, a.bt // 8
    x3 = jax.random.normal(key, (B, T, D), jnp.bfloat16)
    rw = jax.random.normal(key, (D, E), jnp.bfloat16) * 0.1
    wu = jax.random.normal(key, (E, D, F), jnp.bfloat16)
    wdn = jax.random.normal(key, (E, F, D), jnp.bfloat16)
    wg2, wu2, wd2 = (jax.random.normal(key, (D, 2 * F), jnp.bfloat16),
                     jax.random.normal(key, (D, 2 * F), jnp.bfloat16),
                     jax.random.normal(key, (2 * F, D), jnp.bfloat16))

    def moe_f(x, mode, cf=1.25):
        return moe_ffn_stats(x, rw, wg, wu, wdn, top_k=a.topk,
                             capacity_factor=cf, dispatch=mode)[0]

    def dense_f(x):
        return jnp.einsum(
            "btf,fd->btd",
            jax.nn.silu(jnp.einsum("btd,df->btf", x, wg2))
            * jnp.einsum("btd,df->btf", x, wu2), wd2)

    # The grouped path is dropless and capacity-free; the einsum path's
    # cost scales with capacity_factor (E*C = T*k*cf slots of dispatch AND
    # expert compute) — sweep cf to locate the crossover.
    for name, fn in [("grouped (dropless)", lambda x: moe_f(x, "grouped")),
                     ("einsum cf=1.0", lambda x: moe_f(x, "einsum", 1.0)),
                     ("einsum cf=1.25", lambda x: moe_f(x, "einsum", 1.25)),
                     ("einsum cf=2.0", lambda x: moe_f(x, "einsum", 2.0)),
                     ("dense-iso", dense_f)]:
        fwd = timeit(fn, x3, reps=80)
        grad = timeit(
            lambda x: jax.grad(lambda z: jnp.sum(fn(z).astype(jnp.float32)))(x),
            x3, reps=80)
        print(f"ffn {name}: fwd {fwd:.2f} ms, grad {grad:.2f} ms")


if __name__ == "__main__":
    import sys

    sys.path.insert(0, __file__.rsplit("/", 2)[0])
    sys.exit(main())
