"""Llama pretrain throughput on real TPU.

Method notes (important on tunneled/relayed TPU backends): repeated
dispatch of one jitted step can pipeline asynchronously and report
impossible speeds — ``block_until_ready`` alone is not a trustworthy
barrier through the relay.  So K optimizer steps run inside ONE jitted
``lax.scan`` and the final loss is read back to the host, which forces
completion of the whole chain; per-call overhead amortizes across K.

FLOP accounting is 6*N*D (params x tokens, fwd+bwd, no remat recompute
counted) — the standard "model FLOPs" so numbers compare across
frameworks; with full remat the hardware additionally executes ~1 extra
forward (~8ND total).

Measured on v5e (1 chip, bf16, full remat), 953M-param Llama
(dim 2048, L16, H16, inter 5632, T 1024):
  B=16: ~15.6k tokens/s, ~89 model-TFLOP/s (6ND) == ~60% of bf16 peak
        counting the remat recompute.
"""

from __future__ import annotations

import argparse
import sys
import time


def run(batch: int, seq: int, steps: int, dim: int, layers: int, heads: int,
        intermediate: int, policy: str) -> dict:
    import jax
    import jax.numpy as jnp
    import optax

    from kubeflow_controller_tpu.models import LlamaConfig, llama_init, llama_loss
    from kubeflow_controller_tpu.parallel import MeshSpec, build_mesh

    cfg = LlamaConfig(
        vocab_size=32000, dim=dim, n_layers=layers, n_heads=heads,
        n_kv_heads=heads, intermediate=intermediate, max_seq_len=seq,
        dtype="bfloat16", param_dtype="bfloat16", remat=True,
        remat_policy=policy,
    )
    mesh = build_mesh(MeshSpec(fsdp=-1))
    params = jax.jit(lambda k: llama_init(k, cfg))(jax.random.PRNGKey(0))
    n_params = sum(x.size for x in jax.tree.leaves(params))
    opt = optax.adafactor(3e-4)
    opt_state = opt.init(params)
    toks = jax.random.randint(
        jax.random.PRNGKey(1), (steps, batch, seq), 0, cfg.vocab_size)

    with jax.set_mesh(mesh):
        @jax.jit
        def run_steps(p, s, toks):
            def body(carry, t):
                p, s = carry
                loss, g = jax.value_and_grad(
                    lambda p: llama_loss(p, t, cfg, mesh=mesh))(p)
                u, s = opt.update(g, s, p)
                return (optax.apply_updates(p, u), s), loss

            (p, s), losses = jax.lax.scan(body, (p, s), toks)
            return p, s, losses[-1]

        _, _, loss = run_steps(params, opt_state, toks)
        float(loss)  # compile + complete
        t0 = time.time()
        _, _, loss = run_steps(params, opt_state, toks)
        loss_val = float(loss)  # host read == completion barrier
        dt = (time.time() - t0) / steps

    return {
        "params_m": round(n_params / 1e6, 1),
        "ms_per_step": round(dt * 1e3, 1),
        "tokens_per_s": round(batch * seq / dt),
        "model_tflops": round(6 * n_params * batch * seq / dt / 1e12, 1),
        "loss": round(loss_val, 3),
        "batch": batch, "seq": seq, "remat_policy": policy,
    }


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--batch", type=int, default=16)
    p.add_argument("--seq", type=int, default=1024)
    p.add_argument("--steps", type=int, default=8)
    p.add_argument("--dim", type=int, default=2048)
    p.add_argument("--layers", type=int, default=16)
    p.add_argument("--heads", type=int, default=16)
    p.add_argument("--intermediate", type=int, default=5632)
    p.add_argument("--remat-policy", default="full", choices=["full", "dots"])
    args = p.parse_args()
    out = run(args.batch, args.seq, args.steps, args.dim, args.layers,
              args.heads, args.intermediate, args.remat_policy)
    import json

    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.path.insert(0, __file__.rsplit("/", 2)[0])
    sys.exit(main())
