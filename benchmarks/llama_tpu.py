"""Llama pretrain throughput on real TPU.

Method notes (important on tunneled/relayed TPU backends): repeated
dispatch of one jitted step can pipeline asynchronously and report
impossible speeds — ``block_until_ready`` alone is not a trustworthy
barrier through the relay.  So K optimizer steps run inside ONE jitted
``lax.scan`` and the final loss is read back to the host, which forces
completion of the whole chain; per-call overhead amortizes across K.

FLOP accounting is 6*N*D (params x tokens, fwd+bwd, no remat recompute
counted) — the standard "model FLOPs" so numbers compare across
frameworks.  ``mfu_pct`` divides by the MEASURED session compute ceiling
(benchmarks/chip_calib.py: the sustained bf16 SwiGLU-FFN-chain rate,
262.1 TFLOP/s this session) — NOT a nominal datasheet peak: the chip
behind the relay sustains well above the v5e's 197 TFLOP/s bf16 peak, so
the "v5e" label is wrong and MFU against 197 was inflated (round-5
finding; chip_calib.json records the evidence).  With full remat the hardware additionally executes ~1 extra
forward (~8ND total); the named policies ("ffn"/"gateup",
models/llama.py:_maybe_remat) save the FLOPs-dominant matmuls and cut
that recompute where "dots" OOMs.

One command produces the checked-in artifact:

    python benchmarks/llama_tpu.py --sweep --out benchmarks/llama_tpu_v5e.json

which runs the config grid, records every point, and reports the best.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

# MFU denominator: the MEASURED session ceiling, NOT the v5e datasheet
# 197 (the tunneled chip sustains ~262 TFLOP/s bf16 on the FFN matmul
# chain, which a real v5e cannot).  Chip speed drifts between sessions,
# so the checked-in chip_calib.json (re-runnable via
# `python benchmarks/chip_calib.py`) is read at startup when present;
# the constant is only the last-measured fallback.
MEASURED_BF16_CEILING_TFLOPS = 262.1


def _session_peak() -> float:
    try:
        calib = json.load(open(os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "chip_calib.json")))
        return float(calib["rows"]["ffn_chain_bf16"]["tflops"])
    except Exception:
        return MEASURED_BF16_CEILING_TFLOPS


def run(batch: int, seq: int, steps: int, dim: int, layers: int, heads: int,
        intermediate: int, policy: str, peak_tflops: float,
        loss_chunks: int = 0, experts: int = 0, top_k: int = 2,
        moe_dispatch: str = "einsum", attention: str = "auto") -> dict:
    import jax
    import optax

    from kubeflow_controller_tpu.models import LlamaConfig, llama_init, llama_loss
    from kubeflow_controller_tpu.parallel import MeshSpec, build_mesh

    cfg = LlamaConfig(
        vocab_size=32000, dim=dim, n_layers=layers, n_heads=heads,
        n_kv_heads=heads, intermediate=intermediate, max_seq_len=seq,
        dtype="bfloat16", param_dtype="bfloat16", remat=True,
        remat_policy=policy, loss_chunks=loss_chunks,
        n_experts=experts, moe_top_k=top_k, moe_dispatch=moe_dispatch,
        attention=attention,
    )
    mesh = build_mesh(MeshSpec(fsdp=-1))
    params = jax.jit(lambda k: llama_init(k, cfg))(jax.random.PRNGKey(0))
    n_params = sum(x.size for x in jax.tree.leaves(params))
    # MoE: 6ND must count ACTIVATED params — each token runs top_k of E
    # experts, so counting all expert weights inflates MFU beyond 100%.
    n_active = n_params
    if experts:
        expert_params = sum(
            params["layers"][k].size for k in ("w_gate", "w_up", "w_down"))
        n_active = n_params - expert_params + expert_params * top_k // experts
    opt = optax.adafactor(3e-4)
    opt_state = opt.init(params)
    toks = jax.random.randint(
        jax.random.PRNGKey(1), (steps, batch, seq), 0, cfg.vocab_size)

    with jax.set_mesh(mesh):
        # NOTE: no donate_argnums and outputs deliberately discarded — on the
        # tunneled (axon relay) backend, feeding a jit's outputs back as the
        # next call's inputs measures 3x slower (relayout via host), and
        # donation hits the same path.  Steady-state step cost is what the
        # in-scan training loop pays, so time repeated calls on constant
        # inputs instead (docs/PERF.md "Measurement caveat").
        @jax.jit
        def run_steps(p, s, toks):
            def body(carry, t):
                p, s = carry
                loss, g = jax.value_and_grad(
                    lambda p: llama_loss(p, t, cfg, mesh=mesh))(p)
                u, s = opt.update(g, s, p)
                return (optax.apply_updates(p, u), s), loss

            (p, s), losses = jax.lax.scan(body, (p, s), toks)
            return p, s, losses[-1]

        _, _, loss = run_steps(params, opt_state, toks)
        float(loss)  # compile + complete
        t0 = time.time()
        _, _, loss = run_steps(params, opt_state, toks)
        loss_val = float(loss)  # host read == completion barrier
        dt = (time.time() - t0) / steps

    tflops = 6 * n_active * batch * seq / dt / 1e12
    hw = hw_tflops_per_s(6 * n_active * batch * seq, batch, seq, layers,
                         heads, dim // heads, policy, dt)
    return {
        "params_m": round(n_params / 1e6, 1),
        "active_params_m": round(n_active / 1e6, 1),
        "ms_per_step": round(dt * 1e3, 1),
        "tokens_per_s": round(batch * seq / dt),
        "model_tflops": round(tflops, 1),
        "mfu_pct": round(100 * tflops / peak_tflops, 1),
        "hw_tflops": round(hw, 1),
        "hw_mfu_pct": round(100 * hw / peak_tflops, 1),
        "loss": round(loss_val, 3),
        "batch": batch, "seq": seq, "remat_policy": policy,
        "loss_chunks": loss_chunks, "experts": experts,
    }


def hw_tflops_per_s(model_flops: float, batch: int, seq: int, layers: int,
                    heads: int, head_dim: int, policy: str,
                    dt: float) -> float:
    """Hardware-FLOPs-inclusive throughput: 6ND model FLOPs plus the
    attention FLOPs the chip actually executes, which 6ND ignores and
    which dominate the 6ND-MFU slide at long T (docs/PERF.md).

    Attention per layer, causal (~half the T^2 square): forward = 2
    matmuls = 2*B*T^2*H*d FLOPs; backward ~2x forward (dQ/dK/dV); remat
    policies that do not save attention outputs (everything except
    gateup_attn and moe, which both save "attn_proj" —
    models/llama.py:_maybe_remat) recompute the forward once more in the
    backward.  Other recomputed ops are still NOT counted — this column
    isolates the attention-FLOP accounting gap, not total executed work."""
    attn_fwd = 2.0 * batch * seq * seq * heads * head_dim * layers
    factor = 3.0 if policy in ("gateup_attn", "moe") else 4.0
    return (model_flops + factor * attn_fwd) / dt / 1e12


def run_subprocess(args_list) -> dict:
    from benchmarks._common import run_bench_subprocess

    return run_bench_subprocess(os.path.abspath(__file__), args_list)


def sweep(steps: int, out_path: str, peak: float, shape: dict) -> int:
    # The grid: remat policies at the judged 953M size, B and T scaling.
    # Flash attention is on (LlamaConfig.attention="auto") for every point.
    # The MoE A/B triple (docs/PERF.md): 653M-total/238M-active E8 top2 at
    # dim 1024 / L8 / inter 2816, vs the iso-active 238M dense (inter 5632).
    moe_shape = dict(dim=1024, layers=8, heads=16, intermediate=2816)
    iso_dense = dict(dim=1024, layers=8, heads=16, intermediate=5632)
    grid = [
        # The round-1 baseline row: XLA fused attention instead of the
        # Pallas flash kernel — keeps the 45% -> 61% story in ONE artifact.
        dict(batch=16, seq=1024, policy="full", attention="xla"),
        dict(batch=16, seq=1024, policy="full"),
        dict(batch=16, seq=1024, policy="dots"),
        dict(batch=16, seq=1024, policy="ffn"),
        dict(batch=16, seq=1024, policy="gateup"),
        dict(batch=16, seq=1024, policy="gateup_attn"),
        dict(batch=16, seq=1024, policy="gateup_attn", chunks=8),
        dict(batch=32, seq=1024, policy="gateup"),
        dict(batch=8, seq=2048, policy="gateup"),
        dict(batch=8, seq=2048, policy="full"),
        dict(batch=4, seq=4096, policy="gateup"),
        dict(batch=4, seq=4096, policy="gateup", chunks=16),
        dict(batch=4, seq=4096, policy="full"),
        # Long-context: possible at all only because flash attention never
        # materializes the T^2 scores (XLA attention fails to compile at
        # T=8192 on one chip — docs/PERF.md kernel table).
        dict(batch=2, seq=8192, policy="gateup"),
        dict(batch=2, seq=8192, policy="gateup_attn"),
        # MoE A/B: iso-active dense bar, then capacity-einsum dispatch,
        # then the dropless grouped-matmul kernels (ops/grouped_matmul.py)
        # under the MoE-aware remat policy.
        dict(batch=8, seq=1024, policy="gateup", shape=iso_dense,
             triple="iso-dense"),
        dict(batch=8, seq=1024, policy="gateup", shape=moe_shape,
             experts=8, dispatch="einsum", triple="einsum"),
        dict(batch=8, seq=1024, policy="moe", shape=moe_shape,
             experts=8, dispatch="grouped", triple="grouped"),
    ]
    results = []
    for g in grid:
        s = g.get("shape", shape)
        r = run_subprocess([
            "--batch", g["batch"], "--seq", g["seq"], "--steps", steps,
            "--remat-policy", g["policy"],
            "--loss-chunks", g.get("chunks", 0),
            "--experts", g.get("experts", 0),
            "--moe-dispatch", g.get("dispatch", "einsum"),
            "--attention", g.get("attention", "auto"),
            # Forward peak + model shape so per-point mfu_pct is computed
            # against the same values the artifact header records.
            "--peak-tflops", peak, "--dim", s["dim"],
            "--layers", s["layers"], "--heads", s["heads"],
            "--intermediate", s["intermediate"],
        ])
        r.setdefault("batch", g["batch"])
        r.setdefault("seq", g["seq"])
        r.setdefault("remat_policy", g["policy"])
        r.setdefault("loss_chunks", g.get("chunks", 0))
        for key in ("experts", "dispatch", "attention", "triple"):
            if g.get(key):
                r.setdefault(key, g[key])
        if "shape" in g:
            r["shape"] = g["shape"]
        results.append(r)
        print(json.dumps(r), flush=True)
        # Incremental write: a sweep interrupted at row k keeps rows < k
        # (each point costs minutes of relay compile time).
        best = _write_artifact(out_path, peak, shape, results)
    print(json.dumps({"best": best, "artifact": out_path}))
    return 0 if best else 1


def _write_artifact(out_path: str, peak: float, shape: dict, results,
                    model_str: str = ""):
    """Writes the artifact; returns the current best row (or None)."""
    ok = [r for r in results if "model_tflops" in r]
    best = max(ok, key=lambda r: r["model_tflops"]) if ok else None
    artifact = {
        "bench": "llama_tpu_single_chip",
        "accounting": (
            "model_tflops/mfu_pct: 6ND model FLOPs (no remat recompute "
            "counted).  hw_tflops/hw_mfu_pct: adds the EXECUTED attention "
            "FLOPs (causal ~T^2/2; fwd + 2x bwd + 1x remat recompute "
            "unless the policy saves attention) — see hw_tflops_per_s; "
            "other recompute still uncounted"),
        "moe_triple_note": (
            "rows tagged 'triple' are the same-session MoE A/B set "
            "(iso-active dense / capacity-einsum / dropless-grouped); "
            "compare within the tag, not across sessions"),
        "peak_tflops_bf16": peak,
        "peak_basis": (
            "measured session ceiling (chip_calib.py ffn_chain_bf16), not "
            "a datasheet peak: the relay chip sustains ~262 TFLOP/s bf16, "
            "impossible on a nominal v5e (197) — earlier rounds' MFU "
            "against 197 was inflated"),
        "model": model_str or (
            f"Llama (dim {shape['dim']}, L{shape['layers']}, "
            f"H{shape['heads']}, inter {shape['intermediate']}), "
            "adafactor, bf16"),
        "best": best,
        "results": results,
    }
    with open(out_path, "w") as f:
        json.dump(artifact, f, indent=1)
    return best


def triple_only(steps: int, out_path: str, peak: float) -> int:
    """Re-measure ONLY the same-session MoE A/B triple and merge it into
    the existing artifact; every retained row's mfu_pct/hw_mfu_pct is
    rescaled to the CURRENT peak basis (mfu is derived arithmetic —
    model_tflops/ms are the measurements and stay as recorded; see
    peak_basis in the artifact header)."""
    moe_shape = dict(dim=1024, layers=8, heads=16, intermediate=2816)
    iso_dense = dict(dim=1024, layers=8, heads=16, intermediate=5632)
    grid = [
        dict(batch=8, seq=1024, policy="gateup", shape=iso_dense,
             triple="iso-dense"),
        dict(batch=8, seq=1024, policy="gateup", shape=moe_shape,
             experts=8, dispatch="einsum", triple="einsum"),
        dict(batch=8, seq=1024, policy="moe", shape=moe_shape,
             experts=8, dispatch="grouped", triple="grouped"),
    ]
    try:
        doc = json.load(open(out_path))
    except (FileNotFoundError, json.JSONDecodeError):
        doc = {}
    kept = [r for r in doc.get("results", []) if not r.get("triple")]
    # The artifact header's model string describes the SWEEP shape, which
    # --triple does not re-measure: preserve it rather than re-deriving.
    kept_model = doc.get("model")
    for r in kept:
        if "model_tflops" in r:
            r["mfu_pct"] = round(100 * r["model_tflops"] / peak, 1)
            if "hw_tflops" in r:
                r["hw_mfu_pct"] = round(100 * r["hw_tflops"] / peak, 1)
    results = kept
    shape = dict(dim=2048, layers=16, heads=16, intermediate=5632)
    for g in grid:
        s = g["shape"]
        r = run_subprocess([
            "--batch", g["batch"], "--seq", g["seq"], "--steps", steps,
            "--remat-policy", g["policy"],
            "--experts", g.get("experts", 0),
            "--moe-dispatch", g.get("dispatch", "einsum"),
            "--peak-tflops", peak, "--dim", s["dim"],
            "--layers", s["layers"], "--heads", s["heads"],
            "--intermediate", s["intermediate"],
        ])
        r.setdefault("batch", g["batch"])
        r.setdefault("seq", g["seq"])
        r.setdefault("remat_policy", g["policy"])
        for key in ("experts", "dispatch", "triple"):
            if g.get(key):
                r.setdefault(key, g[key])
        r["shape"] = s
        results.append(r)
        print(json.dumps(r), flush=True)
        _write_artifact(out_path, peak, shape, results,
                        model_str=kept_model)
    return 0


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--batch", type=int, default=16)
    p.add_argument("--seq", type=int, default=1024)
    p.add_argument("--steps", type=int, default=8)
    p.add_argument("--dim", type=int, default=2048)
    p.add_argument("--layers", type=int, default=16)
    p.add_argument("--heads", type=int, default=16)
    p.add_argument("--intermediate", type=int, default=5632)
    p.add_argument("--remat-policy", default="full",
                   choices=["full", "dots", "ffn", "gateup", "gateup_attn",
                            "moe"])
    p.add_argument("--loss-chunks", type=int, default=0,
                   help="chunked cross-entropy (0 = dense logits)")
    p.add_argument("--experts", type=int, default=0, help="MoE experts (0=dense)")
    p.add_argument("--top-k", type=int, default=2)
    p.add_argument("--moe-dispatch", default="einsum",
                   choices=["einsum", "scatter", "grouped"])
    p.add_argument("--attention", default="auto",
                   choices=["auto", "flash", "xla"])
    p.add_argument("--peak-tflops", type=float, default=_session_peak())
    p.add_argument("--sweep", action="store_true",
                   help="run the config grid and write the JSON artifact")
    p.add_argument("--triple", action="store_true",
                   help="re-measure only the MoE A/B triple and merge "
                        "(rescales retained rows' mfu to the current peak)")
    p.add_argument("--out", default="benchmarks/llama_tpu_v5e.json")
    args = p.parse_args()
    if args.triple:
        return triple_only(args.steps, args.out, args.peak_tflops)
    if args.sweep:
        return sweep(args.steps, args.out, args.peak_tflops,
                     dict(dim=args.dim, layers=args.layers, heads=args.heads,
                          intermediate=args.intermediate))
    out = run(args.batch, args.seq, args.steps, args.dim, args.layers,
              args.heads, args.intermediate, args.remat_policy,
              args.peak_tflops, loss_chunks=args.loss_chunks,
              experts=args.experts, top_k=args.top_k,
              moe_dispatch=args.moe_dispatch, attention=args.attention)
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.path.insert(0, __file__.rsplit("/", 2)[0])
    sys.exit(main())
