"""Grouped-matmul kernel tuning sweep on the real chip (round-5 VERDICT
item 1): block-shape sweep for the single-k gmm at the bench shapes, a
same-shape dense-Pallas control (E=1, no grouping, no padding) and an XLA
dense matmul to isolate (a) grouped-dispatch overhead from (b) Pallas-vs-XLA
kernel overhead, plus full grouped-FFN fwd+grad points per block_m.

Also starts with a CALIBRATION point: big dense XLA matmuls with known
FLOPs, to pin the chip's actually-achievable TFLOP/s this session (the
v5e bf16 peak is 197; a dense control reading above that means the chip is
not a v5e or the harness is broken — see tpu-relay measurement caveats in
docs/PERF.md).

    python benchmarks/gmm_tune.py --sweep --out benchmarks/gmm_tune_v5e.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _mk_te(M, bm, E, key):
    """Balanced group-aligned tile->expert map: tiles evenly split over E
    experts in order (the layout _grouped_ffn produces under balanced
    routing)."""
    import jax.numpy as jnp

    n_tiles = M // bm
    return (jnp.arange(n_tiles, dtype=jnp.int32) * E // n_tiles).astype(
        jnp.int32)


def point(kind: str, a) -> dict:
    import jax
    import jax.numpy as jnp

    from moe_micro import timeit

    key = jax.random.PRNGKey(0)
    D, F, E, k = a.dim, a.inter, a.experts, a.topk
    n_slots = a.bt * k
    out: dict = {"kind": kind}

    if kind == "calib":
        # Known-FLOPs dense matmuls -> this session's achievable TFLOP/s.
        for name, (m, kk, n) in {
            "mm_8k": (8192, 8192, 8192),
            "mm_bench_up": (18432, 1024, 2816),
            "mm_bench_down": (18432, 2816, 1024),
        }.items():
            x = jax.random.normal(key, (m, kk), jnp.bfloat16)
            w = jax.random.normal(key, (kk, n), jnp.bfloat16)
            ms = timeit(lambda x: x @ w, x, reps=160)
            out[name] = {"ms": round(ms, 4),
                         "tflops": round(2 * m * kk * n / ms / 1e9, 1)}
        return out

    if kind in ("gmm", "gmm_dense_ctl", "gmm_par", "gmm_pa", "gmm_multik"):
        # Single gmm forward at a bench shape.  gmm_dense_ctl: E=1 and no
        # padding — the same kernel minus every grouping effect.
        import kubeflow_controller_tpu.ops.grouped_matmul as gm
        from kubeflow_controller_tpu.ops.grouped_matmul import (
            _single_k_blocks,
            gmm,
        )

        # Schedule experiments: gmm_par/"gmm_pa" flip the single-k grid
        # semantics; gmm_multik forces the k-looped accumulator kernel.
        if kind == "gmm_par":
            gm._SINGLE_K_SEMANTICS = ("parallel", "parallel")
        elif kind == "gmm_pa":
            gm._SINGLE_K_SEMANTICS = ("parallel", "arbitrary")
        elif kind == "gmm_multik":
            gm._single_k_blocks = lambda *args, **kw: None

        K, N = (D, F) if a.shape == "up" else (F, D)
        E_eff = 1 if kind == "gmm_dense_ctl" else E
        M = n_slots if kind == "gmm_dense_ctl" else n_slots + E * a.bm
        lhs = jax.random.normal(key, (M, K), jnp.bfloat16)
        rhs = jax.random.normal(key, (E_eff, K, N), jnp.bfloat16)
        te = (jnp.zeros((M // a.bm,), jnp.int32) if E_eff == 1
              else _mk_te(M, a.bm, E, key))
        ms = timeit(lambda l: gmm(l, rhs, te, None, a.bm, a.bn, a.bn),
                    lhs, reps=320)
        flops = 2 * M * K * N
        out.update(shape=a.shape, bm=a.bm, bn=a.bn,
                   bn_eff=_single_k_blocks(M, K, N, a.bm, a.bn, 2), M=M,
                   ms=round(ms, 4), tflops=round(flops / ms / 1e9, 1))
        return out

    if kind == "ffn":
        # Full grouped FFN (fwd and fwd+grad) at block_m, through the real
        # moe path (single-shard _grouped_ffn + gmm_swiglu fusion).
        from kubeflow_controller_tpu.models.moe import moe_ffn_stats

        B, T = 8, a.bt // 8
        x = jax.random.normal(key, (B, T, D), jnp.bfloat16)
        rw = jax.random.normal(key, (D, E), jnp.bfloat16) * 0.1
        wg = jax.random.normal(key, (E, D, F), jnp.bfloat16)
        wu = jax.random.normal(key, (E, D, F), jnp.bfloat16)
        wd = jax.random.normal(key, (E, F, D), jnp.bfloat16)

        import kubeflow_controller_tpu.models.moe as moe_mod

        def f(x):
            return moe_ffn_stats(x, rw, wg, wu, wd, top_k=k,
                                 dispatch="grouped",
                                 block_m=a.bm)[0]

        fwd = timeit(f, x, reps=120)
        grad = timeit(
            lambda x: jax.grad(lambda z: jnp.sum(f(z).astype(jnp.float32)))(x),
            x, reps=80)
        out.update(bm=a.bm, fwd_ms=round(fwd, 3), grad_ms=round(grad, 3),
                   step_ms=round(fwd + grad, 3))
        return out

    raise SystemExit(f"unknown kind {kind}")


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--kind", default="")
    p.add_argument("--shape", default="up", choices=["up", "down"])
    p.add_argument("--bt", type=int, default=8192)
    p.add_argument("--dim", type=int, default=1024)
    p.add_argument("--inter", type=int, default=2816)
    p.add_argument("--experts", type=int, default=8)
    p.add_argument("--topk", type=int, default=2)
    p.add_argument("--bm", type=int, default=256)
    p.add_argument("--bn", type=int, default=1408)
    p.add_argument("--sweep", action="store_true")
    p.add_argument("--out", default="benchmarks/gmm_tune_v5e.json")
    a = p.parse_args()

    if not a.sweep:
        print(json.dumps(point(a.kind, a)))
        return 0

    from _common import run_bench_subprocess, save_artifact

    here = os.path.abspath(__file__)
    doc = {"bench": "gmm_tune",
           "config": {"bt": a.bt, "dim": a.dim, "inter": a.inter,
                      "experts": a.experts, "topk": a.topk,
                      "dtype": "bfloat16"},
           "method": ("two-point scan extrapolation per point "
                      "(moe_micro.timeit); each point its own subprocess "
                      "with a shared XLA compile cache"),
           "rows": []}

    def run(kind, **kw):
        args = ["--kind", kind]
        for key, v in kw.items():
            args += [f"--{key}", v]
        r = run_bench_subprocess(here, args)
        r.setdefault("kind", kind)
        r.update({k: v for k, v in kw.items() if k not in r})
        doc["rows"].append(r)
        print(json.dumps(r), flush=True)
        save_artifact(a.out, doc)

    run("calib")
    for shape in ("up", "down"):
        run("gmm_dense_ctl", shape=shape, bm=256, bn=1408)
        for bm in (128, 256, 512):
            # bn requests clamp to the largest VMEM-feasible 128-multiple
            # divisor (bn_eff in the row); 256 probes the narrow end.
            for bn in (256, 1408):
                run("gmm", shape=shape, bm=bm, bn=bn)
    for bm in (128, 256, 512):
        run("ffn", bm=bm)
    return 0


if __name__ == "__main__":
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    sys.exit(main())
