"""Sequence-parallel attention schedule cost — the first SP timing table.

Two measurements bound the ring/Ulysses overhead without a multi-chip
machine:

1. **1-chip TPU machinery A/B** (``--tpu``): plain attention vs the same
   shapes routed through ``ring_attention`` / ``ulysses_attention`` on an
   sp=1 mesh.  With one shard the ring makes zero ppermute hops and
   Ulysses' all-to-alls are identity, so the delta IS the shard_map +
   schedule machinery cost — the fixed overhead SP adds before any
   communication happens.

2. **8-device CPU mesh scaling** (``--cpu``): fwd+bwd wall time at a fixed
   GLOBAL sequence length while sp grows 1 -> 8.  CPU milliseconds are not
   TPU milliseconds, but the *shape* of the curve exposes schedule
   pathologies (a schedule that serializes or copies superlinearly shows
   up immediately; per-step collective counts are identical on TPU).

Artifact: ``python benchmarks/sp_bench.py --tpu --cpu --out
benchmarks/sp_sched.json``.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def timeit_grad(fn, *args, reps=40):
    """fwd+bwd time per call, measured inside one jitted scan (see
    moe_micro.timeit for why per-call dispatch cannot be trusted)."""
    import jax
    import jax.numpy as jnp

    def loss(x, rest):
        return jnp.sum(fn(x, *rest).astype(jnp.float32))

    g = jax.grad(loss)

    @jax.jit
    def scanned(x0, rest):
        def body(x, _):
            dx = g(x, rest)
            return x + 0 * dx, None

        out, _ = jax.lax.scan(body, x0, None, length=reps)
        return jnp.sum(out.astype(jnp.float32))

    float(scanned(args[0], args[1:]))
    t0 = time.time()
    float(scanned(args[0], args[1:]))
    return (time.time() - t0) / reps * 1e3


def bench_tpu_machinery(B, T, H, D, reps):
    import jax
    import jax.numpy as jnp

    from kubeflow_controller_tpu.ops.attention import flash_attention
    from kubeflow_controller_tpu.parallel import MeshSpec, build_mesh
    from kubeflow_controller_tpu.parallel.ring import (
        attention_reference,
        ring_attention,
    )
    from kubeflow_controller_tpu.parallel.ulysses import ulysses_attention

    key = jax.random.PRNGKey(0)
    shape = (B, T, H, D)
    q = jax.random.normal(key, shape, jnp.bfloat16)
    k = jax.random.normal(key, shape, jnp.bfloat16)
    v = jax.random.normal(key, shape, jnp.bfloat16)
    mesh = build_mesh(MeshSpec(fsdp=-1))  # all size-1 axes on one chip
    rows = {}
    with jax.set_mesh(mesh):
        rows["plain"] = timeit_grad(
            lambda q, k, v: attention_reference(q, k, v, causal=True),
            q, k, v, reps=reps)
        rows["flash"] = timeit_grad(
            lambda q, k, v: flash_attention(q, k, v, causal=True),
            q, k, v, reps=reps)
        rows["ring_sp1"] = timeit_grad(
            lambda q, k, v: ring_attention(q, k, v, mesh, causal=True),
            q, k, v, reps=reps)
        rows["ulysses_sp1"] = timeit_grad(
            lambda q, k, v: ulysses_attention(q, k, v, mesh, causal=True),
            q, k, v, reps=reps)
    return {"config": {"B": B, "T": T, "H": H, "D": D,
                       "what": "fwd+bwd ms, 1 real TPU chip, sp=1 mesh"},
            "ms": {k2: round(v2, 2) for k2, v2 in rows.items()}}


def bench_cpu_scaling(B, T, H, D, reps):
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding

    from kubeflow_controller_tpu.parallel import MeshSpec, build_mesh, logical_to_pspec
    from kubeflow_controller_tpu.parallel.ring import ring_attention
    from kubeflow_controller_tpu.parallel.ulysses import ulysses_attention

    key = jax.random.PRNGKey(0)
    shape = (B, T, H, D)
    out = []
    for sp in (1, 2, 4, 8):
        # Spare devices park on ep (no attention array uses it): batch
        # stays unsharded so small B never constrains the sp sweep.
        mesh = build_mesh(MeshSpec(sp=sp, ep=-1, fsdp=1))
        spec = logical_to_pspec(("batch", "seq", "heads", "head_dim"))
        sharding = NamedSharding(mesh, spec)
        q = jax.device_put(jax.random.normal(key, shape, jnp.float32), sharding)
        k = jax.device_put(jax.random.normal(key, shape, jnp.float32), sharding)
        v = jax.device_put(jax.random.normal(key, shape, jnp.float32), sharding)
        row = {"sp": sp}
        with jax.set_mesh(mesh):
            row["ring_ms"] = round(timeit_grad(
                lambda q, k, v: ring_attention(q, k, v, mesh, causal=True),
                q, k, v, reps=reps), 2)
            if H % (sp or 1) == 0:
                row["ulysses_ms"] = round(timeit_grad(
                    lambda q, k, v: ulysses_attention(q, k, v, mesh, causal=True),
                    q, k, v, reps=reps), 2)
        out.append(row)
        print(json.dumps(row), flush=True)
    return {"config": {"B": B, "T": T, "H": H, "D": D,
                       "what": "fwd+bwd ms, 8 virtual CPU devices, global T "
                               "fixed while sp grows (relative shape only)"},
            "rows": out}


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--tpu", action="store_true")
    p.add_argument("--cpu", action="store_true")
    p.add_argument("--cpu-inner", action="store_true",
                   help="(internal) run the CPU scaling in THIS process — "
                        "requires JAX_PLATFORMS=cpu and 8 virtual devices")
    p.add_argument("--batch", type=int, default=2)
    p.add_argument("--seq", type=int, default=2048,
                   help="global sequence length (the plain-attention "
                        "baseline materializes [B,H,T,T] f32 scores, so "
                        "keep B*T^2 within one chip's HBM)")
    p.add_argument("--heads", type=int, default=16)
    p.add_argument("--head-dim", type=int, default=64)
    p.add_argument("--reps", type=int, default=40)
    p.add_argument("--out", default="")
    args = p.parse_args()

    if args.cpu_inner:
        import jax

        jax.config.update("jax_platforms", "cpu")
        out = bench_cpu_scaling(args.batch, args.seq, args.heads,
                                args.head_dim, args.reps)
        print("CPU_SCALING " + json.dumps(out), flush=True)
        return 0

    artifact = {"bench": "sp_schedule_cost"}
    if args.tpu:
        artifact["tpu_machinery_sp1"] = bench_tpu_machinery(
            args.batch, args.seq, args.heads, args.head_dim, args.reps)
        print(json.dumps(artifact["tpu_machinery_sp1"]), flush=True)
    if args.cpu:
        # Own process: a jax client that already initialized the TPU
        # backend cannot host the 8-virtual-device CPU mesh.
        import os
        import subprocess

        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                            + " --xla_force_host_platform_device_count=8").strip()
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--cpu-inner",
             "--batch", str(args.batch), "--seq", str(args.seq),
             "--heads", str(args.heads), "--head-dim", str(args.head_dim),
             "--reps", str(args.reps)],
            capture_output=True, text=True, env=env,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        for line in out.stdout.splitlines():
            if line.startswith("CPU_SCALING "):
                artifact["cpu_scaling"] = json.loads(line[len("CPU_SCALING "):])
                break
        else:
            artifact["cpu_scaling"] = {
                "error": (out.stderr or "no output")[-400:].strip()}
        print(json.dumps(artifact["cpu_scaling"]), flush=True)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(artifact, f, indent=1)
        print(json.dumps({"artifact": args.out}))
    return 0


if __name__ == "__main__":
    sys.path.insert(0, __file__.rsplit("/", 2)[0])
    sys.exit(main())
