"""Sequence-parallel attention schedule cost — the first SP timing table.

Two measurements bound the ring/Ulysses overhead without a multi-chip
machine:

1. **1-chip TPU machinery A/B** (``--tpu``): plain attention vs the same
   shapes routed through ``ring_attention`` / ``ulysses_attention`` on an
   sp=1 mesh.  With one shard the ring makes zero ppermute hops and
   Ulysses' all-to-alls are identity, so the delta IS the shard_map +
   schedule machinery cost — the fixed overhead SP adds before any
   communication happens.

2. **8-device CPU mesh scaling** (``--cpu``): fwd+bwd wall time at a fixed
   GLOBAL sequence length while sp grows 1 -> 8.  CPU milliseconds are not
   TPU milliseconds, but the *shape* of the curve exposes schedule
   pathologies (a schedule that serializes or copies superlinearly shows
   up immediately; per-step collective counts are identical on TPU).

Artifact: ``python benchmarks/sp_bench.py --tpu --cpu --out
benchmarks/sp_sched.json``.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def timeit_grad(fn, *args, reps=40):
    """fwd+bwd time per call via moe_micro.timeit — the two-point scan
    extrapolation that removes the relay's fixed per-call cost exactly.
    (This file's earlier single-scan harness carried that cost as a
    ~85ms/reps phantom floor — ~2 ms/iter at reps=40 — which inflated the
    round-3 sp_sched.json numbers; docs/PERF.md measurement caveats.)"""
    import os
    import sys

    import jax
    import jax.numpy as jnp

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from moe_micro import timeit

    def gradcall(x, *rest):
        return jax.grad(
            lambda x: jnp.sum(fn(x, *rest).astype(jnp.float32)))(x)

    return timeit(gradcall, *args, reps=reps)


def bench_tpu_machinery(B, T, H, D, reps):
    import jax
    import jax.numpy as jnp

    from kubeflow_controller_tpu.ops.attention import flash_attention
    from kubeflow_controller_tpu.parallel import MeshSpec, build_mesh
    from kubeflow_controller_tpu.parallel.ring import (
        attention_reference,
        ring_attention,
    )
    from kubeflow_controller_tpu.parallel.ulysses import ulysses_attention

    key = jax.random.PRNGKey(0)
    shape = (B, T, H, D)
    q = jax.random.normal(key, shape, jnp.bfloat16)
    k = jax.random.normal(key, shape, jnp.bfloat16)
    v = jax.random.normal(key, shape, jnp.bfloat16)
    mesh = build_mesh(MeshSpec(fsdp=-1))  # all size-1 axes on one chip
    cases = {
        "plain": lambda q, k, v: attention_reference(q, k, v, causal=True),
        "flash": lambda q, k, v: flash_attention(q, k, v, causal=True),
        "ring_sp1": lambda q, k, v: ring_attention(q, k, v, mesh, causal=True),
        "ulysses_sp1": lambda q, k, v: ulysses_attention(
            q, k, v, mesh, causal=True),
    }
    rows = {}
    with jax.set_mesh(mesh):
        for name, fn in cases.items():
            # Long-T rows: plain attention materializes [B,H,T,T] f32 and
            # OOMs at T=8192 on one chip — record the failure as data (the
            # sp schedules with the flash inner are the point).
            try:
                rows[name] = round(timeit_grad(fn, q, k, v, reps=reps), 2)
            except Exception as e:
                rows[name] = f"error: {str(e)[:120]}"
            print(json.dumps({name: rows[name]}), flush=True)
    return {"config": {"B": B, "T": T, "H": H, "D": D,
                       "what": "fwd+bwd ms, 1 real TPU chip, sp=1 mesh"},
            "ms": rows}


def bench_cpu_scaling(B, T, H, D, reps):
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding

    from kubeflow_controller_tpu.parallel import MeshSpec, build_mesh, logical_to_pspec
    from kubeflow_controller_tpu.parallel.ring import ring_attention
    from kubeflow_controller_tpu.parallel.ulysses import ulysses_attention

    key = jax.random.PRNGKey(0)
    shape = (B, T, H, D)
    out = []
    for sp in (1, 2, 4, 8):
        # Spare devices park on ep (no attention array uses it): batch
        # stays unsharded so small B never constrains the sp sweep.
        mesh = build_mesh(MeshSpec(sp=sp, ep=-1, fsdp=1))
        spec = logical_to_pspec(("batch", "seq", "heads", "head_dim"))
        sharding = NamedSharding(mesh, spec)
        q = jax.device_put(jax.random.normal(key, shape, jnp.float32), sharding)
        k = jax.device_put(jax.random.normal(key, shape, jnp.float32), sharding)
        v = jax.device_put(jax.random.normal(key, shape, jnp.float32), sharding)
        row = {"sp": sp}
        with jax.set_mesh(mesh):
            row["ring_ms"] = round(timeit_grad(
                lambda q, k, v: ring_attention(q, k, v, mesh, causal=True),
                q, k, v, reps=reps), 2)
            if H % (sp or 1) == 0:
                row["ulysses_ms"] = round(timeit_grad(
                    lambda q, k, v: ulysses_attention(q, k, v, mesh, causal=True),
                    q, k, v, reps=reps), 2)
        out.append(row)
        print(json.dumps(row), flush=True)
    return {"config": {"B": B, "T": T, "H": H, "D": D,
                       "what": "fwd+bwd ms, 8 virtual CPU devices, global T "
                               "fixed while sp grows (relative shape only)"},
            "rows": out}


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--tpu", action="store_true")
    p.add_argument("--cpu", action="store_true")
    p.add_argument("--cpu-inner", action="store_true",
                   help="(internal) run the CPU scaling in THIS process — "
                        "requires JAX_PLATFORMS=cpu and 8 virtual devices")
    p.add_argument("--batch", type=int, default=2)
    p.add_argument("--seq", type=int, default=2048,
                   help="global sequence length (the plain-attention "
                        "baseline materializes [B,H,T,T] f32 scores, so "
                        "keep B*T^2 within one chip's HBM)")
    p.add_argument("--heads", type=int, default=16)
    p.add_argument("--head-dim", type=int, default=64)
    p.add_argument("--reps", type=int, default=40)
    p.add_argument("--out", default="")
    args = p.parse_args()

    if args.cpu_inner:
        import jax

        jax.config.update("jax_platforms", "cpu")
        out = bench_cpu_scaling(args.batch, args.seq, args.heads,
                                args.head_dim, args.reps)
        print("CPU_SCALING " + json.dumps(out), flush=True)
        return 0

    artifact = {"bench": "sp_schedule_cost"}

    def save():
        if args.out:
            from _common import save_artifact

            save_artifact(args.out, artifact)

    if args.tpu:
        # T=2048 (the short control) and T=8192 (the length PERF.md names
        # as the sp lever — plain attention OOMs there; the schedules run
        # their flash inner).  Incremental saves: a killed sweep keeps rows.
        artifact["tpu_machinery_sp1"] = {}
        for seq in dict.fromkeys((args.seq, 8192)):
            key = f"T{seq}"
            artifact["tpu_machinery_sp1"][key] = bench_tpu_machinery(
                args.batch, seq, args.heads, args.head_dim, args.reps)
            print(json.dumps(artifact["tpu_machinery_sp1"][key]), flush=True)
            save()
    if args.cpu:
        # Own process: a jax client that already initialized the TPU
        # backend cannot host the 8-virtual-device CPU mesh.
        import os
        import subprocess

        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                            + " --xla_force_host_platform_device_count=8").strip()
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--cpu-inner",
             "--batch", str(args.batch), "--seq", str(args.seq),
             "--heads", str(args.heads), "--head-dim", str(args.head_dim),
             "--reps", str(args.reps)],
            capture_output=True, text=True, env=env,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        for line in out.stdout.splitlines():
            if line.startswith("CPU_SCALING "):
                artifact["cpu_scaling"] = json.loads(line[len("CPU_SCALING "):])
                break
        else:
            artifact["cpu_scaling"] = {
                "error": (out.stderr or "no output")[-400:].strip()}
        print(json.dumps(artifact["cpu_scaling"]), flush=True)
    if args.out:
        save()
        print(json.dumps({"artifact": args.out}))
    return 0


if __name__ == "__main__":
    sys.path.insert(0, __file__.rsplit("/", 2)[0])
    sys.exit(main())
