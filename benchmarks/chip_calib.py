"""Session chip calibration: what this tunneled chip actually sustains.

Round-5 finding: every earlier artifact computed MFU against the nominal
v5e bf16 peak (197 TFLOP/s) — but direct wall-clock (1000-iteration scans,
relay cost amortized to <1%) shows the chip sustaining ~257-271 TFLOP/s on
a bf16 SwiGLU-FFN matmul chain, which is physically impossible on a v5e.
The hardware behind the relay is therefore NOT a v5e (signature does not
cleanly match v4/v5p/v6e either; HBM triad measures ~543 GB/s).  MFU
against a nominal peak is meaningless here; this artifact records the
MEASURED session ceilings, and llama_tpu.py defaults its peak to the
measured FFN-chain ceiling so "mfu_pct" means "fraction of what this chip
demonstrably sustains on dense matmul chains" — a conservative (upper
bound) denominator.

All timings are direct wall-clock over long scans (NOT two-point
extrapolation): the quantity of interest is a sustained-rate lower bound,
and at 300-1000 reps the relay's fixed per-call cost is <1% of total.

    python benchmarks/chip_calib.py --out benchmarks/chip_calib.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def _wall(fn, x, reps):
    import jax
    import jax.numpy as jnp

    @jax.jit
    def scanned(x):
        def body(c, _):
            s = jnp.sum(fn(c).astype(jnp.float32))
            return c + (s * 1e-30).astype(c.dtype), None

        out, _ = jax.lax.scan(body, x, None, length=reps)
        return jnp.sum(out.astype(jnp.float32))

    float(scanned(x))  # compile + complete
    best = float("inf")
    for _ in range(2):
        t0 = time.time()
        float(scanned(x))
        best = min(best, time.time() - t0)
    return best / reps


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--out", default="benchmarks/chip_calib.json")
    args = p.parse_args()

    import jax
    import jax.numpy as jnp

    key = jax.random.PRNGKey(0)
    doc = {"bench": "chip_calib",
           "method": ("direct wall-clock over 300-1000-rep scans; relay "
                      "fixed cost amortized <1%; best-of-2"),
           "rows": {}}

    # bf16 FFN chain (the MoE bench's iso-active dense shape): the highest
    # sustained bf16 rate observed on this chip — the session ceiling.
    D, F2 = 1024, 5632
    x = jax.random.normal(key, (8192, D), jnp.bfloat16)
    wg = jax.random.normal(key, (D, F2), jnp.bfloat16)
    wu = jax.random.normal(key, (D, F2), jnp.bfloat16)
    wd = jax.random.normal(key, (F2, D), jnp.bfloat16)
    dt = _wall(lambda c: (jax.nn.silu(c @ wg) * (c @ wu)) @ wd, x, 600)
    gf = 2 * 8192 * D * F2 * 3 / 1e9
    doc["rows"]["ffn_chain_bf16"] = {
        "shape": "[8192,1024] x3 matmuls inter 5632",
        "ms": round(dt * 1e3, 4), "tflops": round(gf / dt / 1e3, 1)}

    # Square bf16 matmul.
    a = jax.random.normal(key, (8192, 8192), jnp.bfloat16)
    b = jax.random.normal(key, (8192, 8192), jnp.bfloat16)
    dt = _wall(lambda c: c @ b, a, 200)
    doc["rows"]["mm8k_bf16"] = {
        "shape": "[8192,8192]@[8192,8192]",
        "ms": round(dt * 1e3, 4),
        "tflops": round(2 * 8192 ** 3 / dt / 1e12, 1)}

    # HBM triad (read 2, write 1).
    t1 = jax.random.normal(key, (64, 1024, 1024), jnp.float32)
    t2 = jax.random.normal(key, (64, 1024, 1024), jnp.float32)

    # t2 must be an ARGUMENT: a closed-over 256MB constant gets embedded
    # in the remote-compile payload and the relay rejects it (HTTP 413).
    @jax.jit
    def triad(a, t2):
        def body(c, _):
            return c * 1.0001 + t2, None

        out, _ = jax.lax.scan(body, a, None, length=300)
        return jnp.sum(out)

    float(triad(t1, t2))
    dt = float("inf")
    for _ in range(2):  # best-of-2: relay hiccups are one-sided
        t0 = time.time()
        float(triad(t1, t2))
        dt = min(dt, (time.time() - t0) / 300)
    doc["rows"]["hbm_triad_f32"] = {
        "gb_per_iter": round(3 * t1.size * 4 / 1e9, 3),
        "ms": round(dt * 1e3, 4),
        "gb_s": round(3 * t1.size * 4 / 1e9 / dt)}

    doc["nominal_peaks_for_reference"] = {
        "v5e": {"bf16_tflops": 197, "hbm_gb_s": 819},
        "v4": {"bf16_tflops": 275, "hbm_gb_s": 1228},
        "v5p": {"bf16_tflops": 459, "hbm_gb_s": 2765},
        "v6e": {"bf16_tflops": 918, "hbm_gb_s": 1638},
    }
    doc["conclusion"] = (
        "sustained bf16 >= ffn_chain rate rules out v5e (197); no nominal "
        "chip matches both compute and bandwidth signatures through the "
        "relay.  Use ffn_chain_bf16.tflops as the session MFU denominator.")
    print(json.dumps(doc["rows"]))
    if args.out:
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from _common import save_artifact

        save_artifact(args.out, doc)
    return 0


if __name__ == "__main__":
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    sys.exit(main())
