"""Flash-attention kernel vs XLA fused attention on real TPU (fwd+bwd).

Decides where models/llama.py:_attention selects the Pallas kernel: the
crossover is recorded in docs/PERF.md and encoded as
LlamaConfig.flash_min_seq.  Same trustworthy-timing method as
llama_tpu.py: K repetitions inside one jitted lax.scan, host read as the
completion barrier.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def bench_one(impl: str, b: int, t: int, h: int, d: int, steps: int,
              causal: bool = True, bbq: int = 0, bbk: int = 0) -> dict:
    import jax
    import jax.numpy as jnp

    from kubeflow_controller_tpu.ops import flash_attention
    from kubeflow_controller_tpu.parallel.ring import attention_reference

    key = jax.random.PRNGKey(0)
    ks = jax.random.split(key, 3)
    shape = (b, t, h, d)
    q = jax.random.normal(ks[0], shape, dtype=jnp.bfloat16)
    k = jax.random.normal(ks[1], shape, dtype=jnp.bfloat16)
    v = jax.random.normal(ks[2], shape, dtype=jnp.bfloat16)

    if impl == "flash":
        fn = lambda q, k, v: flash_attention(
            q, k, v, causal=causal,
            bwd_block_q=bbq or None, bwd_block_k=bbk or None)
    else:
        fn = lambda q, k, v: attention_reference(q, k, v, causal=causal)

    def loss(q, k, v):
        return jnp.sum(fn(q, k, v).astype(jnp.float32) ** 2)

    grad = jax.grad(loss, argnums=(0, 1, 2))

    @jax.jit
    def run(q, k, v):
        def body(c, _):
            # Carry-dependent input: without it XLA hoists the whole grad
            # out of the scan and the loop times nothing.
            dq, dk, dv = grad(q + (c * 1e-30).astype(q.dtype), k, v)
            return c + jnp.sum(dq[0, 0, 0, :4].astype(jnp.float32)), None

        out, _ = jax.lax.scan(body, jnp.float32(0), None, length=steps)
        return out

    float(run(q, k, v))  # compile
    dt = float("inf")    # min of 3: relay latency noise is large
    for _ in range(3):
        t0 = time.time()
        float(run(q, k, v))  # host read == barrier
        dt = min(dt, (time.time() - t0) / steps)
    # fwd+bwd attention FLOPs: fwd 4*B*H*T^2*D (QK^T + PV), bwd ~2.5x fwd.
    causal_factor = 0.5 if causal else 1.0
    flops = 3.5 * 4 * b * h * t * t * d * causal_factor
    row = {
        "impl": impl, "b": b, "t": t, "h": h, "d": d,
        "ms": round(dt * 1e3, 2),
        "tflops": round(flops / dt / 1e12, 1),
    }
    if bbq or bbk:
        row["bwd_blocks"] = [bbq or 1024, bbk or 1024]
    return row


def main() -> int:
    import os

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--heads", type=int, default=16)
    p.add_argument("--head-dim", type=int, default=128)
    p.add_argument("--steps", type=int, default=8)
    p.add_argument("--tokens", type=int, default=16384,
                   help="B*T held constant across the T sweep")
    p.add_argument("--seqs", type=int, nargs="+",
                   default=[1024, 2048, 4096, 8192])
    p.add_argument("--impl", choices=["xla", "flash"], default="",
                   help="run ONE point in-process (the sweep spawns these)")
    p.add_argument("--out", default="",
                   help="write the sweep's JSON artifact here (e.g. "
                        "benchmarks/attn_tpu_v5e.json)")
    p.add_argument("--bwd-block-q", type=int, default=0)
    p.add_argument("--bwd-block-k", type=int, default=0)
    p.add_argument("--bwd-sweep", action="store_true",
                   help="sweep BACKWARD block shapes at the longest T "
                        "(round-5 VERDICT item 8: the flash bwd dominates "
                        "long-T step time) and record the winner")
    args = p.parse_args()
    if args.impl:
        # Single point, in-process (the subprocess worker of the sweep).
        t = args.seqs[0]
        r = bench_one(args.impl, max(1, args.tokens // t), t,
                      args.heads, args.head_dim, args.steps,
                      bbq=args.bwd_block_q, bbk=args.bwd_block_k)
        print(json.dumps(r))
        return 0
    if args.bwd_sweep:
        from benchmarks._common import run_bench_subprocess, save_artifact

        t = max(args.seqs)
        rows = []
        for bbq, bbk in ((1024, 1024), (512, 1024), (1024, 512),
                         (512, 512), (256, 1024)):
            r = run_bench_subprocess(os.path.abspath(__file__), [
                "--impl", "flash", "--seqs", t, "--tokens", args.tokens,
                "--heads", args.heads, "--head-dim", args.head_dim,
                "--steps", args.steps,
                "--bwd-block-q", bbq, "--bwd-block-k", bbk])
            r.setdefault("bwd_blocks", [bbq, bbk])
            rows.append(r)
            print(json.dumps(r), flush=True)
            if args.out:
                try:
                    doc = json.load(open(args.out))
                except (FileNotFoundError, json.JSONDecodeError):
                    doc = {"bench": "flash_vs_xla_attention_fwd_bwd"}
                doc["bwd_block_sweep_t%d" % t] = rows
                save_artifact(args.out, doc)
        return 0
    # Sweep: one subprocess per point — a failing config (e.g. XLA attention
    # at T=8192, which cannot compile on one chip: that asymmetry IS the
    # result) must not poison the TPU client for later points.
    from benchmarks._common import run_bench_subprocess

    results = []
    for t in args.seqs:
        b = max(1, args.tokens // t)
        for impl in ("xla", "flash"):
            r = run_bench_subprocess(os.path.abspath(__file__), [
                "--impl", impl, "--seqs", t, "--tokens", args.tokens,
                "--heads", args.heads, "--head-dim", args.head_dim,
                "--steps", args.steps,
            ])
            # Same record shape for errors as for successes.
            r.setdefault("impl", impl)
            r.setdefault("t", t)
            r.setdefault("b", b)
            results.append(r)
            print(json.dumps(r), flush=True)
    if args.out:
        artifact = {
            "bench": "flash_vs_xla_attention_fwd_bwd",
            "method": ("min-of-3, K steps inside one jitted scan, host read "
                       "as barrier; B*T held constant; one subprocess per "
                       "point so a failing config cannot poison later ones"),
            "config": {"tokens": args.tokens, "heads": args.heads,
                       "head_dim": args.head_dim, "causal": True},
            "results": results,
        }
        with open(args.out, "w") as f:
            json.dump(artifact, f, indent=1)
        print(json.dumps({"artifact": args.out}))
    return 0


if __name__ == "__main__":
    sys.path.insert(0, __file__.rsplit("/", 2)[0])
    sys.exit(main())
