"""MoE FFN dispatch artifact bench: grouped (dropless) vs einsum across
capacity factors vs iso-active dense, fwd and grad, on the real chip.

Writes rows INCREMENTALLY (a killed sweep keeps finished rows) and repeats
each row so the artifact carries run arrays, not single shots.

    python benchmarks/moe_ffn_bench.py --out benchmarks/moe_ffn_v5e.json
"""

import argparse
import json
import os

import jax
import jax.numpy as jnp


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--bt", type=int, default=8192, help="B*T tokens")
    p.add_argument("--dim", type=int, default=1024)
    p.add_argument("--inter", type=int, default=2816)
    p.add_argument("--experts", type=int, default=8)
    p.add_argument("--topk", type=int, default=2)
    p.add_argument("--repeats", type=int, default=2)
    p.add_argument("--out", default="")
    a = p.parse_args()

    from moe_micro import timeit

    from kubeflow_controller_tpu.models.moe import moe_ffn_stats

    D, F, E = a.dim, a.inter, a.experts
    key = jax.random.PRNGKey(0)
    B, T = 8, a.bt // 8
    x = jax.random.normal(key, (B, T, D), jnp.bfloat16)
    rw = jax.random.normal(key, (D, E), jnp.bfloat16) * 0.1
    wg = jax.random.normal(key, (E, D, F), jnp.bfloat16)
    wu = jax.random.normal(key, (E, D, F), jnp.bfloat16)
    wd = jax.random.normal(key, (E, F, D), jnp.bfloat16)
    wg2, wu2, wd2 = (jax.random.normal(key, (D, 2 * F), jnp.bfloat16),
                     jax.random.normal(key, (D, 2 * F), jnp.bfloat16),
                     jax.random.normal(key, (2 * F, D), jnp.bfloat16))

    def moe_f(x, wg, wu, wd, mode, cf):
        return moe_ffn_stats(x, rw, wg, wu, wd, top_k=a.topk,
                             capacity_factor=cf, dispatch=mode)[0]

    def dense_f(x, wg2, wu2, wd2):
        return jnp.einsum(
            "btf,fd->btd",
            jax.nn.silu(jnp.einsum("btd,df->btf", x, wg2))
            * jnp.einsum("btd,df->btf", x, wu2), wd2)

    doc = {
        "config": {"bt": a.bt, "dim": D, "inter": F, "experts": E,
                   "topk": a.topk, "dtype": "bfloat16",
                   "chip": "v5e-1 (tunneled)"},
        "method": ("per-iteration time via two-point scan extrapolation "
                   "(T(4N)-T(N))/(3N), best-of-2 per point — removes the "
                   "relay's fixed per-call cost exactly (docs/PERF.md "
                   "measurement caveats); repeats[] are full re-estimates"),
        "note": ("grouped is DROPLESS (capacity-free): its cost is flat in "
                 "capacity_factor while the einsum path's dispatch AND "
                 "expert compute scale with E*C = T*k*cf — the crossover "
                 "is the honest selection rule between the two.  grad is "
                 "w.r.t. x AND every FFN weight with a data-dependent "
                 "cotangent (loss = sum(y^2)): round 4's sum(y) + x-only "
                 "grad let XLA collapse the ones-cotangent matmuls and DCE "
                 "the weight grads on the einsum/dense paths while the "
                 "grouped custom-VJP (opaque to XLA) paid its full tgmm "
                 "weight-grad cost — biased AGAINST grouped both ways."),
        "rows": [],
    }

    def write():
        if a.out:
            from _common import save_artifact

            save_artifact(a.out, doc)

    # One source of truth per case: (name, raw_fn(x, *weights), weights).
    cases = [
        ("grouped dropless",
         lambda x, *w: moe_f(x, *w, "grouped", 1.0), (wg, wu, wd)),
        ("einsum cf=1.0",
         lambda x, *w: moe_f(x, *w, "einsum", 1.0), (wg, wu, wd)),
        ("einsum cf=1.25",
         lambda x, *w: moe_f(x, *w, "einsum", 1.25), (wg, wu, wd)),
        ("einsum cf=2.0",
         lambda x, *w: moe_f(x, *w, "einsum", 2.0), (wg, wu, wd)),
        ("dense iso-active control", dense_f, (wg2, wu2, wd2)),
    ]
    for name, raw, weights in cases:
        def fn(x, raw=raw, weights=weights):
            return raw(x, *weights)

        def grad_fn(x, raw=raw, weights=weights):
            # Training-shaped backward: data-dependent cotangent (sum y^2)
            # and grads for x AND the weights, so neither algebraic
            # cotangent collapse nor weight-grad DCE skews the A/B.
            def loss(x, *w):
                return jnp.sum(raw(x, *w).astype(jnp.float32) ** 2)

            return jax.grad(loss, argnums=tuple(range(1 + len(weights))))(
                x, *weights)

        try:
            fwd_runs, grad_runs = [], []
            for _ in range(a.repeats):
                fwd_runs.append(round(timeit(fn, x, reps=120), 3))
                grad_runs.append(round(timeit(grad_fn, x, reps=80), 3))
            row = {"name": name, "fwd_ms": min(fwd_runs),
                   "grad_ms": min(grad_runs),
                   "step_ms": round(min(fwd_runs) + min(grad_runs), 3),
                   "fwd_runs_ms": fwd_runs, "grad_runs_ms": grad_runs}
        except Exception as e:  # record failures as rows, don't lose the sweep
            row = {"name": name, "error": str(e)[:200]}
        doc["rows"].append(row)
        print(json.dumps(row), flush=True)
        write()


if __name__ == "__main__":
    import sys

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    sys.exit(main())
