"""KV-cache decode throughput on real TPU — the inference counterpart of
llama_tpu.py.

The whole generate loop (prefill + per-token decode) is ONE jitted scan
(models/generate.py), so the relay-safe timing recipe applies: time the
second call of the jitted function and read the output back as the
completion barrier (docs/PERF.md "Measurement caveats").

Decode is memory-bandwidth-bound (every step streams all params + the KV
prefix per token), so the interesting numbers are ms/token at B=1
(latency) and tokens/s at larger B (throughput).

    python benchmarks/decode_tpu.py --sweep --out benchmarks/decode_tpu_v5e.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def run(batch: int, prompt_len: int, new_tokens: int, dim: int, layers: int,
        heads: int, intermediate: int, kv_block: int = 0,
        kv_quant: bool = False) -> dict:
    import jax

    from kubeflow_controller_tpu.models import LlamaConfig, llama_init
    from kubeflow_controller_tpu.models.generate import generate

    cfg = LlamaConfig(
        vocab_size=32000, dim=dim, n_layers=layers, n_heads=heads,
        n_kv_heads=heads, intermediate=intermediate,
        max_seq_len=prompt_len + new_tokens,
        dtype="bfloat16", param_dtype="bfloat16", remat=False,
    )
    params = jax.jit(lambda k: llama_init(k, cfg))(jax.random.PRNGKey(0))
    n_params = sum(x.size for x in jax.tree.leaves(params))
    prompt = jax.random.randint(
        jax.random.PRNGKey(1), (batch, prompt_len), 0, cfg.vocab_size)

    # kv_block=0: default blocked reads (generate.DECODE_KV_BLOCK).  To
    # force the dense full-S read for an A/B, pass kv_block = S (a
    # single-block cache takes the dense path).
    kb = kv_block or None
    gen = jax.jit(lambda p, t: generate(p, t, cfg, max_new_tokens=new_tokens,
                                        kv_block=kb, kv_quant=kv_quant))
    # Prefill-only control (same code path, one sampled token): its best
    # wall time splits the end-to-end number into prefill vs decode-scan,
    # so the per-token rate no longer silently carries the B-scaled
    # prefill cost (round-4 VERDICT item 4).
    pre = jax.jit(lambda p, t: generate(p, t, cfg, max_new_tokens=1,
                                        kv_block=kb, kv_quant=kv_quant))
    # block_until_ready is NOT a trustworthy barrier through the tunneled
    # backend (async futures complete "instantly"); a host VALUE read is
    # (docs/PERF.md "Measurement caveats").
    out = gen(params, prompt)
    int(out.sum())  # compile + complete
    int(pre(params, prompt).sum())
    best = float("inf")
    pre_best = float("inf")
    for _ in range(3):
        t0 = time.time()
        out = gen(params, prompt)
        int(out.sum())  # host read = completion barrier
        best = min(best, time.time() - t0)
        t0 = time.time()
        int(pre(params, prompt).sum())
        pre_best = min(pre_best, time.time() - t0)
    total_new = batch * new_tokens
    decode_s = max(best - pre_best, 1e-9)
    decode_ms_tok = decode_s / max(new_tokens - 1, 1) * 1e3
    # Roofline accounting: every decode step streams all params once plus
    # the written KV prefix per sequence (avg over the decode window).
    # eff_gb_s = that traffic / measured per-token time — compare against
    # the chip's HBM bandwidth to see how close to the memory roofline the
    # decode scan runs (weights bf16 = 2 bytes; int8 cache = 1 byte + f32
    # scale per row, i.e. /head positions).
    weights_gb = n_params * 2 / 1e9
    avg_len = prompt_len + new_tokens / 2
    kv_bytes_row = (1 + 4 / (dim // heads)) if kv_quant else 2
    kv_gb = (2 * layers * batch * avg_len * dim * kv_bytes_row) / 1e9
    return {
        "params_m": round(n_params / 1e6, 1),
        "batch": batch,
        "prompt_len": prompt_len,
        "new_tokens": new_tokens,
        "total_s": round(best, 3),
        "prefill_s": round(pre_best, 3),
        "prefill_tokens_per_s": round(batch * prompt_len / pre_best),
        "decode_ms_per_token_per_seq": round(decode_ms_tok, 2),
        "ms_per_token_per_seq": round(best / new_tokens * 1e3, 2),
        "gen_tokens_per_s": round(total_new / best),
        "decode_tokens_per_s": round(batch * (new_tokens - 1) / decode_s),
        "weights_gb": round(weights_gb, 3),
        "kv_read_gb_avg": round(kv_gb, 3),
        "eff_gb_s": round((weights_gb + kv_gb) / (decode_ms_tok / 1e3)),
        "kv_block": kv_block,
        "kv_quant": kv_quant,
        "check_shape": list(out.shape),
    }


def run_subprocess(args_list) -> dict:
    from benchmarks._common import run_bench_subprocess

    return run_bench_subprocess(os.path.abspath(__file__), args_list)


def _write_artifact(args, results) -> list:
    """Incremental write after every row: points cost minutes of relay
    compile each, so an interrupted sweep must keep what it measured.
    Preserves non-sweep keys other tools merge into the artifact (the
    int8_kv_quality rows from decode_quality.py)."""
    ok = [r for r in results if "gen_tokens_per_s" in r]
    try:
        prev = json.load(open(args.out))
    except (FileNotFoundError, json.JSONDecodeError):
        prev = {}
    extra = {k: v for k, v in prev.items()
             if k not in ("bench", "model", "note", "results",
                          "best_throughput")}
    artifact = {
        **extra,
        "bench": "llama_decode_single_chip",
        "model": (f"Llama (dim {args.dim}, L{args.layers}, H{args.heads}, "
                  f"inter {args.intermediate}), bf16, KV-cache greedy decode"),
        "note": ("Decode threads the KV caches through the layer scan as "
                 "CARRY (the xs/ys form copied both [L,B,S,kvH,D] caches "
                 "every token step).  kv_block=0 = default reads: blocked "
                 "length-masked when the cache spans > 1 block (the S=2048 "
                 "rows), the dense single-block read at S=256.  "
                 "kv_block=2048 forces the dense full-S read at S=2048 "
                 "(the A/B); kv_quant = int8 rows with per-row f32 scales.  "
                 "prefill_s is a same-config max_new_tokens=1 control; "
                 "decode_ms_per_token_per_seq excludes it.  Per-token cost "
                 "GROWS with batch because decode streams weights once per "
                 "step but the KV prefix once PER SEQUENCE: traffic/token = "
                 "weights_gb + kv_read_gb_avg (B-proportional), and "
                 "eff_gb_s shows how close that streaming runs to the "
                 "chip's HBM roofline — the round-4 'unexplained' B=32 "
                 "slowdown is this accounting."),
        "results": results,
        "best_throughput": max(ok, key=lambda r: r["gen_tokens_per_s"]) if ok else None,
    }
    from benchmarks._common import save_artifact

    save_artifact(args.out, artifact)  # atomic: never a half-written file
    return ok


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--prompt-len", type=int, default=128)
    p.add_argument("--new-tokens", type=int, default=128)
    p.add_argument("--dim", type=int, default=2048)
    p.add_argument("--layers", type=int, default=16)
    p.add_argument("--heads", type=int, default=16)
    p.add_argument("--intermediate", type=int, default=5632)
    p.add_argument("--kv-block", type=int, default=0,
                   help="cache-read block (0 = default blocked reads; pass "
                        "prompt+new to force the dense full-S read)")
    p.add_argument("--kv-quant", action="store_true",
                   help="int8 KV cache (per-row scales)")
    p.add_argument("--sweep", action="store_true")
    p.add_argument("--out", default="benchmarks/decode_tpu_v5e.json")
    args = p.parse_args()
    shape = [
        "--dim", args.dim, "--layers", args.layers, "--heads", args.heads,
        "--intermediate", args.intermediate,
    ]
    if args.sweep:
        grid = [
            # Short-context points (S=256 = ONE cache block, so these take
            # the dense single-block read; comparable with round 2).
            dict(batch=1), dict(batch=8), dict(batch=32),
            # Long-context A/B at S=2048 (8 blocks): default blocked
            # length-masked reads vs the dense full-S masked read
            # (kv_block = S forces dense), plus int8 KV on top of blocked.
            dict(batch=8, prompt=1024, new=1024),
            dict(batch=8, prompt=1024, new=1024, quant=True),
            dict(batch=8, prompt=1024, new=1024, kv_block=2048),
            # Very long context: S=8192 (32 blocks) at B=1.
            dict(batch=1, prompt=4096, new=4096),
        ]
        results = []
        for g in grid:
            r = run_subprocess([
                "--batch", g["batch"],
                "--prompt-len", g.get("prompt", args.prompt_len),
                "--new-tokens", g.get("new", args.new_tokens),
                "--kv-block", g.get("kv_block", 0),
                *(["--kv-quant"] if g.get("quant") else []), *shape])
            r.setdefault("batch", g["batch"])
            r.setdefault("prompt_len", g.get("prompt", args.prompt_len))
            r.setdefault("new_tokens", g.get("new", args.new_tokens))
            r.setdefault("kv_block", g.get("kv_block", 0))
            r.setdefault("kv_quant", bool(g.get("quant")))
            results.append(r)
            print(json.dumps(r), flush=True)
            ok = _write_artifact(args, results)
        print(json.dumps({"artifact": args.out,
                          "best": max(ok, key=lambda r: r["gen_tokens_per_s"])
                          if ok else None}))
        return 0 if ok else 1
    out = run(args.batch, args.prompt_len, args.new_tokens, args.dim,
              args.layers, args.heads, args.intermediate,
              kv_block=args.kv_block, kv_quant=args.kv_quant)
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.path.insert(0, __file__.rsplit("/", 2)[0])
    sys.exit(main())
