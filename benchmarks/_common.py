"""Shared benchmark plumbing."""

from __future__ import annotations

import json
import os
import subprocess
import sys


def run_bench_subprocess(script_path: str, args_list) -> dict:
    """One measurement per process: an OOMing config must not poison the
    TPU client for subsequent grid points.  Scrapes the last JSON line the
    child printed; on failure returns {"error": stderr tail}.

    Children share a persistent XLA compilation cache: through the relayed
    backend a single compile costs minutes, so re-running a sweep (or
    resuming one that died) must not pay it twice."""
    env = dict(os.environ)
    env.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jaxcache-bench")
    env.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")
    out = subprocess.run(
        [sys.executable, script_path, *map(str, args_list)],
        capture_output=True, text=True, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(script_path))),
    )
    for line in reversed(out.stdout.strip().splitlines()):
        if line.startswith("{"):
            return json.loads(line)
    return {"error": (out.stderr or "no output")[-400:].strip()}


def save_artifact(path: str, obj) -> None:
    """Atomic incremental artifact write (tmp + rename): sweeps call this
    after EVERY row so a killed run keeps its finished rows, and a reader
    never sees a half-written JSON."""
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(obj, fh, indent=1)
    os.replace(tmp, path)
