"""int8-KV decode quality certification on the 953M bench model.

The perf rows in decode_tpu_v5e.json measure kv_quant=true speed; this
measures what quantization does to the MODEL'S OUTPUTS at the same scale
(the round-3 gap: quality was certified only on the tiny test model).

Method: teacher-forced A/B in ONE scan — both caches (bf16 and int8)
decode the same gold continuation step by step, and each step compares
full logits: max |delta| and greedy-argmax agreement.  Teacher forcing
keeps the two paths on the same prefix for all N steps, so agreement is
per-position (free-running greedy would compound one early divergence
into an uninformative suffix mismatch).

    python benchmarks/decode_quality.py --out benchmarks/decode_tpu_v5e.json
"""

import argparse
import json
import os
import sys


def run(batch: int, prompt_len: int, steps: int, dim: int, layers: int,
        heads: int, intermediate: int, ckpt: str = "") -> dict:
    import jax
    import jax.numpy as jnp

    from kubeflow_controller_tpu.models import LlamaConfig, llama_init
    from kubeflow_controller_tpu.models.generate import (
        forward_with_cache,
        init_cache,
    )

    cfg = LlamaConfig(
        vocab_size=32000, dim=dim, n_layers=layers, n_heads=heads,
        n_kv_heads=heads, intermediate=intermediate,
        max_seq_len=prompt_len + steps,
        dtype="bfloat16", param_dtype="bfloat16", remat=False,
    )
    S = prompt_len + steps
    params = jax.jit(lambda k: llama_init(k, cfg))(jax.random.PRNGKey(0))
    if ckpt:
        # Trained weights (train_for_quality.py) + IN-DISTRIBUTION prompts
        # and gold continuations from the same frozen bigram chain the
        # model was trained on: the A/B then measures flip rates at the
        # sharp margins a trained LM actually has, not the near-zero
        # margins of random init.
        import numpy as np

        from train_for_quality import unflatten_like
        from kubeflow_controller_tpu.workloads import data as d

        loaded = dict(np.load(ckpt))
        params = unflatten_like(params, loaded)
        seqs = d.synthetic_tokens(77, batch, prompt_len + steps,
                                  cfg.vocab_size)
        prompt = seqs[:, :prompt_len]
        gold = seqs[:, prompt_len:].T                         # [steps, B]
    else:
        prompt = jax.random.randint(
            jax.random.PRNGKey(1), (batch, prompt_len), 0, cfg.vocab_size)
        gold = jax.random.randint(
            jax.random.PRNGKey(2), (steps, batch), 0, cfg.vocab_size)

    @jax.jit
    def ab(params, prompt, gold):
        cache_a = init_cache(cfg, batch, S, quantize=False)
        cache_b = init_cache(cfg, batch, S, quantize=True)
        la, cache_a = forward_with_cache(params, prompt, cache_a, 0, cfg)
        lb, cache_b = forward_with_cache(params, prompt, cache_b, 0, cfg)

        def step(carry, tok_pos):
            cache_a, cache_b = carry
            tok, pos = tok_pos
            la, cache_a = forward_with_cache(
                params, tok[:, None], cache_a, pos, cfg)
            lb, cache_b = forward_with_cache(
                params, tok[:, None], cache_b, pos, cfg)
            la = la[:, -1].astype(jnp.float32)
            lb = lb[:, -1].astype(jnp.float32)
            delta = jnp.max(jnp.abs(la - lb))
            agree = jnp.sum(jnp.argmax(la, -1) == jnp.argmax(lb, -1))
            return (cache_a, cache_b), (delta, agree)

        _, (deltas, agrees) = jax.lax.scan(
            step, (cache_a, cache_b),
            (gold, prompt_len + jnp.arange(steps)))
        # Prefill logits compared too (the S=prompt_len state).
        pre_delta = jnp.max(jnp.abs(
            la[:, -1].astype(jnp.float32) - lb[:, -1].astype(jnp.float32)))
        return (jnp.maximum(jnp.max(deltas), pre_delta),
                jnp.sum(agrees), jnp.mean(deltas))

    max_delta, agree, mean_delta = ab(params, prompt, gold)
    n = steps * batch
    note = {}
    if ckpt:
        note["position_note"] = (
            "keep prompt_len+steps <= the checkpoint's training "
            "max_seq_len (train_for_quality.py default 1024): positions "
            "beyond it would measure RoPE extrapolation the model never "
            "saw, not trained-margin flip rates")
    return {
        "quality_check": "int8 KV vs bf16 KV, teacher-forced A/B",
        "trained": bool(ckpt),
        **note,
        "batch": batch, "prompt_len": prompt_len,
        "decode_steps": steps, "cache_len": S,
        "positions_compared": n,
        "argmax_agreement": round(float(agree) / n, 6),
        "max_logit_delta": round(float(max_delta), 5),
        "mean_max_logit_delta_per_step": round(float(mean_delta), 5),
    }


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--prompt-len", type=int, default=1024)
    p.add_argument("--steps", type=int, default=1024)
    p.add_argument("--dim", type=int, default=2048)
    p.add_argument("--layers", type=int, default=16)
    p.add_argument("--heads", type=int, default=16)
    p.add_argument("--intermediate", type=int, default=5632)
    p.add_argument("--ckpt", default="",
                   help="npz from train_for_quality.py: trained weights + "
                        "in-distribution prompts (sets trained=true)")
    p.add_argument("--out", default="")
    args = p.parse_args()

    row = run(args.batch, args.prompt_len, args.steps, args.dim,
              args.layers, args.heads, args.intermediate, ckpt=args.ckpt)
    print(json.dumps(row), flush=True)
    if args.out:
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from _common import save_artifact

        try:
            doc = json.load(open(args.out))
        except (FileNotFoundError, json.JSONDecodeError):
            doc = {"bench": "llama_decode_single_chip"}
        key = ("int8_kv_quality_trained" if args.ckpt else "int8_kv_quality")
        doc[key] = row
        save_artifact(args.out, doc)
    return 0


if __name__ == "__main__":
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    sys.exit(main())
