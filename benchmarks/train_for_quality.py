"""Train the 238M bench config on-chip just far enough to develop real
logit margins, checkpoint it, and hand the params to decode_quality.py —
closing round-4's int8-KV caveat (quality was certified only on RANDOM
weights, whose near-zero top-2 margins are the flip-prone worst case;
"trained agreement should be higher" was a hypothesis, not a measurement).

Data is the frozen bigram chain (workloads/data.py:synthetic_tokens, 90%
deterministic successor): next-token loss drops far below log(vocab) within
~1k steps, giving the sharp argmax margins a pretrained LM has.

    python benchmarks/train_for_quality.py --steps 1500 \
        --ckpt /tmp/quality_238m.npz
then
    python benchmarks/decode_quality.py --ckpt /tmp/quality_238m.npz \
        --dim 1024 --layers 8 --intermediate 5632 \
        --prompt-len 512 --steps 512 \
        --out benchmarks/decode_tpu_v5e.json

(prompt_len + steps must stay <= the training --seq, 1024 by default —
positions past it would measure RoPE extrapolation, not trained margins.)
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def flatten_params(params):
    import jax

    flat, _ = jax.tree_util.tree_flatten_with_path(params)
    return {jax.tree_util.keystr(path): v for path, v in flat}


def unflatten_like(template, flat: dict):
    import jax
    import jax.numpy as jnp

    import ml_dtypes
    import numpy as np

    def load(arr, leaf):
        if arr.dtype == np.dtype("V2"):  # legacy npz of raw bf16 bytes
            arr = arr.view(ml_dtypes.bfloat16)
        return jnp.asarray(arr).astype(leaf.dtype)

    leaves, _ = jax.tree_util.tree_flatten_with_path(template)
    vals = [load(flat[jax.tree_util.keystr(path)], leaf)
            for path, leaf in leaves]
    return jax.tree_util.tree_unflatten(
        jax.tree_util.tree_structure(template), vals)


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--steps", type=int, default=1500)
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--seq", type=int, default=1024)
    p.add_argument("--dim", type=int, default=1024)
    p.add_argument("--layers", type=int, default=8)
    p.add_argument("--heads", type=int, default=16)
    p.add_argument("--intermediate", type=int, default=5632)
    p.add_argument("--lr", type=float, default=3e-4)
    p.add_argument("--ckpt", default="/tmp/quality_238m.npz")
    a = p.parse_args()

    import jax
    import numpy as np
    import optax

    from kubeflow_controller_tpu.models import LlamaConfig, llama_init, llama_loss
    from kubeflow_controller_tpu.parallel import MeshSpec, build_mesh
    from kubeflow_controller_tpu.workloads import data as d

    cfg = LlamaConfig(
        vocab_size=32000, dim=a.dim, n_layers=a.layers, n_heads=a.heads,
        n_kv_heads=a.heads, intermediate=a.intermediate, max_seq_len=a.seq,
        dtype="bfloat16", param_dtype="bfloat16", remat=True,
        remat_policy="gateup",
    )
    mesh = build_mesh(MeshSpec(fsdp=-1))
    params = jax.jit(lambda k: llama_init(k, cfg))(jax.random.PRNGKey(0))
    n_params = sum(x.size for x in jax.tree.leaves(params))
    opt = optax.adafactor(a.lr)
    opt_state = opt.init(params)

    # One scan per chunk keeps host<->device chatter off the relay; tokens
    # are regenerated per chunk (the bigram chain is the same frozen one
    # decode_quality prompts from).
    chunk = 100

    @jax.jit
    def run_chunk(p, s, toks):
        def body(carry, t):
            p, s = carry
            loss, g = jax.value_and_grad(
                lambda p: llama_loss(p, t, cfg, mesh=mesh))(p)
            u, s = opt.update(g, s, p)
            return (optax.apply_updates(p, u), s), loss

        (p, s), losses = jax.lax.scan(body, (p, s), toks)
        return p, s, losses

    t0 = time.time()
    first_loss = last_loss = None
    with jax.set_mesh(mesh):
        for start in range(0, a.steps, chunk):
            n = min(chunk, a.steps - start)
            toks = d.synthetic_tokens(1000 + start, n * a.batch, a.seq,
                                      cfg.vocab_size)
            toks = toks.reshape(n, a.batch, a.seq)
            params, opt_state, losses = run_chunk(params, opt_state, toks)
            losses = np.asarray(losses)
            if first_loss is None:
                first_loss = float(losses[0])
            last_loss = float(losses[-1])
            print(json.dumps({"step": start + n, "loss": round(last_loss, 4),
                              "elapsed_s": round(time.time() - t0, 1)}),
                  flush=True)

    # Save as f32: npz round-trips ml_dtypes.bfloat16 poorly (jit rejects
    # the loaded arrays); unflatten_like casts back to the template dtype.
    np.savez(a.ckpt, **{k: np.asarray(v, dtype=np.float32)
                        for k, v in flatten_params(params).items()})
    print(json.dumps({
        "trained": True, "params_m": round(n_params / 1e6, 1),
        "steps": a.steps, "tokens": a.steps * a.batch * a.seq,
        "first_loss": round(first_loss, 4), "final_loss": round(last_loss, 4),
        "log_vocab": round(float(np.log(cfg.vocab_size)), 4),
        "elapsed_s": round(time.time() - t0, 1), "ckpt": a.ckpt,
    }), flush=True)
    return 0


if __name__ == "__main__":
    import os

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    sys.exit(main())
