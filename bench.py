"""Headline benchmark: dist-mnist TFJob wall-clock-to-Succeeded.

The driver's target metric (BASELINE.json): time from TFJob creation to
``status.phase == Succeeded`` for the distributed MNIST job — the same
2-PS/4-worker, 200-step, batch-100 run the reference documents at 9.54s of
pure training on a dev box (ref: docs/get_started.md:49-63), except here
the clock covers the WHOLE job: reconcile, pod+service materialization,
gang execution of real JAX training processes, status rollup.

``vs_baseline`` is the speedup over the reference's published 9.536664s
training elapsed (>1.0 = faster than the baseline number).  The JSON also
carries reconcile percentiles and workload details.

Workers train on the cpu platform: the benchmark measures the framework's
orchestration + training loop end-to-end, and the one tunneled TPU chip
cannot be shared by 4 concurrent worker processes.
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

BASELINE_S = 9.536664  # ref: docs/get_started.md:63 "Training elapsed time"


def run_dist_mnist() -> dict:
    from kubeflow_controller_tpu.api.core import Container, PodTemplateSpec
    from kubeflow_controller_tpu.api.meta import ObjectMeta
    from kubeflow_controller_tpu.api.tfjob import (
        ReplicaType,
        TFJob,
        TFJobPhase,
        TFReplicaSpec,
    )
    from kubeflow_controller_tpu.cluster import (
        Cluster,
        FakeKubelet,
        PhasePolicy,
        TPUInventory,
        TPUSlice,
    )
    from kubeflow_controller_tpu.controller import Controller

    def replica(typ: str, n: int, *args_extra) -> TFReplicaSpec:
        t = PodTemplateSpec()
        t.spec.containers.append(Container(
            name="tensorflow",
            image="dist",
            command=[sys.executable, "-m",
                     "kubeflow_controller_tpu.workloads.mnist_dist",
                     "--platform", "cpu", *args_extra],
            working_dir=REPO,
        ))
        t.spec.restart_policy = "OnFailure"
        return TFReplicaSpec(
            replicas=n, tf_replica_type=ReplicaType(typ), template=t
        )

    # The judged dist-MNIST config (BASELINE.json configs[1]):
    # 2 workers + 1 PS, 200 steps, global batch 100.
    job = TFJob(metadata=ObjectMeta(name="bench-dist-mnist", namespace="default"))
    job.spec.tf_replica_specs = [
        replica("PS", 1),
        replica("Worker", 2, "--steps", "200", "--batch-size", "100"),
    ]

    cluster = Cluster()
    inventory = TPUInventory([TPUSlice("slice-0", "v5e-8", num_hosts=2)])
    kubelet = FakeKubelet(cluster, policy=PhasePolicy(), inventory=inventory,
                          execute=True)
    ctrl = Controller(cluster, inventory=inventory, resync_period_s=1.0)
    kubelet.start()
    ctrl.run(threadiness=2)
    kubelet.wait_warm()  # cluster warm-up (image-pull analog) precedes the job
    try:
        t0 = time.time()
        cluster.tfjobs.create(job)
        deadline = t0 + 600
        phase = None
        while time.time() < deadline:
            j = cluster.tfjobs.get("default", "bench-dist-mnist")
            phase = j.status.phase
            if phase in (TFJobPhase.SUCCEEDED, TFJobPhase.FAILED):
                break
            time.sleep(0.05)
        elapsed = time.time() - t0
        snap = ctrl.metrics.snapshot()
    finally:
        ctrl.stop()
        kubelet.stop()

    if phase != TFJobPhase.SUCCEEDED:
        raise RuntimeError(f"bench job ended {phase}: {j.status.reason}")
    return {"elapsed_s": elapsed, "metrics": snap}


def main() -> int:
    result = run_dist_mnist()
    elapsed = result["elapsed_s"]
    print(json.dumps({
        "metric": "dist_mnist_tfjob_wallclock_to_succeeded",
        "value": round(elapsed, 3),
        "unit": "s",
        "vs_baseline": round(BASELINE_S / elapsed, 3),
        "details": {
            "baseline_s": BASELINE_S,
            "reconcile_p50_ms": round(result["metrics"]["reconcile_p50_s"] * 1e3, 3),
            "reconcile_p99_ms": round(result["metrics"]["reconcile_p99_s"] * 1e3, 3),
            "syncs": result["metrics"]["syncs"],
            "workload": "1xPS + 2xWorker, 200 steps, global batch 100, all-reduce DP",
        },
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
